"""Solver driver: the orchestration layer.

The reference's ``main()`` functions (``mpi/...stat.c:35-310``,
``cuda/cuda_heat.cu:166-269``) interleave allocation, distribution, the
step loop, convergence polling and collection imperatively. Here the whole
simulation — N steps, halo exchanges, convergence votes — is a single
jitted XLA program:

- fixed-step mode: ``lax.fori_loop`` over fused steps (the CUDA
  ``i < STEPS`` semantics, ``cuda/cuda_heat.cu:204``);
- converge mode: ``lax.while_loop`` whose body advances
  ``check_interval`` steps and computes the residual max-norm *on
  device*, replacing the reference's host-polled flag reduction
  (``cuda/cuda_heat.cu:219-236``) and MPI allreduce vote
  (``mpi/...stat.c:235-262``) with zero host round-trips;
- distribution: ``shard_map`` over a named ICI mesh — the grid is born
  sharded (no master scatter/gather, ``mpi/...stat.c:86-127,270-298``).

Double buffering falls out of functional purity + buffer donation: XLA
ping-pongs the two HBM buffers exactly like the reference's
``old = 1-old`` swap (``cuda/cuda_heat.cu:217``).
"""

from __future__ import annotations

import collections
import functools
import time
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from parallel_heat_tpu.config import HeatConfig
from parallel_heat_tpu.utils import profiling
from parallel_heat_tpu.models import HeatPlate2D, HeatPlate3D
from parallel_heat_tpu.ops import (
    step_2d,
    step_2d_residual,
    step_3d,
    step_3d_residual,
)
from parallel_heat_tpu.parallel.halo import (
    block_step_2d,
    block_step_2d_residual,
)
from parallel_heat_tpu.parallel.mesh import make_heat_mesh

from parallel_heat_tpu.utils.compat import shard_map as _shard_map


@dataclass
class HeatResult:
    """Outcome of one simulation run."""

    grid: jax.Array
    steps_run: int
    converged: Optional[bool]
    residual: Optional[float]
    elapsed_s: float
    # Runtime-guard verdict (``HeatConfig.guard_interval``): True/False
    # when the non-finite guard actually ran on this result's grid, None
    # when no check ran (guard disabled, or this stream chunk fell
    # between guard boundaries). Observation-only — see SEMANTICS.md.
    finite: Optional[bool] = None
    # Grid-stats sample (``HeatConfig.diag_interval``): the
    # :func:`grid_stats` dict (min/max/heat/update_l2/update_linf plus
    # ``step``/``steps_since``) when a diagnostics sample ran on this
    # result's grid, None otherwise. Observation-only, like ``finite``.
    diagnostics: Optional[dict] = None

    def to_numpy(self) -> np.ndarray:
        """Gather the (possibly sharded) final grid to host memory."""
        return np.asarray(self.grid)


def model_for(config: HeatConfig):
    if config.ndim == 3:
        return HeatPlate3D(config.nx, config.ny, config.nz,
                           config.cx, config.cy, config.cz)
    return HeatPlate2D(config.nx, config.ny, config.cx, config.cy)


def _resolve_backend(config: HeatConfig) -> str:
    if jnp.dtype(config.dtype).itemsize == 8:
        # Mosaic has no 64-bit types ("Unsupported type in mosaic
        # dialect: 'f64'", probed on v5e) — float64 always runs the
        # XLA-fused path, declining exactly like the geometry-based
        # picker declines. Without this, the default backend="auto"
        # crashed at trace time on TPU for f64 configs.
        if config.backend == "pallas":
            # Loud decline (once per process): a user benchmarking an
            # explicit 'pallas' request should not silently get jnp
            # numbers. --explain shows the same routing on demand.
            import warnings

            warnings.warn(
                "backend='pallas' with dtype='float64' runs the XLA-fused "
                "jnp path: Mosaic has no 64-bit types (this dtype-level "
                "decline mirrors the geometry declines; see --explain)",
                RuntimeWarning,
            )
        return "jnp"
    if config.backend != "auto":
        return config.backend
    plat = jax.devices()[0].platform
    return "pallas" if plat in ("tpu", "axon") else "jnp"


def _resolve_halo_depth(config: HeatConfig, backend: str) -> int:
    """Resolve ``halo_depth=None`` (auto) to a concrete exchange depth.

    Auto picks the Mosaic block temporal kernel's depth (the dtype's
    sublane count) exactly when that kernel would actually run: the
    resolved backend is pallas, a mesh is set, and the block geometry
    admits (probed by building the kernel — the builders are lru_cached,
    so the probe is the build). Everything else resolves to 1 (the
    classic per-step exchange, which keeps the interior/edge overlap
    split). Explicit user values always win; ``config.validate()``
    rejects explicit values the kernels cannot honor.
    """
    if config.halo_depth is not None:
        return config.halo_depth
    if config.scheme != "explicit":
        # The K-deep temporal exchange is an explicit-scheme schedule;
        # the implicit V-cycle exchanges 1-deep halos per smoothing
        # sweep under GSPMD (validate() rejects explicit K > 1 there).
        return 1
    mesh_shape = config.mesh_or_unit()
    if not any(d > 1 for d in mesh_shape) or backend != "pallas":
        return 1
    from parallel_heat_tpu.ops import pallas_stencil as ps
    from parallel_heat_tpu.parallel.mesh import AXIS_NAMES

    if config.ndim == 2:
        sub = ps._sub_rows(config.dtype)
        if sub > min(config.block_shape()):
            # Kernel G's depth is the sublane count; blocks smaller
            # than that cannot host it (3D has no such constraint —
            # kernel H's sweep bounds depth by block extent itself).
            return 1
        # The probe IS the build (pick_block_temporal_2d is the same
        # decision site the real round and explain use — shared
        # lru_cache entries, no probe/build divergence).
        kind, _, _ = ps.pick_block_temporal_2d(
            config.replace(halo_depth=sub), AXIS_NAMES[:2])
        return sub if kind != "jnp" else 1
    # 3D: kernel H supports any depth; score the feasible (sx, K)
    # pairs (kernel cost + modeled exchange cost) and take the best.
    pick = ps._pick_block_temporal_3d(config.block_shape(), mesh_shape,
                                      config.dtype)
    return pick[1] if pick is not None else 1


def _resolved(config: HeatConfig):
    """(config-with-concrete-depth, backend, was_auto) — the one place
    the None-means-auto depth AND the None-means-auto exchange
    schedule are substituted, shared by :func:`_build_runner` and
    :func:`explain` so the reported path can never diverge from the
    built one."""
    backend = _resolve_backend(config)
    depth = _resolve_halo_depth(config, backend)
    was_auto = config.halo_depth is None
    if config.halo_depth != depth:
        # Downstream (the temporal module, block factories) reads
        # config.halo_depth as the concrete depth; substitute the
        # resolved value once here so None never escapes the driver.
        # Re-validate: resolution happens after the caller's
        # config.validate(), so an auto-picked depth must pass the
        # same bounds an explicit one would (defense in depth against
        # picker bugs like round 4's +1-past-bmin correction).
        config = config.replace(halo_depth=depth).validate()
    # Exchange schedule (halo_overlap): resolved after the depth — the
    # pipelined-round probe needs the concrete K. The resolver is
    # shared with the round builders (temporal.resolve_halo_overlap),
    # so substituting here only makes the choice visible to explain
    # and the cache keys; it cannot fork from what the rounds build.
    # Implicit schemes take no temporal rounds (validate() rejects the
    # flag there), so the schedule stays unresolved/None for them.
    if config.scheme == "explicit":
        from parallel_heat_tpu.parallel.temporal import (
            resolve_halo_overlap)

        mode = resolve_halo_overlap(config, backend)
        if config.halo_overlap != mode:
            config = config.replace(halo_overlap=mode).validate()
    elif any(d > 1 for d in config.mesh_or_unit()):
        # Sharded implicit: resolve mg_partition="auto" to the
        # concrete V-cycle spelling here (same discipline as the
        # depth/schedule above — one resolution site shared by
        # _build_runner and explain, consulting the "mg_partition"
        # TuneDB site over the analytic partition plan).
        from parallel_heat_tpu.ops import multigrid_sharded

        mg_mode = multigrid_sharded.resolve_mg_partition(config)
        if config.mg_partition != mg_mode:
            config = config.replace(mg_partition=mg_mode).validate()
    return config, backend, was_auto


def _dtype_of(config: HeatConfig):
    return jnp.dtype(config.dtype)


def _observer_free(config: HeatConfig) -> HeatConfig:
    """THE strip site (SEMANTICS.md "Statically verified contracts"):
    the exact config :func:`_build_runner` and the executable cache key
    on, with every observation-only field reset to its default.

    The guard, diagnostics, and dispatch pipelining are observation /
    orchestration only and never part of the compiled step program:
    stripping them here means an instrumented or pipelined run reuses
    (and can never diverge from) the plain run's compiled programs.
    The field list is ``config.OBSERVATION_ONLY_FIELDS`` — the same
    declaration the heatlint cache-key audit (rule HL101) checks, so
    classifying a new field as observation-only IS stripping it; a
    field classified nowhere fails CI before it can fork a program.
    """
    import dataclasses

    from parallel_heat_tpu.config import OBSERVATION_ONLY_FIELDS

    defaults = {f.name: f.default for f in dataclasses.fields(config)}
    kw = {name: defaults[name] for name in OBSERVATION_ONLY_FIELDS
          if getattr(config, name) != defaults[name]}
    return config.replace(**kw) if kw else config


# --------------------------------------------------------------------------
# Loop construction (shared by single-device and per-shard programs)
# --------------------------------------------------------------------------

def steps_to_multistep(step, step_residual, unroll: int = 1):
    """Lift single-step fns to the ``multi_step(u, k)`` interface.

    Backends that fuse many steps per invocation (the VMEM-resident
    Pallas kernel) provide ``multi_step`` natively; plain per-step
    backends get this fori_loop lifting.

    ``unroll > 1`` amortizes the per-iteration loop-carry copy XLA
    inserts when the body ends in a custom call (a Pallas kernel's
    output cannot alias the fixed carry buffer); pure-HLO jnp steps
    update the carry in place and should keep ``unroll=1``.
    """

    def multi_step(u, k):
        return lax.fori_loop(0, k, lambda i, uu: step(uu), u,
                             unroll=unroll)

    def multi_step_residual(u, k):
        # k-1 plain steps, then one step with a fused residual — the
        # residual is the diff of the *last* step of the chunk, matching
        # the reference's consecutive-buffer check (mpi/...stat.c:245).
        u = lax.fori_loop(0, k - 1, lambda i, uu: step(uu), u,
                          unroll=unroll)
        return step_residual(u)

    return multi_step, multi_step_residual


def _make_loop(multi_step, multi_step_residual, config: HeatConfig):
    """Build ``run(u) -> (u, steps_run, converged, residual)``.

    ``multi_step(u, k)`` / ``multi_step_residual(u, k)`` (static ``k``)
    operate on whatever array the caller gives (full grid or shard
    block); this function only encodes the stepping / convergence
    policy, so the same loop serves every backend and mesh.
    """
    steps = config.steps

    if not config.converge:

        def run_fixed(u):
            if steps > 0:
                u = multi_step(u, steps)
            return (u, jnp.int32(steps), jnp.bool_(False),
                    jnp.float32(jnp.nan))

        return run_fixed

    ci = config.check_interval
    eps = config.eps
    n_full = steps // ci
    rem = steps % ci
    full_steps = n_full * ci

    def cond(carry):
        _, k, res = carry
        return (res >= eps) & (k < full_steps)

    def body(carry):
        u, k, _ = carry
        u, res = multi_step_residual(u, ci)
        return (u, k + ci, res)

    def run_converge(u):
        u, k, res = lax.while_loop(
            cond, body, (u, jnp.int32(0), jnp.float32(jnp.inf))
        )
        converged = res < eps
        if rem > 0:
            # Tail iterations past the last full check window (the
            # reference likewise runs them uninspected when STEPS is not
            # a multiple of STEP).
            u = lax.cond(
                converged,
                lambda uu: uu,
                lambda uu: multi_step(uu, rem),
                u,
            )
            k = jnp.where(converged, k, k + rem)
        return u, k, converged, res

    return run_converge


# --------------------------------------------------------------------------
# Runner builders (cached per config)
# --------------------------------------------------------------------------

def _single_multistep(config: HeatConfig, backend: str):
    """(multi_step, multi_step_residual) on the full grid, one device."""
    if config.scheme != "explicit":
        # Implicit schemes: every step is a multigrid V-cycle solve
        # (ops/multigrid.py). The ONE dispatch site — the ensemble
        # engine's vmap path and the HL103 trace targets route through
        # here too, so the batched/audited programs are the program.
        from parallel_heat_tpu.ops import multigrid

        return multigrid.implicit_multistep(config, backend)
    if backend == "pallas":
        from parallel_heat_tpu.ops import pallas_stencil

        if config.ndim == 2:
            return pallas_stencil.single_grid_multistep(config)
        return pallas_stencil.single_grid_multistep_3d(config)
    if config.ndim == 3:
        cx, cy, cz = config.cx, config.cy, config.cz
        return steps_to_multistep(
            lambda u: step_3d(u, cx, cy, cz),
            lambda u: step_3d_residual(u, cx, cy, cz),
        )
    cx, cy = config.cx, config.cy
    if config.accumulate == "f32chunk":
        # The chunked-f32 contract is backend-independent (SEMANTICS.md):
        # the jnp backend honors it with the same chunk depth the
        # temporal kernels use.
        from parallel_heat_tpu.ops import pallas_stencil

        return pallas_stencil.f32chunk_jnp_multistep(
            config.shape, config.dtype, float(cx), float(cy))
    return steps_to_multistep(
        lambda u: step_2d(u, cx, cy),
        lambda u: step_2d_residual(u, cx, cy),
    )


@functools.lru_cache(maxsize=64)
def _build_runner(config: HeatConfig):
    """Compile the full simulation program for ``config``.

    Returns ``(fn, mesh_or_None)`` where ``fn(u0)`` ->
    ``(grid, steps_run, converged, residual)``.
    """
    config.validate()
    config, backend, _ = _resolved(config)
    mesh_shape = config.mesh_or_unit()
    is_sharded = any(d > 1 for d in mesh_shape)

    if not is_sharded:
        multi_step, multi_step_residual = _single_multistep(config, backend)
        run = _make_loop(multi_step, multi_step_residual, config)
        return jax.jit(run, donate_argnums=0), None

    if config.scheme != "explicit" and config.mg_partition == "partitioned":
        # Partitioned V-cycle: per-level padded shard_map blocks with
        # a halo exchange per smoothing sweep and coarse-level
        # agglomeration (ops/multigrid_sharded.py). The parity pin is
        # on the hand-scheduled block programs themselves — never a
        # GSPMD partition constraint (see the replicated branch below
        # for why GSPMD-partitioned V-cycles fork bits on XLA:CPU).
        from parallel_heat_tpu.ops import multigrid_sharded

        mesh = make_heat_mesh(mesh_shape)
        run = multigrid_sharded.build_partitioned_runner(
            config, backend, mesh)
        return jax.jit(run, donate_argnums=0), mesh

    if config.scheme != "explicit":
        # Sharded implicit runs compute the V-cycle REPLICATED: the
        # grid enters in its mesh sharding, is gathered once, the
        # whole step loop runs as full-shape fusions on every device,
        # and the final grid leaves re-sharded for downstream
        # consumers (checkpoint gather, diagnostics). This is what
        # makes the bitwise pin — sharded == single-device, exactly —
        # hold BY CONSTRUCTION: the replicated module's fusion
        # computations are identical to the solo module's, so their
        # codegen is too. A GSPMD-partitioned V-cycle is measurably
        # NOT bitwise-stable on XLA:CPU (FMA contraction is decided
        # per fused loop body, and partitioning reshuffles vector
        # bodies/tails and layouts — one-ulp forks at ~20% of cells,
        # probed at several meshes); partitioning the levels with
        # padded shard_map blocks is the roadmap follow-on
        # (SEMANTICS.md "Implicit stepping"). The pallas transfer
        # kernels likewise decline here — the jnp spelling is the
        # pinned one.
        from parallel_heat_tpu.ops import multigrid

        mesh = make_heat_mesh(mesh_shape)
        rep = NamedSharding(mesh, P())
        ms, msr = multigrid.implicit_multistep(config, "jnp")
        inner = _make_loop(ms, msr, config)

        def run(u_in):
            # No exit re-shard: a trailing constraint back-propagates
            # partitioned shardings INTO the loop (probed — it
            # reintroduces the (2,4) fork), so the result grid stays
            # replicated (each device holds the full final grid;
            # gather/checkpoint/IO consume it directly).
            return inner(jax.lax.with_sharding_constraint(u_in, rep))

        return jax.jit(run, donate_argnums=0), mesh

    if config.ndim == 3:
        from parallel_heat_tpu.parallel import halo3d

        mesh = make_heat_mesh(mesh_shape)
        names = mesh.axis_names
        spec = P(*names)

        def local_run3(u_local):
            bidx = tuple(lax.axis_index(n) for n in names)
            kw = dict(mesh_shape=mesh_shape, grid_shape=config.shape,
                      block_index=bidx, cx=config.cx, cy=config.cy,
                      cz=config.cz, axis_names=names)
            if config.halo_depth > 1:
                from parallel_heat_tpu.parallel import temporal

                ms, msr = temporal.block_temporal_multistep(config, kw,
                                                            backend=backend)
            else:
                kw["overlap"] = config.overlap
                ms, msr = steps_to_multistep(
                    lambda u: halo3d.block_step_3d(u, **kw),
                    lambda u: halo3d.block_step_3d_residual(u, **kw),
                )
            return _make_loop(ms, msr, config)(u_local)

        run = _shard_map(
            local_run3, mesh=mesh, in_specs=spec,
            out_specs=(spec, P(), P(), P()),
            # Same rationale as the 2D branch below: pallas_call's
            # internal slices don't carry varying-manual-axes
            # annotations; the pmax in the residual round guarantees
            # the scalar outputs' replication either way.
            check_vma=backend != "pallas",
        )
        return jax.jit(run, donate_argnums=0), mesh

    mesh = make_heat_mesh(mesh_shape)
    names = mesh.axis_names
    spec = P(*names)
    use_pallas = backend == "pallas"

    def local_run(u_local):
        bidx = tuple(lax.axis_index(n) for n in names)
        # The temporal path's comm/compute schedule is halo_overlap
        # (resolved in config; see temporal.block_temporal_multistep);
        # the per-step `overlap` interior/edge split is added only for
        # the per-step paths (same pattern as the 3D branch above).
        kw = dict(mesh_shape=mesh_shape, grid_shape=config.shape,
                  block_index=bidx, cx=config.cx, cy=config.cy,
                  axis_names=names)
        if config.halo_depth > 1:
            # K-deep temporal exchange: K steps per collective round
            # (parallel/temporal.py; Mosaic kernel G when the resolved
            # backend is pallas and depth == the dtype's sublane count,
            # jnp rounds otherwise).
            from parallel_heat_tpu.parallel import temporal

            ms, msr = temporal.block_temporal_multistep(config, kw,
                                                        backend=backend)
            pre = post = lambda u: u
        elif use_pallas:
            from parallel_heat_tpu.ops import pallas_stencil

            kw["overlap"] = config.overlap
            # The pallas block step carries an extended block between
            # steps; pre/post convert at loop entry/exit.
            step, stepr, pre, post = pallas_stencil.block_steps(config, kw)
            ms, msr = steps_to_multistep(step, stepr)
        else:
            kw["overlap"] = config.overlap
            step = lambda u: block_step_2d(u, **kw)
            stepr = lambda u: block_step_2d_residual(u, **kw)
            pre = post = lambda u: u
            ms, msr = steps_to_multistep(step, stepr)
        u_out, k, c, r = _make_loop(ms, msr, config)(pre(u_local))
        return post(u_out), k, c, r

    # check_vma off for the pallas backend: pallas_call's internal slices
    # don't carry varying-manual-axes annotations (notably under the HLO
    # interpreter). Replication of the scalar outputs is guaranteed by
    # the pmax in the residual step either way.
    run = _shard_map(
        local_run, mesh=mesh, in_specs=spec,
        out_specs=(spec, P(), P(), P()),
        check_vma=not use_pallas,
    )
    return jax.jit(run, donate_argnums=0), mesh


def make_initial_grid(config: HeatConfig) -> jax.Array:
    """Build the initial grid, sharded over the mesh when one is set.

    The grid is *born sharded*: each device materializes its block from
    an iota formula under GSPMD — no host-side full grid, no master
    scatter (contrast ``mpi/...stat.c:86-127`` and SURVEY.md §2d.1-2).
    """
    config.validate()
    model = model_for(config)
    dtype = _dtype_of(config)
    mesh_shape = config.mesh_or_unit()
    if any(d > 1 for d in mesh_shape):
        mesh = make_heat_mesh(mesh_shape)
        sharding = NamedSharding(mesh, P(*mesh.axis_names))
        build = jax.jit(
            lambda: model.init_grid(dtype), out_shardings=sharding
        )
        return build()
    return jax.jit(lambda: model.init_grid(dtype))()


def _prepare_initial(config: HeatConfig,
                     initial: Optional[jax.Array]) -> jax.Array:
    """Default, validate, place on the mesh, copy (runners donate
    their input buffer).

    Sharded configs ``device_put`` caller-supplied grids with the
    target ``NamedSharding`` BEFORE any device computation: host
    (NumPy) inputs — a gathered ``.npz`` resume, the CLI's
    ``--resume``, any user array — transfer per-shard slices
    (O(N²/P) per device) and are dtype-cast on the host first. The
    naive ``jnp.asarray`` spelling would commit the FULL grid to
    device 0 and only then reshard — a 4 GiB single-device spike at
    32768² f32, exactly the O(N²)-per-rank quirk of the reference
    (``mpi/...stat.c:46,72-75``, SURVEY §2d.1) this framework
    eliminates everywhere else.
    """
    if initial is None:
        return jax.block_until_ready(make_initial_grid(config))
    if tuple(initial.shape) != config.shape:
        raise ValueError(
            f"initial grid shape {tuple(initial.shape)} does not match "
            f"config shape {config.shape}"
        )
    dtype = _dtype_of(config)
    mesh_shape = config.mesh_or_unit()
    if any(d > 1 for d in mesh_shape):
        mesh = make_heat_mesh(mesh_shape)
        sharding = NamedSharding(mesh, P(*mesh.axis_names))
        if not isinstance(initial, jax.Array):
            # Cast on the host so the device never sees the off-dtype
            # full grid (e.g. resuming an f32 checkpoint into bf16).
            initial = np.asarray(initial, dtype=dtype)
        # device_put redistributes whatever the input's current
        # placement is (host slices, single-device, other mesh) into
        # per-shard blocks; astype+copy then run sharded (the copy
        # also protects the caller from the runner's donation —
        # device_put alone may alias an already-correctly-placed
        # array).
        out = jnp.copy(jax.device_put(initial, sharding).astype(dtype))
    else:
        # Copy (the runner donates its input buffer — protect the
        # caller) and honor the configured storage dtype.
        out = jnp.copy(jnp.asarray(initial).astype(dtype))
    return jax.block_until_ready(out)


def explain(config: HeatConfig, ensemble: Optional[int] = None) -> dict:
    """Resolve — without running anything — which execution path a
    config takes: backend, mesh, and the exact kernel/pick the solver's
    factories would choose. Surfaced by the CLI as ``--explain``;
    useful for understanding why a geometry declined to a fallback.

    The kernel decisions are NOT mirrored here: each factory's choice
    lives in a shared pick function (``ps.pick_single_2d`` /
    ``pick_single_3d`` / ``pick_block_2d``; the temporal rounds probe
    the same lru_cached builders with the same args the real rounds
    use), so a pick-order change is automatically reflected —
    mirroring once desynchronized exactly the decline cases --explain
    exists for (the kernel-C omission, see test_explain_sharded_tiled_
    fallback). Only the label formatting lives here.

    ``ensemble`` (a member count B) additionally reports the batched
    ensemble engine's resolved path for this config — the same
    ``ensemble.engine.ensemble_path`` decision the engine executes —
    plus the daemon-packing verdict (``ensemble.engine.packable``).

    Two keys report the decision provenance:

    - ``decided_by``: per consulted site (``single_2d``,
      ``block_temporal_2d``, ``ensemble_2d``, ``halo_overlap``),
      whether the tuning DB (``tuned-db`` — with the winning entry
      key), a ``forced`` pin, or the ``analytic-model`` made the
      choice. Collected by re-running the SAME pickers under
      ``tune.record``, so it can never desynchronize from execution.
    - ``halo_overlap_effective``: the schedule that actually runs —
      an explicit/tuned ``"pipeline"`` downgrades to ``"overlap"``
      at build time when the pipelined round declines the geometry;
      artifact writers (``bench.py``, ``tools/scaling_study.py``)
      label rows with this instead of re-deriving it by hand.
    """
    from parallel_heat_tpu import tune

    with tune.record() as notes:
        out = _explain_body(config, ensemble)
    decided: dict = {}
    for n in notes:
        d = {"source": n["source"], "choice": n["choice"]}
        if "entry" in n:
            d["entry"] = n["entry"]
        # Last note wins: depth probes consult the same sites with
        # trial configs before the final resolved pick re-runs them.
        decided[n["site"]] = d
    out["decided_by"] = decided
    return out


def _explain_body(config: HeatConfig, ensemble: Optional[int]) -> dict:
    config = config.validate()
    auto_overlap = config.halo_overlap in (None, "auto")
    config, backend, auto_depth = _resolved(config)
    mesh_shape = config.mesh_or_unit()
    is_sharded = any(d > 1 for d in mesh_shape)
    out = {
        "backend": backend,
        "dtype": config.dtype,
        "shape": config.shape,
        "mesh": mesh_shape if is_sharded else None,
        "mode": "converge" if config.converge else "fixed",
        "scheme": config.scheme,
    }
    # The static work model (prof/model.py): FLOPs + HBM + ICI per
    # step for THIS resolved schedule, priced against the generation
    # peaks — the roofline denominator every attribution consumer
    # joins against. Computed here (config is already resolved) so a
    # run_header's embedded explain carries it for free.
    try:
        from parallel_heat_tpu.prof import model as _prof_model

        out["work_model"] = _prof_model.work_model(config,
                                                   resolved=True)
    except Exception as e:  # noqa: BLE001 — explain must still
        # resolve when the model cannot (observation-only plane)
        out["work_model_error"] = f"{type(e).__name__}: {e}"
    # The schedule that actually runs: resolve_halo_overlap lets an
    # explicit "pipeline" through unchecked (explicit wins), but the
    # round builder falls back to the deferred schedule when the
    # pipelined round declines — report the post-fallback value so
    # artifact labels can't drift from what ran.
    effective = config.halo_overlap
    if effective == "pipeline":
        from parallel_heat_tpu.ops import pallas_stencil as _ps
        from parallel_heat_tpu.parallel.mesh import AXIS_NAMES as _AX

        if (backend != "pallas" or config.ndim != 2
                or _ps.pick_block_temporal_2d_pipelined(
                    config, _AX[:2]) is None):
            effective = "overlap"
    out["halo_overlap_effective"] = effective
    if ensemble is not None:
        from parallel_heat_tpu.ensemble.engine import (
            ensemble_path, packable)

        path = (None if is_sharded
                else ensemble_path(_observer_free(config)))
        ok, reason = packable(config)
        out["ensemble"] = {
            "members": int(ensemble),
            "path": ("kernel M (member-batched VMEM-resident "
                     "multi-step)" if path == "M"
                     else "vmap over the jnp multistep family"
                     if path == "vmap"
                     else "unsupported (sharded members run solo)"),
            "packable": ok,
            "packable_reason": reason,
        }
    if config.guard_interval is not None:
        out["guard"] = (f"isfinite-all every {config.guard_interval} "
                        f"steps (observation-only)")
    if config.diag_interval is not None:
        out["diagnostics"] = (f"fused grid stats every "
                              f"{config.diag_interval} steps "
                              f"(observation-only)")
    if config.pipeline_depth is not None:
        out["pipeline"] = (f"depth {config.pipeline_depth} dispatch-"
                           f"ahead stream (dispatch-order only; "
                           f"observer drain overlaps the next chunk)")
    if config.scheme != "explicit":
        # Implicit path: report the exact hierarchy/smoother/transfer
        # structures implicit_multistep builds (shared helpers in
        # ops/multigrid.py — no mirroring, same no-desync rationale as
        # the kernel picks below).
        from parallel_heat_tpu.ops import multigrid

        partitioned = (is_sharded
                       and config.mg_partition == "partitioned")
        mg = multigrid.explain_hierarchy(
            config,
            backend if (not is_sharded or partitioned) else "jnp")
        out["multigrid"] = mg
        if partitioned:
            from parallel_heat_tpu.ops import multigrid_sharded

            mg["partition_plan"] = multigrid_sharded.explain_partition(
                config)
            agg = mg["partition_plan"]["agglomerate_from"]
            out["path"] = (
                f"implicit {config.scheme}: partitioned multigrid "
                f"V-cycle per step "
                f"({mg['partition_plan']['partitioned_levels']} of "
                f"{len(mg['levels'])} levels on shard blocks, "
                + (f"agglomerated from level {agg}, "
                   if agg is not None else "no agglomeration, ")
                + f"{mg['smoother']}, {mg['transfers']})")
        else:
            if is_sharded:
                out["mg_partition"] = config.mg_partition
            out["path"] = (
                f"implicit {config.scheme}: multigrid V-cycle per "
                f"step ({len(mg['levels'])} levels, {mg['smoother']}, "
                f"{mg['transfers']})")
        return out

    if is_sharded:
        out["halo_depth"] = (f"{config.halo_depth} (auto)" if auto_depth
                             else config.halo_depth)
        if config.halo_depth > 1:
            # The exchange/compute schedule (SEMANTICS.md "Overlapped
            # exchange") — resolved by the same
            # temporal.resolve_halo_overlap the rounds build with.
            out["halo_overlap"] = (f"{config.halo_overlap} (auto)"
                                   if auto_overlap
                                   else config.halo_overlap)
    if backend != "pallas":
        if config.accumulate == "f32chunk":
            from parallel_heat_tpu.ops import pallas_stencil as ps

            out["path"] = ("chunked-f32 jnp multistep "
                           f"K={ps._sub_rows(config.dtype)}")
            return out
        out["path"] = "XLA-fused jnp stencil"
        if is_sharded:
            # Same feasibility check the round builder applies
            # (block_multistep_*'s b0 >= 2k fallback) — the reported
            # schedule must match the built one.
            can_defer = (config.block_shape()[0]
                         >= 2 * config.halo_depth)
            deep = ("K-deep temporal exchange rounds"
                    + (", deferred bands — the last exchange phase's "
                       "ppermutes overlap the bulk update"
                       if config.halo_overlap != "phase" and can_defer
                       else ", phase-separated"))
            out["path"] += (
                f" on shard blocks (halo_depth={config.halo_depth}: "
                + (deep if config.halo_depth > 1
                   else "per-step halo exchange")
                + ")")
        return out

    from parallel_heat_tpu.ops import pallas_stencil as ps

    dtype = config.dtype
    cx, cy = float(config.cx), float(config.cy)
    sub = ps._sub_rows(dtype)

    if is_sharded:
        bx_by = config.block_shape()
        if config.halo_depth > 1:
            from parallel_heat_tpu.parallel.mesh import AXIS_NAMES

            if config.ndim == 2 and config.halo_depth == sub:
                kind, built, _ = ps.pick_block_temporal_2d(
                    config, AXIS_NAMES[:2])
                if kind in ("G-uni", "G-fuse"):
                    sched = ""
                    if (config.halo_overlap == "pipeline"
                            and ps.pick_block_temporal_2d_pipelined(
                                config, AXIS_NAMES[:2]) is not None):
                        sched = (", pipelined double-buffered edge "
                                 "strips — the next round's ppermutes "
                                 "(both phases) overlap the bulk "
                                 "kernel")
                    elif (config.halo_overlap != "phase"
                          and ps.pick_block_temporal_2d_deferred(
                              config, AXIS_NAMES[:2]) is not None):
                        sched = (", deferred N/S bands — phase-2 "
                                 "ppermutes overlap the bulk kernel")
                    layout = ("uniform-window fused"
                              if kind == "G-uni" else "fused")
                    out["path"] = (
                        f"kernel G (shard-block temporal, K={sub}, "
                        f"{layout} exchange assembly" + sched
                        + f") per exchange round, tail {built.tail}")
                    return out
                if kind == "G-circ":
                    out["path"] = (
                        f"kernel G (shard-block temporal, K={sub}, "
                        f"circular layout) per exchange round, "
                        f"tail {built.tail}")
                    return out
                if kind == "G":
                    out["path"] = (
                        f"kernel G (shard-block temporal, K={sub}, "
                        f"legacy padded layout) per exchange round, "
                        f"padded width {built.padded_width}")
                    return out
            if config.ndim == 3:
                # Mirrors temporal._pallas_round_3d's build args.
                K = config.halo_depth
                halos = tuple(K if d > 1 else 0 for d in mesh_shape)
                args3 = (bx_by, dtype, cx, cy, float(config.cz),
                         config.shape, K, halos, AXIS_NAMES[:3])
                built = ps._build_temporal_block_3d_fused(*args3)
                label = "fused exchange assembly"
                if (built is not None
                        and config.halo_overlap != "phase"
                        and ps.pick_block_temporal_3d_deferred(
                            config, AXIS_NAMES[:3], mesh_shape)
                        is not None):
                    label += (", deferred x bands — phase-3 ppermutes "
                              "overlap the bulk kernel")
                if built is None:
                    built = ps._build_temporal_block_3d(*args3)
                    label = "assembled layout"
                if built is not None:
                    out["path"] = (
                        f"kernel H (3D shard-block temporal, K={K}, "
                        f"{label}) per exchange round, sx={built.sx}, "
                        f"tails=({built.tail_y}, {built.tail_z})")
                    return out
            out["path"] = (f"jnp K-deep temporal rounds "
                           f"(halo_depth={config.halo_depth}) on shard "
                           f"blocks")
            return out
        if config.ndim == 2:
            from parallel_heat_tpu.parallel.mesh import AXIS_NAMES

            kind, _ = ps.pick_block_2d(config, AXIS_NAMES[:2])
            if kind == "B":
                t = ps._pick_strip_rows(bx_by[0], bx_by[1], dtype,
                                        sharded=True)
                out["path"] = (f"kernel B (streaming strip, sharded) "
                               f"T={t} + jnp edge-column epilogue")
                return out
            if kind == "C":
                tc = ps._pick_tile_2d(bx_by[0], bx_by[1], dtype,
                                      sharded=True)
                out["path"] = (f"kernel C (2D-tiled, sharded) "
                               f"tile={tc[0]}x{tc[1]} + jnp edge-column "
                               f"epilogue")
                return out
        out["path"] = "jnp block step (per-step halo exchange)"
        return out

    if config.ndim == 3:
        kind, pick = ps.pick_single_3d(config.shape, dtype)
        if kind == "F":
            out["path"] = (f"kernel F (X-slab temporal) sx={pick[0]} "
                           f"K={pick[1]}")
        elif kind == "D":
            out["path"] = (f"kernel D (XY-tiled 3D slab) sx={pick[0]} "
                           f"ty={pick[1]}")
        else:
            out["path"] = "XLA-fused jnp stencil (3D pickers declined)"
        return out

    acc = config.accumulate == "f32chunk"
    kind, _ = ps.pick_single_2d(config.shape, dtype, cx, cy,
                                accumulate=config.accumulate)
    if acc:
        # Same decision site as execution (single_grid_multistep's
        # f32chunk branch); the suffix names the changed numerics.
        if kind == "E":
            t = ps._pick_temporal_strip(config.nx, config.ny, dtype,
                                        acc_f32=True)
            out["path"] = (f"kernel E (temporal-blocked strip, f32-chunk "
                           f"accumulation) T={t} K={sub}")
        elif kind == "E-uni":
            t = ps._pick_temporal_strip(config.nx, config.ny, dtype,
                                        acc_f32=True, uniform=True)
            out["path"] = (f"kernel E-uni (uniform-gather temporal "
                           f"strip, f32-chunk accumulation) T={t} "
                           f"K={sub}")
        elif kind == "I":
            ti = ps._pick_tile_temporal_2d(config.nx, config.ny, dtype,
                                           acc_f32=True)
            out["path"] = (f"kernel I (2D-tiled temporal, f32-chunk "
                           f"accumulation) tile={ti[0]}x{ti[1]} K={sub}")
        elif kind == "I-uni":
            ti = ps._pick_tile_temporal_2d(config.nx, config.ny, dtype,
                                           acc_f32=True, uniform=True)
            out["path"] = (f"kernel I-uni (uniform-gather 2D-tiled "
                           f"temporal, f32-chunk accumulation) "
                           f"tile={ti[0]}x{ti[1]} K={sub}")
        else:
            out["path"] = ("chunked-f32 jnp multistep (temporal kernels "
                           f"declined) K={sub}")
        return out
    if kind == "A":
        out["path"] = "kernel A (VMEM-resident multi-step)"
    elif kind == "E":
        t = ps._pick_temporal_strip(config.nx, config.ny, dtype)
        out["path"] = f"kernel E (temporal-blocked strip) T={t} K={sub}"
    elif kind == "E-uni":
        t = ps._pick_temporal_strip(config.nx, config.ny, dtype,
                                    uniform=True)
        out["path"] = (f"kernel E-uni (uniform-gather temporal strip) "
                       f"T={t} K={sub}")
    elif kind == "I":
        ti = ps._pick_tile_temporal_2d(config.nx, config.ny, dtype)
        out["path"] = (f"kernel I (2D-tiled temporal) tile="
                       f"{ti[0]}x{ti[1]} K={sub}")
    elif kind == "I-uni":
        ti = ps._pick_tile_temporal_2d(config.nx, config.ny, dtype,
                                       uniform=True)
        out["path"] = (f"kernel I-uni (uniform-gather 2D-tiled "
                       f"temporal) tile={ti[0]}x{ti[1]} K={sub}")
    elif kind == "B":
        t_b = ps._pick_strip_rows(config.nx, config.ny, dtype,
                                  sharded=False)
        out["path"] = f"kernel B (streaming strip) T={t_b}"
    elif kind == "C":
        t_c = ps._pick_tile_2d(config.nx, config.ny, dtype, sharded=False)
        out["path"] = f"kernel C (2D-tiled streaming) tile={t_c[0]}x{t_c[1]}"
    else:
        out["path"] = "XLA-fused jnp stencil (2D pickers declined)"
    return out


_COMPILED_CACHE: "collections.OrderedDict" = collections.OrderedDict()


def _compiled_for(runner, config: HeatConfig, u):
    """AOT-compile ``runner`` for ``u``'s shape/sharding, memoized.

    Lowering+compiling *before* the caller starts its clock keeps
    compile time out of ``elapsed_s`` even on the first run of a
    config — the reference's binaries are likewise built before their
    wall-clock brackets start (``mpi/Makefile``, ``cuda/Makefile``),
    so one-shot timings stay comparable. ``jit``'s own cache is keyed
    internally and would only be populated by a real (buffer-donating)
    call; this explicit executable cache gives the same reuse without
    running a simulation to warm it.

    The runner object itself is part of the key (not just the config):
    after ``_build_runner.cache_clear()`` a fresh jit wrapper misses
    here naturally, so executables cannot outlive the runner-cache
    invalidation the tests rely on. Holding the runner as a dict key
    also keeps it alive, so identity cannot be recycled.
    """
    key = (runner, config, u.shape, str(u.dtype),
           str(getattr(u, "sharding", None)))
    hit = _COMPILED_CACHE.get(key)
    if hit is None:
        if len(_COMPILED_CACHE) >= 256:
            # Evict least-recently-USED (hits move entries to the end
            # below), never a still-hot config; wiping everything would
            # recompile those.
            _COMPILED_CACHE.popitem(last=False)
        hit = runner.lower(u).compile()
        _COMPILED_CACHE[key] = hit
    else:
        _COMPILED_CACHE.move_to_end(key)
    return hit


def _warn_if_diverged(res: Optional[float], steps_run: int,
                      checked: bool) -> None:
    """Runtime divergence detection (converge mode only — fixed-step
    runs compute no residual to inspect): a non-finite residual means
    the scheme blew up (inf - inf = NaN in the diff, or overflow to
    inf), the while-loop's ``res >= eps`` went False, and the run
    stopped early reporting ``converged=False``. Surface that as a
    warning so the early exit is not mistaken for a quiet
    non-convergence — the reference has no such guard (SURVEY.md §5
    "Failure detection: none"); this pairs with the pre-run
    ``HeatConfig.stability_margin`` check.

    ``checked`` must be False when no residual check actually ran
    (fewer steps than one ``check_interval``): the loop seed is the
    inf sentinel then, indistinguishable from a real non-finite
    residual, and warning on it would flag perfectly stable runs."""
    import math
    import warnings

    if checked and res is not None and not math.isfinite(res):
        warnings.warn(
            f"simulation diverged: non-finite residual after {steps_run} "
            f"steps (coefficient sum past the stability bound? see "
            f"HeatConfig.stability_margin); grid values are garbage, "
            f"boundary cells remain exact",
            RuntimeWarning,
        )


@jax.jit
def _all_finite(u):
    # The guard reduction: one fused isfinite-all over the grid. Under
    # jit a sharded input reduces on device (psum-free all-reduce via
    # GSPMD) and returns a replicated scalar — no grid gather. jit
    # memoizes per shape/dtype/sharding, so repeated guard checks of a
    # long run reuse one executable.
    return jnp.isfinite(u).all()


def grid_all_finite(grid) -> bool:
    """On-device non-finite guard: True iff every cell is finite.

    Observation-only (reads the grid, never writes, no donation) and
    cheap — a single fused reduction, O(bytes) at memory bandwidth.
    Used by :func:`solve_stream` / :func:`solve` when
    ``HeatConfig.guard_interval`` is set, and by the run supervisor
    (``parallel_heat_tpu.supervisor``) to decide rollback. The
    TraceAnnotation brackets the host-side dispatch+wait, so profiler
    timelines show the guard as a named phase (it is never part of the
    compiled step programs).
    """
    with jax.profiler.TraceAnnotation("heat:guard"):
        return bool(_all_finite(grid))


@jax.jit
def _grid_stats_solo(u):
    # The diagnostics reduction without an update baseline: min, max and
    # total heat content in ONE fused pass (XLA fuses the three
    # reductions into a single read of the grid — the same fusion shape
    # as the guard's `_all_finite`). Sub-f32 storage accumulates the sum
    # in f32; f32/f64 accumulate natively.
    acc = u if jnp.dtype(u.dtype).itemsize >= 4 else u.astype(jnp.float32)
    return jnp.min(u), jnp.max(u), jnp.sum(acc)


@jax.jit
def _grid_stats_delta(u, prev):
    # Full diagnostics pass: grid extrema + heat content + L2/L-inf of
    # the update since the previous sample, one fused read of both
    # buffers. Like `_all_finite`, a sharded input reduces on device
    # under GSPMD and returns replicated scalars — no gather.
    acc = u if jnp.dtype(u.dtype).itemsize >= 4 else u.astype(jnp.float32)
    d = (u.astype(acc.dtype) - prev.astype(acc.dtype))
    return (jnp.min(u), jnp.max(u), jnp.sum(acc),
            jnp.sqrt(jnp.sum(d * d)), jnp.max(jnp.abs(d)))


def grid_stats(grid, prev=None) -> dict:
    """Fused on-device grid diagnostics: ``min``, ``max``, ``heat``
    (total heat content, the conserved-quantity-style observable), and
    — when ``prev`` (an earlier grid of the same shape) is given —
    ``update_l2``/``update_linf``, the norms of the change since
    ``prev``.

    Observation-only, exactly like :func:`grid_all_finite`: one fused
    reduction pass, reads only (no donation, no writes), never part of
    any compiled step program. Used by :func:`solve_stream` /
    :func:`solve` under ``HeatConfig.diag_interval`` and by the
    supervisor's progress guard (stall/drift classification). The
    TraceAnnotation brackets the host-side dispatch+wait so profiler
    timelines show diagnostics as a named phase.
    """
    with jax.profiler.TraceAnnotation("heat:diag"):
        if prev is None:
            mn, mx, heat = _grid_stats_solo(grid)
            l2 = linf = None
        else:
            mn, mx, heat, l2, linf = _grid_stats_delta(grid, prev)
            l2, linf = float(l2), float(linf)
        return {"min": float(mn), "max": float(mx), "heat": float(heat),
                "update_l2": l2, "update_linf": linf}


def _start_host_copies(*values) -> None:
    """Begin non-blocking device->host transfers of observer scalars
    (chunk step counts, guard verdicts, diagnostics reductions) so the
    copies complete behind the next chunk's compute instead of
    serializing at the drain. Accepts arrays, tuples of arrays, or
    None; tolerates arrays without ``copy_to_host_async`` (older jax)
    — the eventual host read then pays the sync itself."""
    for v in values:
        if v is None:
            continue
        items = v if isinstance(v, tuple) else (v,)
        for a in items:
            start = getattr(a, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:  # noqa: BLE001 — observation-only
                    pass


def _warn_guard_tripped(step: int) -> None:
    """The fixed-step analog of :func:`_warn_if_diverged`: the runtime
    guard found non-finite values, so every step from the first bad one
    on produced garbage (boundary cells remain exact — SEMANTICS.md
    "Boundary exactness"). The supervisor upgrades this observation to
    rollback/retry; plain streamed runs get the loud warning."""
    import warnings

    warnings.warn(
        f"runtime guard: non-finite grid values detected at step {step} "
        f"(coefficient sum past the stability bound? see "
        f"HeatConfig.stability_margin); grid values are garbage from the "
        f"first bad step on, boundary cells remain exact",
        RuntimeWarning,
    )


def resolved_pipeline_depth(config: HeatConfig,
                            pipeline_depth: Optional[int] = None) -> int:
    """The dispatch depth :func:`solve_stream` will run ``config`` at:
    the explicit argument wins, else ``config.pipeline_depth``, else
    auto — 2 for fixed-step runs on an accelerator backend, 1
    otherwise. Converge runs must drain each chunk's on-device
    convergence vote before dispatching the next, so dispatch-ahead
    cannot apply; on CPU the host and the "device" share cores, so
    there is no idle accelerator for depth 2 to keep busy and the
    protection copy + in-flight buffer pressure are a measured ~10%
    pessimization (priced by ``bench.py --row stream512``,
    BENCH_r06_stream512_dryrun.json) — the same platform-aware shape
    as ``backend="auto"``. Exposed so drivers that hand stream-yielded
    grids to other consumers (the supervisor's async saver) can tell
    whether those grids are already donation-protected copies
    (depth > 1) without re-deriving the auto rule."""
    depth = (pipeline_depth if pipeline_depth is not None
             else config.pipeline_depth)
    if depth is not None:
        return depth
    if config.converge:
        return 1
    plat = jax.devices()[0].platform
    return 2 if plat in ("tpu", "axon", "gpu", "cuda", "rocm") else 1


def _emit_profile(telemetry, model, *, step: int, steps: int,
                  wall_s: float, gap_s=None) -> None:
    """Join one chunk against the work model and emit the `profile`
    event (prof/attrib.py). Observation-only: any failure is swallowed
    — attribution must never be able to end a stream."""
    if model is None:
        return
    try:
        from parallel_heat_tpu.prof import attrib as _prof_attrib

        seg = _prof_attrib.attribute_chunk(
            {"step": telemetry.step_offset + step, "steps": steps,
             "wall_s": wall_s, "gap_s": gap_s}, model)
        telemetry.emit("profile", **seg)
    except Exception:  # noqa: BLE001 — observation-only
        pass


def solve_stream(config: HeatConfig, initial: Optional[jax.Array] = None,
                 chunk_steps: Optional[int] = None, telemetry=None,
                 pipeline_depth: Optional[int] = None):
    """Iterate the simulation in host-visible chunks; yields a
    :class:`HeatResult` after each chunk (cumulative ``steps_run``).

    The periodic-snapshot driver: between chunks the caller may
    checkpoint (``utils.checkpoint.save_checkpoint``), stream metrics,
    or render — state the reference exposes only at program exit
    (SURVEY.md §5 "Checkpoint/resume: none"). Each chunk runs the same
    compiled program ``solve`` uses (donated double-buffers, on-device
    convergence), so chunking costs one dispatch per chunk, nothing
    more. In converge mode ``chunk_steps`` is rounded up to a multiple
    of ``check_interval``, keeping the check schedule identical to an
    unchunked run; iteration stops at convergence. Under
    ``accumulate='f32chunk'`` in fixed mode, ``chunk_steps`` is
    likewise rounded up to a multiple of the dtype's sublane count (the
    f32-accumulation chunk depth K): each stream chunk is an
    independent compiled run whose state enters and leaves in the
    storage dtype, so a boundary that is not K-aligned would silently
    restart the f32 chunk mid-window and shift the rounding schedule
    away from the unchunked run's (SEMANTICS.md "Sub-f32 rounding
    points"). Converge mode needs no extra rounding there: the
    check-interval rounding already reproduces the unchunked run's
    per-``check_interval`` chunk restarts exactly.

    ``telemetry`` (a :class:`utils.telemetry.Telemetry`) receives a
    ``run_header`` event plus one ``chunk`` event per yield (steps,
    chunk wall time, throughput, residual, guard verdict), and — when
    ``config.diag_interval`` is set — a ``diagnostics`` event per
    sample (:func:`grid_stats` at the first chunk boundary at-or-after
    each interval multiple, plus the final chunk; the sample also
    rides ``HeatResult.diagnostics``). Pure host-side observation
    between dispatches: the compiled programs, their cache keys, and
    the yielded results are identical with or without a sink or a
    diag interval (pinned by ``tests/test_telemetry.py`` /
    ``tests/test_diagnostics.py``).

    ``pipeline_depth`` (explicit argument wins over
    ``config.pipeline_depth``; ``None`` = auto — 2 for fixed-step
    runs on an accelerator backend, 1 otherwise; see
    :func:`resolved_pipeline_depth`) selects the dispatch pipelining
    of the chunk loop (SEMANTICS.md "Pipelined stream"). At depth 1 the loop
    is fully synchronous: each chunk is dispatched, waited for, then
    observed. At depth >= 2, chunk *n+1* is dispatched immediately
    after chunk *n*'s dispatch returns — JAX async dispatch keeps the
    device busy through the observer drain, telemetry, and whatever
    the caller does between yields — and chunk *n*'s observers (guard
    verdict, diagnostics, step scalars) are fetched afterwards via
    non-blocking device-to-host copies. Every yielded grid at
    depth >= 2 is a donation-protected device copy (enqueued before
    the next dispatch donates the live buffer), so the consume-before-
    advancing rule above is automatically satisfied; the copy costs
    one grid read+write of HBM traffic per boundary — ~1/chunk_steps
    of a step. Pipelining is dispatch-order only: grids, guard/diag
    values, compiled programs (zero new runner-cache entries), and
    checkpoint bytes are identical to the depth-1 loop; per-chunk
    ``wall_s`` switches to drain-to-drain brackets (the depth-1
    dispatch-to-ready bracket is kept at depth 1).

    Consume each yielded grid (e.g. ``np.asarray`` / checkpoint) before
    advancing the generator: the next chunk donates that buffer to XLA
    (at ``pipeline_depth >= 2`` the yielded grid is a protected copy
    and survives advancing, but the rule keeps callers depth-agnostic).
    """
    config = config.validate()
    guard_interval = config.guard_interval
    diag_interval = config.diag_interval
    depth = resolved_pipeline_depth(config, pipeline_depth)
    if depth < 1:
        raise ValueError(f"pipeline_depth must be >= 1, got {depth}")
    elif depth > 1 and config.converge:
        raise ValueError(
            "pipeline_depth > 1 is fixed-step only (converge mode must "
            "read each chunk's convergence verdict before dispatching "
            "the next chunk)")
    # Strip observation-only fields so the runner/executable caches key
    # on the observer-free config (see _observer_free's docstring).
    config = _observer_free(config)
    if chunk_steps is not None and chunk_steps < 1:
        raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
    total = config.steps
    chunk = chunk_steps if chunk_steps else max(1, total)
    if config.converge:
        ci = config.check_interval
        chunk = ((chunk + ci - 1) // ci) * ci
    elif config.accumulate == "f32chunk":
        from parallel_heat_tpu.config import sublane_count

        sub = sublane_count(config.dtype)
        chunk = ((chunk + sub - 1) // sub) * sub
    u = _prepare_initial(config, initial)

    prof_model = None
    if telemetry is not None:
        telemetry.run_header(config, pipeline_depth=depth)
        cells = profiling.cell_count(config)
        bytes_per_cell = profiling.bytes_per_cell(config)
        # Work model for the per-chunk `profile` events: pure host
        # arithmetic over the resolved schedule (prof/model.py); a
        # model that cannot build silently disables attribution — the
        # stream itself must never depend on the observer.
        try:
            from parallel_heat_tpu.prof import model as _prof_model

            prof_model = _prof_model.work_model(config)
        except Exception:  # noqa: BLE001 — observation-only
            prof_model = None

    done = 0
    elapsed = 0.0
    next_guard = guard_interval if guard_interval is not None else None
    next_diag = diag_interval if diag_interval is not None else None
    prev_diag = None
    prev_diag_step = 0
    # Implicit runs: whether the once-per-stream level-wall-share
    # measurement already rode a vcycle sample (sync loop only — the
    # pipelined dispatch region must not synchronize, so depth > 1
    # streams carry chunk/diag events but no vcycle samples).
    vc_shares_sent = False
    if next_diag is not None:
        # The update-residual baseline: a COPY of the initial state (the
        # first chunk donates `u` itself). This is the one grid-sized
        # cost diagnostics carries; samples between boundaries pay only
        # the fused reduction.
        prev_diag = jnp.copy(u)

    if depth > 1:
        # ------------------------------------------------------------
        # Pipelined dispatch (fixed-step; SEMANTICS.md "Pipelined
        # stream"): keep up to `depth` chunks in flight, drain the
        # oldest chunk's observers while its successors compute.
        # ------------------------------------------------------------
        # Pre-compile every chunk program before the clock starts,
        # like solve(): the drain-to-drain wall brackets would
        # otherwise charge a mid-stream compile (the final partial
        # chunk's program) to one chunk's timing.
        sizes, rem = set(), total
        while rem > 0:
            c = min(chunk, rem)
            sizes.add(c)
            rem -= c
        for c in sizes:
            ccfg = config.replace(steps=c)
            runner, _ = _build_runner(ccfg)
            _compiled_for(runner, ccfg, u)

        inflight = collections.deque()
        disp_done = 0
        t_mark = time.perf_counter()
        # Device-starvation probe: set at a drain that finds EVERY
        # dispatched chunk already complete (the device is provably
        # idle from that instant until the next dispatch); the window
        # is attributed to the next chunk's gap_s. A host-observable
        # LOWER bound on idleness — it is what makes the report tool's
        # `busy<X` CI gate meaningful for pipelined runs.
        idle_mark = None

        def _dispatch():  # heatlint: dispatch-region
            # The pragma scopes heatlint rule HL201: nothing in this
            # function may synchronize with the device (block_until_
            # ready, device_get, np.asarray, scalar reads) — a blocking
            # call here would serialize the pipeline it exists to fill.
            nonlocal u, disp_done, next_guard, next_diag
            nonlocal prev_diag, prev_diag_step, idle_mark
            c = min(chunk, total - disp_done)
            ccfg = config.replace(steps=c)
            runner, _ = _build_runner(ccfg)
            compiled = _compiled_for(runner, ccfg, u)
            td0 = time.perf_counter()
            with jax.profiler.TraceAnnotation("heat:chunk"):
                grid, k, conv, res = compiled(u)
            dispatch_s = time.perf_counter() - td0
            gap_s = 0.0
            if idle_mark is not None:
                # Idle ends when the dispatch STARTS enqueuing (td0),
                # not when the call returns — counting dispatch_s too
                # would overstate the starvation lower bound.
                gap_s = max(0.0, td0 - idle_mark)
                idle_mark = None
            disp_done += c
            u = grid
            end = disp_done
            is_last = end >= total
            if is_last:
                keep = grid  # the final grid is never donated
            else:
                # Donation-protected copy, enqueued BEFORE the next
                # dispatch donates `grid`: the observers read it and
                # the caller receives it — bitwise the depth-1 loop's
                # boundary grid, and safe to consume at any time.
                keep = jnp.copy(grid)
            fin_dev = None
            if next_guard is not None and (end >= next_guard or is_last):
                fin_dev = _all_finite(keep)
                while next_guard <= end:
                    next_guard += guard_interval
            stats_dev = None
            steps_since = None
            if next_diag is not None and (end >= next_diag or is_last):
                stats_dev = _grid_stats_delta(keep, prev_diag)
                steps_since = end - prev_diag_step
                prev_diag, prev_diag_step = keep, end
                while next_diag <= end:
                    next_diag += diag_interval
            _start_host_copies(k, fin_dev, stats_dev)
            inflight.append((keep, k, fin_dev, stats_dev, steps_since,
                             c, dispatch_s, gap_s))

        while True:
            while len(inflight) < depth and disp_done < total:
                _dispatch()
            if not inflight:
                return
            (keep, k, fin_dev, stats_dev, steps_since, c,
             dispatch_s, gap_s) = inflight.popleft()
            tw0 = time.perf_counter()
            k = int(k)  # blocks until this chunk's program completed
            now = time.perf_counter()
            drain_wait_s = now - tw0
            chunk_wall = now - t_mark
            t_mark = now
            elapsed += chunk_wall
            done += k
            if inflight:
                probe = getattr(inflight[-1][1], "is_ready", None)
                if probe is not None and probe():
                    # The NEWEST dispatched chunk (and therefore every
                    # older one — the device queue is FIFO) already
                    # completed: the device is idle from this instant
                    # until the next dispatch. Mark it; _dispatch
                    # charges the window to the next chunk's gap_s.
                    idle_mark = now
            underrun = k < c
            finite: Optional[bool] = None
            if fin_dev is not None:
                finite = bool(fin_dev)
            elif underrun and next_guard is not None:
                # Defensive under-run (the fixed-step programs always
                # run exactly c steps): mirror the sync loop's is_last
                # rule — the stream must not END unguarded just because
                # the dispatch-time schedule could not see this was the
                # last chunk.
                finite = grid_all_finite(keep)
            if finite is False:
                _warn_guard_tripped(done)
            diag: Optional[dict] = None
            if stats_dev is not None:
                mn, mx, heat, l2, linf = stats_dev
                diag = {"min": float(mn), "max": float(mx),
                        "heat": float(heat), "update_l2": float(l2),
                        "update_linf": float(linf), "step": done,
                        "steps_since": steps_since}
            elif (underrun and next_diag is not None
                  and prev_diag_step <= done):
                # The is_last mirror for diagnostics (skipped only if
                # the dispatch-ahead already moved the baseline past
                # this chunk — a future-state baseline would be wrong).
                diag = grid_stats(keep, prev=prev_diag)
                diag["step"] = done
                diag["steps_since"] = done - prev_diag_step
            observe_s = time.perf_counter() - now
            if telemetry is not None:
                telemetry.chunk(step=done, steps=k, wall_s=chunk_wall,
                                cells=cells,
                                bytes_per_cell=bytes_per_cell,
                                residual=None, converged=None,
                                finite=finite, gap_s=gap_s,
                                dispatch_s=dispatch_s,
                                drain_wait_s=drain_wait_s,
                                observe_s=observe_s)
                _emit_profile(telemetry, prof_model, step=done,
                              steps=k, wall_s=chunk_wall, gap_s=gap_s)
                if diag is not None:
                    telemetry.diagnostics(**diag)
            yield HeatResult(grid=keep, steps_run=done, converged=None,
                             residual=None, elapsed_s=elapsed,
                             finite=finite, diagnostics=diag)
            if underrun:
                # The in-flight successors computed from a state the
                # host never certified; abandon them (their outputs
                # are simply dropped).
                return

    t_complete_prev = None
    while done < total:
        t_iter = time.perf_counter()
        # Host-side idle bracket (the observer/checkpoint/caller tax
        # between the previous chunk's completion and this dispatch) —
        # reported on the chunk event so tools/metrics_report.py can
        # price exactly what pipelining hides.
        gap_s = (t_iter - t_complete_prev
                 if t_complete_prev is not None else 0.0)
        c = min(chunk, total - done)
        ccfg = config.replace(steps=c)
        runner, _ = _build_runner(ccfg)
        compiled = _compiled_for(runner, ccfg, u)
        t0 = time.perf_counter()
        with jax.profiler.TraceAnnotation("heat:chunk"):
            grid, k, conv, res = compiled(u)
            jax.block_until_ready(grid)
        k = int(k)
        chunk_wall = time.perf_counter() - t0
        t_complete_prev = t0 + chunk_wall
        elapsed += chunk_wall
        done += k
        u = grid
        if config.converge:
            out_conv: Optional[bool] = bool(conv)
            out_res: Optional[float] = float(res)
        else:
            out_conv, out_res = None, None
        _warn_if_diverged(out_res, done, k >= config.check_interval)
        finite: Optional[bool] = None
        # Last yield of this stream? (all steps done, converged early,
        # or the defensive under-run below) — the guard must not leave
        # the FINAL grid unchecked just because the remaining steps
        # never reached the next boundary (solve() always checks its
        # end state; a short stream would otherwise be quietly
        # unguarded).
        is_last = (done >= total or bool(out_conv) or k < c)
        if next_guard is not None and (done >= next_guard or is_last):
            # First chunk boundary at-or-after the guard boundary: one
            # fused reduction, outside the timed bracket (the guard is
            # an observer, not part of the simulation).
            finite = grid_all_finite(grid)
            while next_guard <= done:
                next_guard += guard_interval
            if not finite:
                _warn_guard_tripped(done)
        diag: Optional[dict] = None
        if next_diag is not None and (done >= next_diag or is_last):
            # Same boundary rule as the guard: the first chunk boundary
            # at-or-after each diag_interval multiple, plus the final
            # chunk (a short stream must not end unsampled).
            diag = grid_stats(grid, prev=prev_diag)
            diag["step"] = done
            diag["steps_since"] = done - prev_diag_step
            prev_diag = jnp.copy(grid)  # next baseline (grid is donated)
            prev_diag_step = done
            while next_diag <= done:
                next_diag += diag_interval
            if config.scheme != "explicit":
                # Implicit runs: the V-cycle convergence sample rides
                # the diag cadence — an observation-only re-solve of
                # ONE step from this boundary's state (the yielded
                # trajectory never moves; SEMANTICS.md "Implicit
                # stepping"). The first sample of a stream also
                # carries the measured per-level wall shares.
                from parallel_heat_tpu.ops import multigrid

                vc = multigrid.cycle_trace(config, grid)
                if not vc_shares_sent:
                    vc["level_wall_share"] = {
                        f"l{i}": s for i, s in enumerate(
                            multigrid.level_wall_shares(config))}
                    vc_shares_sent = True
                diag["vcycle"] = vc
                if telemetry is not None:
                    telemetry.emit("vcycle", step=done, **vc)
        if telemetry is not None:
            observe_s = time.perf_counter() - t_complete_prev
            telemetry.chunk(step=done, steps=k, wall_s=chunk_wall,
                            cells=cells, bytes_per_cell=bytes_per_cell,
                            residual=out_res, converged=out_conv,
                            finite=finite, gap_s=gap_s,
                            observe_s=observe_s)
            _emit_profile(telemetry, prof_model, step=done, steps=k,
                          wall_s=chunk_wall, gap_s=gap_s)
            if diag is not None:
                telemetry.diagnostics(
                    **{**diag, "step": done})
        yield HeatResult(grid=grid, steps_run=done, converged=out_conv,
                         residual=out_res, elapsed_s=elapsed,
                         finite=finite, diagnostics=diag)
        if config.converge and out_conv:
            return
        if k < c:  # defensive: a chunk that under-ran without converging
            return


def solve(config: HeatConfig, initial: Optional[jax.Array] = None,
          block_until_ready: bool = True) -> HeatResult:
    """Run one simulation end-to-end. The main entry point.

    ``initial`` defaults to the model's polynomial initial condition.
    A caller-supplied ``initial`` is copied first: the compiled runner
    donates its input buffer (the double-buffer swap), which would
    otherwise invalidate the caller's array. Timing covers the step
    loop only — the program is AOT-compiled before the clock starts
    (``_compiled_for``), so ``elapsed_s`` never includes compile, cold
    or warm, matching the reference's wall-clock brackets around
    precompiled binaries (``cuda/cuda_heat.cu:203,239``).
    """
    config = config.validate()
    guard_interval = config.guard_interval
    diag_interval = config.diag_interval
    # solve is ONE compiled dispatch — there is no intermediate
    # boundary to observe (or to pipeline: pipeline_depth is inert
    # here), so the guard and diagnostics degrade to a single
    # end-of-run check/sample (use solve_stream or the supervisor
    # for within-run detection). Stripped from the config so compiled
    # programs are shared with (and bitwise identical to)
    # uninstrumented runs (see _observer_free).
    config = _observer_free(config)
    runner, _ = _build_runner(config)
    initial = _prepare_initial(config, initial)
    compiled = _compiled_for(runner, config, initial)
    diag_baseline = None
    if diag_interval is not None:
        # The runner donates `initial`; keep a copy as the end-of-run
        # update-residual baseline (initial -> final change).
        diag_baseline = jnp.copy(initial)

    t0 = time.perf_counter()
    with jax.profiler.TraceAnnotation("heat:solve"):
        grid, steps_run, converged, residual = compiled(initial)
        if block_until_ready:
            # One host-visible scalar read *is* the flush: on remote-TPU
            # transports (axon tunnel) block_until_ready returns at
            # dispatch, so reading a device value is the only way to
            # bracket completion. steps_run is scalar-replicated, so this
            # is a single-element transfer, not a grid gather.
            jax.block_until_ready(grid)
            steps_run = int(steps_run)
    elapsed = time.perf_counter() - t0

    if not block_until_ready:
        steps_run = int(steps_run)
    if config.converge:
        conv: Optional[bool] = bool(converged)
        res: Optional[float] = float(residual)
    else:
        conv, res = None, None
    _warn_if_diverged(res, steps_run,
                      config.converge
                      and steps_run >= config.check_interval)
    finite: Optional[bool] = None
    if guard_interval is not None:
        finite = grid_all_finite(grid)
        if not finite:
            _warn_guard_tripped(steps_run)
    diag: Optional[dict] = None
    if diag_interval is not None:
        diag = grid_stats(grid, prev=diag_baseline)
        diag["step"] = steps_run
        diag["steps_since"] = steps_run
        if config.scheme != "explicit":
            from parallel_heat_tpu.ops import multigrid

            diag["vcycle"] = multigrid.cycle_trace(config, grid)
    return HeatResult(grid=grid, steps_run=steps_run, converged=conv,
                      residual=res, elapsed_s=elapsed, finite=finite,
                      diagnostics=diag)
