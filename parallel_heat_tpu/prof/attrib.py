"""Attribution join: measured telemetry x static work model.

:func:`attribute_chunk` prices ONE chunk event against a work model:
achieved throughput, achieved-roofline fraction, and a named dominant
bound from the ``compute / hbm / ici / host`` taxonomy. The lane
accounting is deliberately simple and host-visible:

- ``host``: the chunk's ``gap_s`` (device idle charged to this chunk —
  the observer/checkpoint/caller tax the stream measured);
- ``ici``: measured ``exchange_s`` when the producer attributed one,
  else the model's predicted exchange share of the wall;
- the remaining device-busy wall goes to ``compute`` or ``hbm`` —
  whichever lane the model says is slower for this schedule.

The dominant bound is the largest lane. The roofline fraction is the
chunk's achieved Mcells*steps/s over the model's roofline rate — on
CPU this is honestly tiny (the peaks are the v5e row's; see
``prof.model``), which is why every alerting consumer treats it as a
relative series, never an absolute floor.

:func:`attribute_stream` folds a whole event stream: live-emitted
``profile`` events are used verbatim when present (they are the
producer's own join); otherwise chunks are re-attributed here against
the header's embedded ``work_model`` (or one rebuilt from the header
config — the degradation ladder is explicit in the output's
``degraded`` field, mirroring metrics_report's torn/foreign-line
contract: bad inputs degrade the report, they never throw).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from parallel_heat_tpu.prof.model import valid_model

# Schema of the `profile` telemetry event and of attribute_stream's
# document. Bump on any field rename/retype; consumers ignore unknown
# fields by the telemetry contract.
PROFILE_SCHEMA = 1


def attribute_chunk(chunk: dict, model: dict) -> dict:
    """One chunk event + one work model -> one profile segment."""
    wall = chunk.get("wall_s")
    steps = chunk.get("steps")
    wall = float(wall) if isinstance(wall, (int, float)) else 0.0
    steps = int(steps) if isinstance(steps, (int, float)) else 0

    seg = {
        "prof_schema": PROFILE_SCHEMA,
        "model_version": model.get("model_version"),
        "tune_key": model.get("tune_key"),
        "site": model.get("site"),
        "step": chunk.get("step"),
        "steps": steps,
        "wall_s": wall,
    }
    if wall <= 0 or steps <= 0:
        # Sub-resolution chunk: unmeasured, not wrong (same null
        # convention as the chunk event's own rates).
        seg.update(mcells_steps_per_s=None, roofline_frac=None,
                   bound=None, shares=None)
        return seg

    cells = model["cells"]
    mcells = cells * steps / wall / 1e6
    roof = model["roofline_mcells_steps_per_s"]

    gap = chunk.get("gap_s")
    host_s = float(gap) if isinstance(gap, (int, float)) else 0.0
    host_s = min(max(host_s, 0.0), wall)
    ex = chunk.get("exchange_s")
    if isinstance(ex, (int, float)):
        ici_s = min(max(float(ex), 0.0), wall - host_s)
    else:
        ici_s = min(model.get("t_ici_s", 0.0) * steps, wall - host_s)
    device_s = max(wall - host_s - ici_s, 0.0)
    device_lane = ("compute"
                   if model.get("t_compute_s", 0.0)
                   >= model.get("t_hbm_s", 0.0) else "hbm")
    shares = {"compute": 0.0, "hbm": 0.0, "ici": ici_s / wall,
              "host": host_s / wall}
    shares[device_lane] = device_s / wall
    bound = max(shares, key=lambda k: shares[k])
    seg.update(mcells_steps_per_s=mcells,
               roofline_frac=mcells / roof,
               bound=bound, shares=shares)
    return seg


def model_from_header(header: dict) -> Tuple[Optional[dict],
                                             Optional[str]]:
    """``(model, degraded_reason)`` from a run_header event.

    Ladder: the header's embedded ``explain.work_model`` (stamped by
    the producer — authoritative for the machine that ran); else a
    model rebuilt from the header's config on THIS machine (honest but
    re-resolved, flagged); else ``(None, reason)``.
    """
    ex = header.get("explain")
    if isinstance(ex, dict):
        m = valid_model(ex.get("work_model"))
        if m is not None:
            return m, None
    cfg_doc = header.get("config")
    if isinstance(cfg_doc, dict):
        try:
            import json

            from parallel_heat_tpu.config import HeatConfig
            from parallel_heat_tpu.prof.model import work_model

            m = work_model(HeatConfig.from_json(json.dumps(cfg_doc)))
            return m, "work model rebuilt from header config"
        except Exception as e:  # noqa: BLE001 — degrade, never throw
            return None, (f"work model unavailable "
                          f"({type(e).__name__}: {e})")
    return None, "run_header carries no work model and no config"


def _pct(sorted_vals: List[float], q: float) -> float:
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def attribute_stream(events: Sequence[dict],
                     model: Optional[dict] = None) -> dict:
    """Fold one telemetry event stream into an attribution document."""
    degraded: Optional[str] = None
    segments: List[dict] = []
    live_profile = False
    chunks: List[dict] = []
    totals = {"wall_s": 0.0, "steps": 0, "checkpoint_s": 0.0,
              "barrier_s": 0.0, "chunks": 0}
    model = valid_model(model)

    for e in events:
        if not isinstance(e, dict):
            continue
        ev = e.get("event")
        if ev == "run_header" and model is None:
            model, degraded = model_from_header(e)
        elif ev == "profile":
            live_profile = True
            segments.append(e)
        elif ev == "chunk":
            chunks.append(e)
            totals["chunks"] += 1
            w = e.get("wall_s")
            if isinstance(w, (int, float)):
                totals["wall_s"] += float(w)
            s = e.get("steps")
            if isinstance(s, (int, float)):
                totals["steps"] += int(s)
        elif ev in ("checkpoint_save",):
            w = e.get("wall_s")
            if isinstance(w, (int, float)):
                totals["checkpoint_s"] += float(w)
        elif ev in ("checkpoint_barrier", "barrier_wait"):
            w = e.get("wait_s")
            if isinstance(w, (int, float)):
                totals["barrier_s"] += float(w)

    if not live_profile and model is not None:
        segments = [attribute_chunk(c, model) for c in chunks]
    if not segments and model is None and degraded is None:
        degraded = "no run_header in stream"

    hist: dict = {}
    fracs: List[float] = []
    mcells: List[float] = []
    worst: Optional[dict] = None
    for seg in segments:
        b = seg.get("bound")
        if isinstance(b, str):
            hist[b] = hist.get(b, 0) + 1
        f = seg.get("roofline_frac")
        if isinstance(f, (int, float)):
            fracs.append(float(f))
            if worst is None or f < worst["roofline_frac"]:
                worst = {"step": seg.get("step"),
                         "roofline_frac": float(f),
                         "bound": seg.get("bound")}
        m = seg.get("mcells_steps_per_s")
        if isinstance(m, (int, float)):
            mcells.append(float(m))

    doc = {
        "schema": PROFILE_SCHEMA,
        "model": model,
        "degraded": degraded,
        "live_profile": live_profile,
        "segments": segments,
        "bound_histogram": hist,
        "totals": totals,
        "worst": worst,
    }
    if fracs:
        sf = sorted(fracs)
        doc["roofline_frac"] = {
            "mean": sum(sf) / len(sf), "min": sf[0], "max": sf[-1],
            "p50": _pct(sf, 0.50), "p90": _pct(sf, 0.90),
            "n": len(sf)}
    else:
        doc["roofline_frac"] = None
    if model is not None and mcells:
        measured = sum(mcells) / len(mcells)
        predicted = model["roofline_mcells_steps_per_s"]
        doc["model_vs_measured"] = {
            "predicted_mcells_steps_per_s": predicted,
            "measured_mean_mcells_steps_per_s": measured,
            "achieved_fraction": measured / predicted,
        }
    else:
        doc["model_vs_measured"] = None
    return doc
