"""heatprof — the roofline-attributed performance plane.

The stack can detect that a run is slow (``obs``'s ``perf_regression``
latch) but not say *why*: telemetry records walls, TuneDB records
winners, and the measured VPU roofline exists only as a standalone
study. This package is the join: a STATIC work model per resolved
schedule (:mod:`prof.model` — FLOPs/step, HBM bytes/step, ICI bytes
per exchange, derived from the config + the resolved path, priced
against :mod:`ops.tpu_params` peaks) folded against a run's MEASURED
telemetry stream (:mod:`prof.attrib` — per-chunk achieved throughput,
achieved-roofline fraction, and a named dominant bound from the
compute / hbm / ici / host taxonomy).

Everything here is host-side observation: the model is pure
arithmetic over an already-resolved config, the join is a pure fold
over already-emitted events, and neither touches a compiled program
(the ``tests/test_prof.py`` observation-only pin holds this to the
same contract as telemetry itself). Surfaces: ``solver.explain``'s
``work_model`` key, the schema-versioned ``profile`` telemetry event,
Perfetto counter tracks on the heattrace export, the
``roofline_frac`` series in ``obs``, and the ``tools/heatprof.py``
CLI.
"""

from parallel_heat_tpu.prof.attrib import (  # noqa: F401 — package API
    PROFILE_SCHEMA, attribute_chunk, attribute_stream,
    model_from_header)
from parallel_heat_tpu.prof.model import (  # noqa: F401 — package API
    BOUNDS, MODEL_VERSION, work_model)
