"""Static work model of one resolved schedule.

The model answers "how fast could this config possibly run on this
hardware" without running anything: per-step FLOPs and HBM traffic
from the grid geometry (the same ``profiling.cell_count`` /
``bytes_per_cell`` accounting the telemetry chunk events use — they
must never disagree), per-exchange ICI traffic from the halo depth and
the shard boundary extents, all priced against the
:mod:`ops.tpu_params` generation peaks. The slowest of the three
lanes is the roofline step time; its name is the PREDICTED bound.

Identity: the model is keyed by the same (site, topology, geometry)
content address TuneDB uses (``tune.tune_key``), so a measured row, a
tuned entry, and a work model for one decision context all carry the
same key and can be joined by content, not by path convention.

FLOP accounting matches ``tools/vpu_roofline.py``'s study: a 5-point
2D cell-step is 7 flops (3 mul + 4 add), the 3*ndim+1 generalization
gives the 3D 7-point star 10. The VPU peak in the table is the
sustained stencil rate in cells/s, so the compute lane is priced in
cells directly; the flop counts are carried for report readers.

On CPU ``tpu_params.params()`` deliberately falls back to the v5e row
(picker decisions stay identical to hardware), so a CPU run's
achieved-roofline fraction is honestly tiny (~1e-3). Consumers must
therefore treat roofline fractions as RELATIVE instruments — the
``efficiency_regression`` alert compares a window against the same
site's own history, never against an absolute floor.
"""

from __future__ import annotations

from typing import Optional

MODEL_VERSION = 1

# The bound taxonomy, in attribution-priority order (docs/
# OBSERVABILITY.md "Performance attribution" is the prose contract).
BOUNDS = ("compute", "hbm", "ici", "host")


def _flops_per_cell(ndim: int) -> int:
    """Per cell-step flops of the ndim-dimensional star stencil:
    ndim axis contributions (1 mul + 1 add each) + center (1 mul) +
    (ndim - 1) adds folding the axes + 1 add into the center
    = 3*ndim + 1 (2D 5-point: 7; 3D 7-point: 10)."""
    return 3 * int(ndim) + 1


def work_model(config, *, resolved: bool = False) -> dict:
    """The static work model for one config, as a plain JSON-safe dict.

    ``resolved=True`` promises the caller already ran the config
    through ``solver._resolved`` (explain's body does); otherwise the
    auto depth/schedule are concretized here through the same resolver
    the build uses, so the modeled exchange traffic can never describe
    a different schedule than the one that runs. Pure host arithmetic
    after resolution — nothing is compiled or dispatched.
    """
    import jax.numpy as jnp

    from parallel_heat_tpu import tune
    from parallel_heat_tpu.ops import tpu_params
    from parallel_heat_tpu.utils import profiling

    config = config.validate()
    if not resolved:
        from parallel_heat_tpu.solver import _resolved

        config, _, _ = _resolved(config)

    mesh = config.mesh_or_unit()
    is_sharded = any(d > 1 for d in mesh)
    n_shards = 1
    for d in mesh:
        n_shards *= int(d)

    cells = profiling.cell_count(config)
    bpc = profiling.bytes_per_cell(config)
    itemsize = int(jnp.dtype(config.dtype).itemsize)
    flops_cell = _flops_per_cell(config.ndim)

    # --- identity: the TuneDB content address for this context -------
    if config.scheme != "explicit":
        site = "mg_partition" if is_sharded else "single_2d"
    else:
        site = "halo_overlap" if is_sharded else "single_2d"
    topology = tune.current_topology()
    geometry = tune.geometry_for(site, config)
    key, _ = tune.tune_key(site, topology, geometry)

    # --- per-step work ----------------------------------------------
    flops_per_step = flops_cell * cells
    hbm_bytes_per_step = cells * bpc

    # --- per-exchange ICI traffic (sharded explicit runs only) ------
    # One exchange round per halo_depth steps; per device the round
    # moves, for each partitioned axis, two directions x depth x the
    # local boundary slab x itemsize (matches the temporal rounds'
    # ppermute payloads; the deferred/pipelined schedules move the
    # same bytes, just overlapped).
    depth = config.halo_depth if config.scheme == "explicit" else 1
    depth = int(depth) if depth else 1
    block = config.block_shape()
    ici_bytes_per_exchange = 0
    if is_sharded:
        for ax, d in enumerate(mesh):
            if d <= 1:
                continue
            slab = 1
            for j, b in enumerate(block):
                if j != ax:
                    slab *= int(b)
            ici_bytes_per_exchange += 2 * depth * slab * itemsize
    exchanges_per_step = (1.0 / depth) if is_sharded else 0.0

    # --- roofline lanes (whole-grid rates: per-device peaks scale by
    # the shard count — HBM and VPU are per-chip resources, ICI is
    # per-link and every shard exchanges concurrently) ---------------
    p = tpu_params.params()
    mg = None
    if config.scheme != "explicit":
        # --- implicit: per-level V-cycle lanes ----------------------
        # The work unit is ONE V-cycle (cycles per step are a runtime
        # quantity — the vcycle telemetry event measures them); every
        # level is carried in f32 regardless of storage dtype. Per
        # level: 2*mg_smooth Jacobi sweeps (pre+post; the coarsest
        # runs mg_smooth + _COARSE_SWEEPS), each sweep streaming
        # u-read + b-read + u-write (12 B/cell f32). Partitioned
        # levels (mg_partition; ops/multigrid_sharded.py) divide
        # compute/HBM by the shard count and pay one 1-deep exchange
        # per sweep plus two extras per non-coarsest level (the
        # pre-restriction residual exchange and the restrict/prolong
        # seam shifts); replicated levels run full-shape on EVERY
        # device — divisor 1, the honest zero-speedup accounting.
        from parallel_heat_tpu.config import multigrid_level_shapes
        from parallel_heat_tpu.ops.multigrid import _COARSE_SWEEPS

        nu = int(config.mg_smooth)
        shapes = multigrid_level_shapes(config.shape, config.mg_levels)
        n_levels = len(shapes)
        k_part = 0
        blocks = None
        if is_sharded and config.mg_partition == "partitioned":
            from parallel_heat_tpu.ops import multigrid_sharded

            plan = multigrid_sharded.partition_plan(
                config, min_partitioned=1)
            k_part = plan["partitioned_levels"]
            blocks = [lv.get("block_shape") for lv in plan["levels"]]
        level_cells = [(s[0] - 2) * (s[1] - 2) for s in shapes]
        sweeps = [2 * nu if l < n_levels - 1 else nu + _COARSE_SWEEPS
                  for l in range(n_levels)]
        t_compute = t_hbm = t_ici = 0.0
        mg_hbm = mg_ici = 0
        exchanges = 0
        for l in range(n_levels):
            div = n_shards if l < k_part else 1
            t_compute += level_cells[l] * sweeps[l] / (
                p.vpu_cells_per_s * div)
            lvl_hbm = level_cells[l] * sweeps[l] * 12
            mg_hbm += lvl_hbm
            t_hbm += lvl_hbm / (p.hbm_stream_bytes_per_s * div)
            if l < k_part:
                perim = 0
                for ax, d in enumerate(mesh):
                    if d <= 1:
                        continue
                    slab = 1
                    for j, b in enumerate(blocks[l]):
                        if j != ax:
                            slab *= int(b)
                    perim += 2 * slab * 4
                n_ex = sweeps[l] + (2 if l < n_levels - 1 else 0)
                exchanges += n_ex
                lvl_ici = n_ex * perim
                mg_ici += lvl_ici
                t_ici += (lvl_ici / p.ici_bytes_per_s
                          + n_ex * 2.0 * p.collective_latency_s)
        mg = {
            "work_unit": "vcycle",
            "mg_partition": (config.mg_partition if is_sharded
                             else None),
            "n_levels": n_levels,
            "partitioned_levels": k_part,
            "level_cells": level_cells,
            "sweeps_per_cycle": sweeps,
            "hbm_bytes_per_cycle": int(mg_hbm),
            "ici_bytes_per_cycle": int(mg_ici),
            "exchanges_per_cycle": int(exchanges),
        }
    else:
        t_compute = cells / (p.vpu_cells_per_s * n_shards)
        t_hbm = hbm_bytes_per_step / (
            p.hbm_stream_bytes_per_s * n_shards)
        t_ici = 0.0
        if is_sharded:
            t_ici = exchanges_per_step * (
                ici_bytes_per_exchange / p.ici_bytes_per_s
                + p.collective_latency_s)
    step_time = max(t_compute, t_hbm, t_ici)
    lanes = {"compute": t_compute, "hbm": t_hbm, "ici": t_ici}
    predicted = max(lanes, key=lambda k: lanes[k])

    return {
        "model_version": MODEL_VERSION,
        "site": site,
        "tune_key": key,
        "topology": topology,
        "geometry": geometry,
        "scheme": str(config.scheme),
        "ndim": int(config.ndim),
        "cells": int(cells),
        "n_shards": n_shards,
        "bytes_per_cell": int(bpc),
        "flops_per_cell": flops_cell,
        "flops_per_step": int(flops_per_step),
        "hbm_bytes_per_step": int(hbm_bytes_per_step),
        "halo_depth": depth if is_sharded else None,
        "ici_bytes_per_exchange": int(ici_bytes_per_exchange),
        "exchanges_per_step": exchanges_per_step,
        "device_kind": p.kind,
        "peaks": {
            "vpu_cells_per_s": p.vpu_cells_per_s,
            "hbm_stream_bytes_per_s": p.hbm_stream_bytes_per_s,
            "ici_bytes_per_s": p.ici_bytes_per_s,
            "collective_latency_s": p.collective_latency_s,
        },
        "t_compute_s": t_compute,
        "t_hbm_s": t_hbm,
        "t_ici_s": t_ici,
        "step_time_s": step_time,
        "predicted_bound": predicted,
        "roofline_steps_per_s": 1.0 / step_time,
        "roofline_mcells_steps_per_s": cells / step_time / 1e6,
        # Implicit-only: the per-level V-cycle lane decomposition
        # (None for the explicit scheme). When present, the lane
        # times above are per V-CYCLE, not per step — see the mg
        # block comment.
        "mg": mg,
    }


def valid_model(doc) -> Optional[dict]:
    """``doc`` if it is a usable work-model dict (version we can read,
    positive roofline), else ``None`` — the one acceptance gate every
    consumer (attribution, monitor, bench stamping) shares."""
    if not isinstance(doc, dict):
        return None
    if doc.get("model_version") != MODEL_VERSION:
        return None
    roof = doc.get("roofline_mcells_steps_per_s")
    if not isinstance(roof, (int, float)) or not roof > 0:
        return None
    return doc
