"""Run supervisor: the fault-tolerant long-run driver.

The reference has no failure handling at all (SURVEY.md §5: "Failure
detection: none", "Checkpoint/resume: none") — a blown-up run burns its
whole budget on garbage, a preemption loses everything since launch.
Production TPU simulation stacks treat the opposite as table stakes:
a compiled inner loop bracketed by periodic guarded checkpointing is
exactly the run-loop shape of the TPU CFD framework (arXiv:2108.11076)
and the long-campaign Ising driver (arXiv:1903.11714). This module
wires that shape around :func:`solver.solve_stream`:

- **guard**: the on-device isfinite-all reduction
  (:func:`solver.grid_all_finite`) runs at a configurable step cadence
  AND before every checkpoint save — retained snapshots are
  finite-verified by construction, so rollback targets are always good;
- **checkpoint loop**: ``utils.checkpoint.save_generation`` keeps the
  newest N generations (each individually crash-atomic), pruning older
  ones; a kill between a sharded generation's shard write and its
  manifest write leaves the previous generation discoverable
  (``latest_checkpoint`` only sees COMPLETE saves — chaos-tested);
- **preemption**: SIGTERM/SIGINT handlers set a flag (nothing else —
  async-signal-safe); the loop notices at the next chunk boundary,
  flushes a final checkpoint, and returns an ``interrupted`` result
  carrying the exact resume command;
- **retry-with-rollback**: a tripped guard or a transient dispatch
  error rolls back to the newest retained generation and retries with
  bounded exponential backoff; deterministic failures (stability-bound
  violation) and exhausted budgets raise :class:`PermanentFailure`
  with a diagnosis naming the first bad chunk;
- **progress guard** (:class:`SupervisorPolicy` ``stall_windows`` /
  ``drift_tolerance``): the failure modes the NaN guard cannot see.
  A converge run whose residual sets no new minimum across K chunk
  windows is classified STALLED (``PermanentFailure(kind="stalled")``
  — replaying a deterministic plateau cannot help; the classic cause
  is eps below the storage dtype's reachable floor). A grid whose
  min/max/total-heat-content escapes the initial envelope (the
  explicit scheme's maximum principle) trips a retryable ``drift``
  rollback — finite corruption, the isfinite-invisible analog of a
  NaN trip. Both ride :func:`solver.grid_stats`, the same fused
  observation-only reduction ``HeatConfig.diag_interval`` samples.

- **distributed supervision** (``parallel/coordinator.py``,
  SEMANTICS.md "Distributed supervision"): on a multi-process
  ``shard_map`` run every boundary verdict above — guard, drift, stop
  flags, transient faults — is exchanged over the ``jax.distributed``
  KV store and merged deterministically, so every process takes the
  identical action at the identical chunk boundary (one rank rolling
  back alone would wedge the pod inside a collective); checkpoint
  generations commit through the two-phase
  ``save_generation_coordinated`` protocol; and a dead peer is
  detected by its static heartbeat within one bounded barrier timeout
  — the survivors exit ``EXIT_PREEMPTED`` with an ELASTIC resume
  command for the surviving mesh instead of hanging in ``ppermute``
  forever. Single-process, the coordinator is the identity and this
  module's behavior is bitwise the pre-coordinator one.

Everything here is observation + orchestration on the host side of
chunk boundaries: the compiled simulation programs are bit-for-bit the
ones an unsupervised run uses (SEMANTICS.md "Runtime guard and
supervisor"), so a recovered or resumed run reproduces the
uninterrupted run exactly (chaos-tested bitwise on the jnp backend).
"""

from __future__ import annotations

import contextlib
import math
import os
import shlex
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

from parallel_heat_tpu.config import HeatConfig
from parallel_heat_tpu.parallel import coordinator as coordination
from parallel_heat_tpu.solver import (
    HeatResult,
    _prepare_initial,
    grid_all_finite,
    grid_stats,
    resolved_pipeline_depth,
    solve_stream,
)
from parallel_heat_tpu.utils import checkpoint as ckpt
from parallel_heat_tpu.utils.faults import InjectedTransientError

# Process exit codes of supervised CLI runs (one vocabulary for the
# CLI, restart loops, and the test suite — no magic numbers):
# EXIT_PREEMPTED: a SIGTERM/SIGINT arrived; a final checkpoint was
# flushed and the printed resume command continues the run.
# EXIT_PERMANENT_FAILURE: retrying cannot help (stability-bound
# violation, exhausted retry budget); diagnosis on stderr.
EXIT_PREEMPTED = 3
EXIT_PERMANENT_FAILURE = 4


def default_checkpoint_every(config) -> int:
    """The default supervised checkpoint cadence (one tenth of the
    run), rounded UP to the f32chunk sublane multiple when that
    accumulate mode is active — the supervisor's K-alignment
    requirement (stream boundaries are rounding points, SEMANTICS.md).
    THE shared rule for every caller that supervises without an
    explicit --checkpoint-every: the solver CLI and service workers
    must not drift apart on it."""
    every = max(1, config.steps // 10)
    if config.accumulate == "f32chunk":
        from parallel_heat_tpu.config import sublane_count

        sub = sublane_count(config.dtype)
        every = ((every + sub - 1) // sub) * sub
    return every


class PermanentFailure(RuntimeError):
    """A failure retrying cannot fix; ``.diagnosis`` says what, where,
    and what to do about it. ``.kind`` classifies the verdict:
    ``"unstable"`` (stability-bound violation), ``"stalled"`` (the
    progress guard: residual stopped improving in converge mode),
    ``"drift"`` (heat-content drift persisted through retries),
    ``"exhausted"`` (retry budget spent on a recurring fault)."""

    def __init__(self, diagnosis: str, kind: str = "exhausted"):
        super().__init__(diagnosis)
        self.diagnosis = diagnosis
        self.kind = kind


class _GuardTrip(Exception):
    """Internal: a runtime guard fired. ``window`` is the
    (last_known_good_step, detected_step] chunk the corruption landed
    in; ``kind`` is ``"nan"`` (the isfinite guard) or ``"drift"`` (the
    progress guard's heat-content envelope — finite but unphysical
    values the NaN guard is blind to)."""

    def __init__(self, window: Tuple[int, int], kind: str = "nan"):
        super().__init__(f"{kind} guard tripped in steps {window}")
        self.window = window
        self.kind = kind


@dataclass
class SupervisorPolicy:
    """Knobs of the supervised run loop (all host-side; none affect
    simulation numerics)."""

    # Steps between retained checkpoint generations.
    checkpoint_every: int = 1000
    # Retained generations; older ones are pruned after each save.
    keep_checkpoints: int = 3
    # Steps between guard checks BETWEEN checkpoints. None: guard runs
    # only at checkpoint boundaries (every save is finite-verified
    # either way). The effective dispatch chunk is
    # gcd(checkpoint_every, guard_interval) so both schedules land on
    # exact chunk boundaries.
    guard_interval: Optional[int] = None
    # Rollback-retry budget for transient faults; exceeding it raises
    # PermanentFailure.
    max_retries: int = 3
    # Bounded exponential backoff between retries:
    # min(backoff_max_s, backoff_base_s * 2**(retry-1)).
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0
    # Checkpoint layout / compression, passed through to save_generation.
    layout: str = "auto"
    compress: bool = False
    # Asynchronous checkpointing (default on): saves run through
    # utils.checkpoint.AsyncCheckpointer — a donation-protected device
    # copy is enqueued at the boundary, the gather + finite-verify +
    # atomic commit happen on a worker thread while the next chunks
    # compute, and every rollback/interrupt/exit DRAINS in-flight saves
    # first (the barrier: a rollback can never restore an uncommitted
    # generation). Committed bytes are identical to synchronous saves;
    # False restores the fully synchronous save-at-the-boundary loop.
    async_checkpoint: bool = True
    # Progress guard, converge mode: classify the run as STALLED (a
    # PermanentFailure with kind="stalled" — retrying a deterministic
    # plateau cannot help) after this many consecutive chunk residual
    # observations without a new minimum. None = off. The classic
    # pathology: eps set below the storage dtype's reachable floor, the
    # iteration enters a rounding limit cycle and burns its whole step
    # budget at a flat residual (observed: f32 plateaus at 2^-15 against
    # eps=1e-6).
    stall_windows: Optional[int] = None
    # Progress guard, any mode: tolerance of the two physics bounds
    # checked at guard boundaries with the same fused stats reduction
    # diagnostics use — (1) grid extrema confined to the initial
    # envelope (maximum principle: with sum(c) <= 1/2 every update is
    # a convex combination, so values can never leave the
    # initial+boundary range), and (2) total heat content changing no
    # faster than the boundary-flux rate bound (region-scale
    # corruption inside the envelope still jumps heat unphysically).
    # A violation means corruption or a boundary bug the isfinite
    # guard cannot see; it is a retryable guard trip with
    # kind="drift". None = off.
    drift_tolerance: Optional[float] = None
    # Multi-process (SPMD) supervision — parallel/coordinator.py.
    # barrier_timeout_s bounds every chunk-boundary consensus exchange:
    # a peer whose heartbeat stops CHANGING for this long is declared
    # lost (PeerLostError -> a clean peer_lost preemption with an
    # elastic resume command) instead of wedging the pod inside a
    # collective. peer_heartbeat_s is the background beat cadence (KV
    # key + the <stem>.hb.pN.json probe file the stem lock's reclaim
    # judgment reads); it must be well under barrier_timeout_s so a
    # slow-but-alive peer keeps proving liveness. Single-process runs
    # never touch either.
    barrier_timeout_s: float = 60.0
    peer_heartbeat_s: float = 0.5
    # Injectable time sources. `sleep_fn` receives every backoff delay
    # (the bounded-exponential schedule above): tests pin the schedule
    # by recording calls instead of sleeping wall-clock, and service
    # workers can interleave housekeeping with the wait. `clock` is the
    # monotonic wall-second source for wall_s/latency bookkeeping
    # (observation only — never simulation numerics).
    sleep_fn: Callable[[float], None] = field(default=time.sleep)
    clock: Callable[[], float] = field(default=time.perf_counter)

    def validate(self) -> "SupervisorPolicy":
        if self.checkpoint_every < 1:
            raise ValueError(f"checkpoint_every must be >= 1, got "
                             f"{self.checkpoint_every}")
        if self.keep_checkpoints < 1:
            raise ValueError(f"keep_checkpoints must be >= 1, got "
                             f"{self.keep_checkpoints}")
        if self.guard_interval is not None and self.guard_interval < 1:
            raise ValueError(f"guard_interval must be >= 1, got "
                             f"{self.guard_interval}")
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got "
                             f"{self.max_retries}")
        if self.stall_windows is not None and self.stall_windows < 1:
            raise ValueError(f"stall_windows must be >= 1 (or None to "
                             f"disable the stall classifier), got "
                             f"{self.stall_windows}")
        if self.drift_tolerance is not None and self.drift_tolerance < 0:
            raise ValueError(f"drift_tolerance must be >= 0 (or None to "
                             f"disable the drift guard), got "
                             f"{self.drift_tolerance}")
        if self.barrier_timeout_s <= 0:
            raise ValueError(f"barrier_timeout_s must be > 0, got "
                             f"{self.barrier_timeout_s}")
        if not 0 < self.peer_heartbeat_s <= self.barrier_timeout_s:
            raise ValueError(
                f"peer_heartbeat_s must be in (0, barrier_timeout_s="
                f"{self.barrier_timeout_s:g}], got "
                f"{self.peer_heartbeat_s} — a beat slower than the "
                f"barrier timeout would declare live peers dead")
        return self


@dataclass
class SupervisorResult:
    """Outcome of one supervised invocation."""

    # Final simulation result (None when the run was interrupted before
    # any chunk, or config.steps == 0). `steps_run`/converged/residual
    # are the LAST stream's view; `steps_done` below is authoritative.
    result: Optional[HeatResult]
    # Absolute step count the newest checkpoint (and `result.grid`)
    # corresponds to.
    steps_done: int
    # True: a SIGTERM/SIGINT arrived; a final checkpoint was flushed and
    # `resume_command` reproduces the run.
    interrupted: bool
    retries: int
    rollbacks: int
    guard_trips: int
    # Absolute steps at which the guard detected non-finite values.
    guard_trip_steps: Tuple[int, ...]
    checkpoints_written: int
    last_checkpoint: Optional[str]
    resume_command: Optional[str]
    # Signal name when interrupted ("SIGTERM"/"SIGINT"), else None.
    signal_name: Optional[str] = None
    wall_s: float = 0.0
    # Progress-guard trips (stall classifications + drift detections)
    # observed by this invocation.
    progress_trips: int = 0


class _StopFlag:
    __slots__ = ("signum",)

    def __init__(self):
        self.signum: Optional[int] = None


@contextlib.contextmanager
def _saver_cleanup(saver):
    """Close a supervisor-owned AsyncCheckpointer on every exit path
    (worker thread + queue cleanup); pass None for caller-owned savers
    — they are drained at barriers but never closed here. Close errors
    are swallowed: cleanup must not mask the run's own outcome."""
    try:
        yield
    finally:
        if saver is not None:
            try:
                saver.close()
            except Exception:  # noqa: BLE001
                pass


@contextlib.contextmanager
def _signal_handlers(flag: _StopFlag):
    """Install SIGTERM/SIGINT handlers that ONLY set a flag (the whole
    body is one attribute store — async-signal-safe; all real work
    happens at the next chunk boundary). Restores previous handlers on
    exit. Outside the main thread (where Python forbids signal.signal)
    the run proceeds unguarded — preemption then behaves like the
    unsupervised baseline."""
    def handler(signum, frame):
        flag.signum = signum

    prev = {}
    try:
        for s in (signal.SIGTERM, signal.SIGINT):
            prev[s] = signal.signal(s, handler)
    except ValueError:  # not the main thread
        prev = {}
    try:
        yield
    finally:
        for s, h in prev.items():
            signal.signal(s, h)


def _is_transient_dispatch_error(e: BaseException) -> bool:
    """Conservative transient classifier for real runtime errors: only
    status strings the TPU runtime uses for go-away-and-retry
    conditions. Anything else (shape errors, OOM-by-construction,
    compile failures) re-raises — retrying deterministic bugs would
    just burn the budget."""
    if isinstance(e, InjectedTransientError):
        return True
    if type(e).__name__ not in ("XlaRuntimeError", "JaxRuntimeError"):
        return False
    msg = str(e)
    return any(tok in msg for tok in
               ("UNAVAILABLE", "ABORTED", "preempt", "Socket closed",
                "connection reset"))


_KEEP_MESH = object()  # _resume_command sentinel: keep config.mesh_shape


def _resume_command(config: HeatConfig, stem: str, total_abs: int,
                    policy: SupervisorPolicy,
                    extra_flags: Tuple[str, ...] = (),
                    mesh_override=_KEEP_MESH) -> str:
    """The exact CLI line that continues this run from its newest
    checkpoint (printed on preemption; also in SupervisorResult).
    ``extra_flags`` carries caller flags the config doesn't know about
    (the CLI's --out/--initial-out etc.) so the resumed run still
    delivers everything the original invocation asked for.
    ``mesh_override`` (a tuple or None) replaces the config's mesh in
    the printed line — the elastic-degrade path: a peer-lost exit
    prints a mesh the SURVIVING hosts can actually build, resuming
    through the checkpoint reshard-on-load path."""
    parts = ["python -m parallel_heat_tpu",
             f"--nx {config.nx}", f"--ny {config.ny}"]
    if config.nz is not None:
        parts.append(f"--nz {config.nz}")
    parts.append(f"--steps {total_abs}")
    if config.converge:
        parts += ["--converge", f"--eps {config.eps:g}",
                  f"--check-interval {config.check_interval}"]
    for flag, val, default in (("--cx", config.cx, 0.1),
                               ("--cy", config.cy, 0.1)):
        if val != default:
            parts.append(f"{flag} {val:g}")
    if config.nz is not None and config.cz != 0.1:
        parts.append(f"--cz {config.cz:g}")
    if config.dtype != "float32":
        parts.append(f"--dtype {config.dtype}")
    if config.backend != "auto":
        parts.append(f"--backend {config.backend}")
    mesh = (config.mesh_shape if mesh_override is _KEEP_MESH
            else mesh_override)
    if mesh is not None:
        parts.append("--mesh " + ",".join(map(str, mesh)))
    if config.halo_depth is not None:
        parts.append(f"--halo-depth {config.halo_depth}")
    if config.halo_overlap not in (None, "auto"):
        parts.append(f"--halo-overlap {config.halo_overlap}")
    if not config.overlap:
        parts.append("--no-overlap")
    if config.accumulate != "storage":
        parts.append(f"--accumulate {config.accumulate}")
    if config.scheme != "explicit":
        # SEMANTIC like everything above it: dropping --scheme would
        # resume an implicit checkpoint as an EXPLICIT run — at the
        # super-stability coefficients implicit runs exist for, a
        # deterministic blow-up (and at any coefficients a different
        # trajectory, breaking the resume-bitwise contract).
        parts.append(f"--scheme {config.scheme}")
        defaults = HeatConfig()
        for flag, val, default in (
                ("--mg-tol", config.mg_tol, defaults.mg_tol),
                ("--mg-cycles", config.mg_cycles, defaults.mg_cycles),
                ("--mg-smooth", config.mg_smooth, defaults.mg_smooth),
                ("--mg-levels", config.mg_levels, defaults.mg_levels),
                ("--mg-partition", config.mg_partition,
                 defaults.mg_partition)):
            if val != default:
                parts.append(f"{flag} {val:g}" if isinstance(val, float)
                             else f"{flag} {val}")
    parts += ["--supervise", f"--checkpoint {shlex.quote(stem)}",
              f"--checkpoint-every {policy.checkpoint_every}",
              f"--keep-checkpoints {policy.keep_checkpoints}",
              f"--max-retries {policy.max_retries}"]
    if policy.guard_interval is not None:
        parts.append(f"--guard-interval {policy.guard_interval}")
    if policy.barrier_timeout_s != 60.0:
        parts.append(f"--barrier-timeout {policy.barrier_timeout_s:g}")
    if config.diag_interval is not None:
        parts.append(f"--diag-interval {config.diag_interval}")
    if config.pipeline_depth is not None:
        parts.append(f"--pipeline-depth {config.pipeline_depth}")
    if policy.stall_windows is not None:
        parts.append(f"--stall-windows {policy.stall_windows}")
    if policy.drift_tolerance is not None:
        parts.append(f"--drift-tolerance {policy.drift_tolerance:g}")
    if policy.layout != "auto":
        parts.append(f"--checkpoint-layout {policy.layout}")
    if not policy.async_checkpoint:
        parts.append("--no-async-checkpoint")
    # Caller flags may carry paths ("--out", "my out.npy"): quote each
    # token so the printed line survives a shell round trip verbatim.
    parts.extend(shlex.quote(t) for t in extra_flags)
    parts.append("--resume auto")
    return " ".join(parts)


def run_supervised(config: HeatConfig, checkpoint,
                   policy: Optional[SupervisorPolicy] = None,
                   initial=None, start_step: int = 0,
                   faults=None, say=None,
                   resume_extra_flags: Tuple[str, ...] = (),
                   telemetry=None, checkpointer=None,
                   interrupt=None, coordinator=None) -> SupervisorResult:
    """Run ``config.steps`` more steps under supervision (guard +
    retained checkpoints + retry-with-rollback + preemption-safe exit).

    ``config.steps`` counts steps REMAINING for this invocation (the
    same convention the CLI's ``--resume`` reduction uses);
    ``start_step`` is the absolute step ``initial`` corresponds to, so
    checkpoint generations are stamped with absolute steps and a
    resumed invocation continues the same generation family.
    ``faults`` (a :class:`utils.faults.FaultPlan`) is the chaos-test
    hook; production runs pass None and pay only the guard reduction
    plus checkpoint I/O. ``telemetry`` (a
    :class:`utils.telemetry.Telemetry`) receives the run header, every
    stream chunk, checkpoint save/load latencies, and each lifecycle
    event (guard_trip / retry / rollback / signal / permanent_failure
    / run_end) — host-side observation only, per the guard's contract.
    ``checkpointer`` (a :class:`utils.checkpoint.AsyncCheckpointer`)
    overrides the policy-built async saver — the chaos harness injects
    throttled ones to widen the in-flight window; a caller-supplied
    checkpointer is drained at every barrier but NOT closed here.
    ``interrupt`` (optional zero-argument callable) is the flag-only
    interrupt hook: polled at exactly the chunk boundaries the signal
    flag is, a truthy return (a short reason string, e.g. "deadline")
    triggers the same checkpoint-flush-and-exit path a SIGTERM does,
    with the reason in ``SupervisorResult.signal_name`` — how service
    workers enforce per-job deadlines and cancellation without a
    second signal vocabulary.

    ``coordinator`` (a :class:`parallel_heat_tpu.parallel.coordinator.
    Coordinator`) is the multi-process consensus layer; by default one
    is built automatically — the identity coordinator single-process
    (behavior bitwise the pre-coordinator supervisor), a KV-store
    coordinator when this runtime is part of a ``jax.distributed``
    job. With a distributed coordinator every chunk-boundary verdict
    (guard/drift/stop/transient) and the retry/rollback/halt decision
    is a CONSENSUS, checkpoint generations commit through the
    two-phase protocol, and a dead peer surfaces as a bounded
    ``peer_lost`` preemption carrying an elastic resume command
    (SEMANTICS.md "Distributed supervision"). Tests inject
    thread-simulated coordinators here; a caller-supplied coordinator
    is never closed by the supervisor.

    The run holds an exclusive lock on the checkpoint stem
    (``utils.checkpoint.acquire_stem_lock``): two supervised runs
    sharing a stem would prune and roll back to each other's
    generations, so the second raises
    :class:`utils.checkpoint.StemLockError` at startup instead. A
    stale lock (the holder pid is dead — SIGKILL/OOM) is reclaimed
    automatically; multi-process SPMD runs are one logical run whose
    lock is held by process 0 FOR all ranks, with reclaim additionally
    gated on the run's per-rank coordinator heartbeats — a crashed
    process 0 with live peers keeps the stem locked until those peers'
    own peer-lost exit stops their beats.

    Raises :class:`PermanentFailure` for non-retryable failures; the
    last retained checkpoint still holds the newest verified-good
    state.
    """
    policy = (policy or SupervisorPolicy()).validate()
    stem = ckpt.checkpoint_stem(checkpoint)
    coord = coordinator
    own_coord = False
    if coord is None:
        # NOTE: no heartbeat probe file yet — it is enabled only after
        # the stem lock is held. The probe files feed the lock's
        # stale-reclaim judgment, and a restarting run writing its own
        # <stem>.hb.pN.json first would block reclaim of its
        # predecessor's stale lock forever (identical file names
        # across runs).
        coord = coordination.distributed_coordinator(
            namespace=f"heatsup:{os.path.basename(stem)}:{start_step}",
            barrier_timeout_s=policy.barrier_timeout_s,
            heartbeat_interval_s=policy.peer_heartbeat_s)
        own_coord = True
    release_stem = None
    try:
        lock_err = None
        if coord.process_index == 0:
            try:
                release_stem = ckpt.acquire_stem_lock(
                    stem,
                    heartbeat_glob=(f"{stem}.hb.p*.json"
                                    if coord.distributed else None),
                    heartbeat_timeout_s=(3 * policy.barrier_timeout_s
                                         if coord.distributed
                                         else None))
            except ckpt.StemLockError as e:
                lock_err = str(e)
                if not coord.distributed:
                    raise
        if coord.distributed:
            # Startup consensus: every rank must learn rank 0's lock
            # verdict — a rank proceeding while rank 0 bailed would
            # wait a whole barrier timeout to find out the hard way.
            verdicts = coord.exchange("startup", {"lock": lock_err})
            if verdicts[0].get("lock") is not None:
                raise ckpt.StemLockError(verdicts[0]["lock"])
            # Lock held (by rank 0, for everyone): NOW the per-rank
            # probe files may exist — they extend the lock's life past
            # a dead rank 0, never block a fresh acquisition.
            if getattr(coord, "heartbeat_path", None) is None:
                coord.set_heartbeat_path(
                    coordination.heartbeat_path_for(
                        stem, coord.process_index))
        return _run_supervised(
            config, checkpoint, policy=policy, initial=initial,
            start_step=start_step, faults=faults, say=say,
            resume_extra_flags=resume_extra_flags, telemetry=telemetry,
            checkpointer=checkpointer, interrupt=interrupt,
            coordinator=coord)
    finally:
        if release_stem is not None:
            release_stem()
        if own_coord:
            coord.close()


def _local_shard_stats(grid) -> dict:
    """Host-side partial grid stats over THIS process's addressable
    shards (min/max/heat) — the distributed drift guard's input to
    ``coordinator.merge_stats``. Never a device collective: a verdict
    must be formable even when a peer is gone. f64 host accumulation
    (the drift bounds carry slack; exactness is not required,
    determinism is — numpy reductions are)."""
    import numpy as np

    shards = getattr(grid, "addressable_shards", None)
    if shards is None:
        arrs = [np.asarray(grid)]
    else:
        arrs = [np.asarray(s.data) for s in shards]
    return {"min": float(min(a.min() for a in arrs)),
            "max": float(max(a.max() for a in arrs)),
            "heat": float(sum(a.sum(dtype=np.float64) for a in arrs))}


def _local_finite(coord, grid) -> bool:
    """The guard observation: single-process keeps the fused on-device
    reduction (bitwise the pre-coordinator supervisor); a distributed
    coordinator switches to the host-side check of THIS process's
    addressable shards — process-local, so (a) a rank-local corruption
    produces a rank-local verdict (the split-brain the consensus merge
    exists to resolve) and (b) no guard can wedge on a dead peer."""
    if coord.distributed:
        return ckpt._host_all_finite(grid)
    return grid_all_finite(grid)


def _global_stats(coord, grid) -> dict:
    """Grid stats for the drift guard: the fused device reduction
    single-process; host-side partials merged over the coordinator
    when distributed (same no-collective rationale as
    :func:`_local_finite`)."""
    if not coord.distributed:
        return grid_stats(grid)
    parts = coord.exchange("stats", _local_shard_stats(grid))
    return coordination.merge_stats(parts)


def _run_supervised(config: HeatConfig, checkpoint,
                    policy: Optional[SupervisorPolicy] = None,
                    initial=None, start_step: int = 0,
                    faults=None, say=None,
                    resume_extra_flags: Tuple[str, ...] = (),
                    telemetry=None, checkpointer=None,
                    interrupt=None,
                    coordinator=None) -> SupervisorResult:
    """The supervised loop proper; :func:`run_supervised` wraps it in
    the stem lock and the coordinator lifecycle."""
    config = config.validate()
    policy = (policy or SupervisorPolicy()).validate()
    coord = coordinator if coordinator is not None \
        else coordination.Coordinator()
    if faults is not None:
        bind = getattr(faults, "bind_process", None)
        if bind is not None:
            # Rank-scoped plans (FaultPlan.only_process) judge against
            # the COORDINATOR rank: thread-simulated ranks share one
            # OS process, so the runtime's process index would lie.
            bind(coord.process_index)
    say = say or (lambda *a: None)
    if telemetry is not None:
        # Header carries the user's config (guard_interval included);
        # idempotent, so the per-segment streams' calls are no-ops —
        # which is why the resolved dispatch depth must ride THIS call
        # (the documented run_header schema), not the streams' later
        # dropped ones.
        telemetry.run_header(
            config, pipeline_depth=resolved_pipeline_depth(config))
    # The supervisor owns guarding — the inner stream runs guard-free
    # (one compiled-program family shared with unsupervised runs).
    run_base = (config.replace(guard_interval=None)
                if config.guard_interval is not None else config)
    guard_iv = (policy.guard_interval or config.guard_interval
                or policy.checkpoint_every)
    every = policy.checkpoint_every
    chunk = math.gcd(every, guard_iv)
    if chunk < min(every, guard_iv):
        # Non-nested cadences (e.g. checkpoint_every=1000 with
        # guard_interval=333 -> gcd 1): both schedules still land
        # exactly, but every chunk is a separate host dispatch — a
        # degenerate gcd silently turns a fused thousand-step run into
        # per-step dispatch. Loud, because the fix is one flag away.
        import warnings

        warnings.warn(
            f"supervisor dispatch chunk is gcd(checkpoint_every="
            f"{every}, guard_interval={guard_iv}) = {chunk} steps — "
            f"far smaller chunks mean more host dispatches per run; "
            f"pick a guard_interval that divides checkpoint_every to "
            f"dispatch {min(every, guard_iv)}-step chunks instead",
            RuntimeWarning,
        )
    if config.accumulate == "f32chunk":
        from parallel_heat_tpu.config import sublane_count

        sub = sublane_count(config.dtype)
        if every % sub or guard_iv % sub:
            # Stream boundaries ARE rounding points under f32chunk
            # (SEMANTICS.md): a non-K-multiple cadence would silently
            # shift every boundary up and desync the guard/checkpoint
            # schedule from the requested one. Make it loud instead.
            raise ValueError(
                f"accumulate='f32chunk' requires checkpoint_every and "
                f"guard_interval to be multiples of the chunk depth "
                f"K={sub} (stream boundaries are rounding points — "
                f"SEMANTICS.md)")
    total_abs = start_step + config.steps
    stem = ckpt.checkpoint_stem(checkpoint)
    ckpt_cfg = config.replace(steps=total_abs)  # self-describing target

    retries = rollbacks = trips = n_ckpt = progress = 0
    trip_steps: list = []
    trip_windows: list = []
    last_path: Optional[str] = None
    clock = policy.clock  # injectable wall source (observation only)
    t0 = clock()

    # Async saver: policy-built unless the caller injected one (the
    # chaos harness passes throttled checkpointers to widen the
    # in-flight window). None = the synchronous save path.
    saver = checkpointer
    own_saver = False
    if saver is None and policy.async_checkpoint:
        saver = ckpt.AsyncCheckpointer(keep=policy.keep_checkpoints,
                                       layout=policy.layout,
                                       compress=policy.compress)
        own_saver = True
    # Commit bookkeeping is written by the saver's worker thread and
    # read by this loop — one lock keeps n_ckpt/last_path coherent.
    ckpt_lock = threading.Lock()
    # Stream yields at depth > 1 are already donation-protected copies
    # (SEMANTICS.md "Pipelined stream"), so the async saver can
    # snapshot them without a second device copy; depth-1 yields are
    # live buffers the next chunk donates and still need one.
    ckpt_protect = resolved_pipeline_depth(run_base) == 1

    def _mk(result, done, interrupted, signame=None, resume_cmd=None):
        return SupervisorResult(
            result=result, steps_done=done, interrupted=interrupted,
            retries=retries, rollbacks=rollbacks, guard_trips=trips,
            guard_trip_steps=tuple(trip_steps),
            checkpoints_written=n_ckpt, last_checkpoint=last_path,
            resume_command=resume_cmd, signal_name=signame,
            wall_s=clock() - t0, progress_trips=progress)

    def emit(event, **fields):
        if telemetry is not None:
            telemetry.emit(event, **fields)

    def emit_consensus(action, step, merged):
        # One event per boundary whose MERGED verdict demands an
        # action (trip/rollback/interrupt/transient): the artifact
        # every rank's shard carries, so cross-rank agreement is
        # auditable (the mp chaos cells assert the same action at the
        # same step on every shard). Distributed only — single-process
        # streams stay byte-compatible with the pre-coordinator ones.
        if coord.distributed:
            emit("consensus_verdict", step=step, action=action,
                 verdict={k: v for k, v in merged.items()
                          if v is not None})

    def fail(diagnosis: str, kind: str = "exhausted",
             drained: bool = False) -> PermanentFailure:
        if not drained:
            try:
                # Drain in-flight saves so the terminal telemetry
                # counts (and the on-disk generation set a post-mortem
                # inspects) are final; swallowed — a failed async save
                # must not mask the diagnosis being raised. Callers
                # that already ran a barrier (the stall/exhausted
                # paths, whose diagnoses name last_path) pass
                # drained=True so one logical drain emits one
                # checkpoint_barrier event.
                ckpt_barrier("failure")
            except Exception:  # noqa: BLE001
                pass
        emit("permanent_failure", diagnosis=diagnosis, kind=kind)
        if telemetry is not None:
            telemetry.run_end(outcome="permanent_failure", kind=kind,
                              steps_done=done, retries=retries,
                              rollbacks=rollbacks, guard_trips=trips,
                              checkpoints_written=n_ckpt,
                              wall_s=clock() - t0)
        return PermanentFailure(diagnosis, kind=kind)

    def _committed(rec):
        # Worker-thread hook: runs when an async generation actually
        # landed (or was skipped by the finite-verify commit gate).
        nonlocal n_ckpt, last_path
        if rec.get("error") is not None:
            return  # surfaced at the next barrier, like a sync raise
        if rec.get("skipped"):
            say(f"Supervisor: async checkpoint at step {rec['step']} "
                f"skipped (non-finite snapshot); previous generation "
                f"stays newest")
            emit("checkpoint_skipped", step=rec["step"],
                 reason="non_finite")
            return
        with ckpt_lock:
            n_ckpt += 1
            gen = n_ckpt
            last_path = rec["path"]
        emit("checkpoint_save", step=rec["step"], path=str(rec["path"]),
             wall_s=rec["wall_s"], kept=policy.keep_checkpoints,
             generation=gen, gather_s=rec["gather_s"],
             **{"async": True})
        say(f"Supervisor: checkpoint at step {rec['step']} -> "
            f"{rec['path']}")

    def save(grid, step_abs):
        nonlocal n_ckpt, last_path
        if saver is not None:
            # Device copy now (donation-safe), gather + finite-verify +
            # atomic commit on the worker — the next chunk dispatches
            # while the snapshot drains. Barriers (rollback/interrupt/
            # final) are the only places the loop waits for it. Under
            # a distributed coordinator the worker runs the two-phase
            # commit (save_generation_coordinated): its KV exchanges
            # live on the worker thread, host-side only.
            saver.submit(stem, grid, step_abs, ckpt_cfg,
                         on_done=_committed, protect=ckpt_protect,
                         coordinator=(coord if coord.distributed
                                      else None))
            return
        t_save = clock()
        if coord.distributed:
            path, skipped = ckpt.save_generation_coordinated(
                stem, grid, step_abs, ckpt_cfg, coord,
                keep=policy.keep_checkpoints, layout=policy.layout,
                compress=policy.compress)
            if skipped:
                emit("checkpoint_skipped", step=step_abs,
                     reason="non_finite_consensus")
                say(f"Supervisor: checkpoint at step {step_abs} "
                    f"skipped by consensus (a rank reported non-finite "
                    f"shards); previous generation stays newest")
                return
            last_path = path
        else:
            last_path = ckpt.save_generation(
                stem, grid, step_abs, ckpt_cfg,
                keep=policy.keep_checkpoints,
                layout=policy.layout, compress=policy.compress)
        n_ckpt += 1
        emit("checkpoint_save", step=step_abs, path=str(last_path),
             wall_s=clock() - t_save,
             kept=policy.keep_checkpoints, generation=n_ckpt)
        say(f"Supervisor: checkpoint at step {step_abs} -> {last_path}")
        return last_path

    def ckpt_barrier(reason: str):
        # The async-save barrier: every rollback, interrupt, failure and
        # completion drains in-flight saves BEFORE acting on the
        # retained-generation set, so discovery/rollback can never see
        # (or restore) an uncommitted generation. Re-raises the first
        # worker error — the same surface a synchronous save has.
        if saver is None:
            return
        wait_s = saver.drain()
        emit("checkpoint_barrier", reason=reason, wait_s=wait_s)

    def interrupted(cur, done, why, already_saved):
        # Flush-and-exit on SIGTERM/SIGINT (`why` an int signum) or on
        # the caller's interrupt hook (`why` a reason string — service
        # deadlines/cancellation ride the same path). The flushed state
        # must honor the retained-generations-are-good invariant: a
        # signal landing between a corruption and its guard boundary
        # must not persist garbage, so the flush itself is
        # guard-verified (skipped — the previous generation stays
        # newest — when non-finite; the async saver's commit gate
        # re-verifies the gathered copy either way). Both barriers
        # matter: a SIGTERM can land with a periodic save still in
        # flight, and the resume command below must name a COMMITTED
        # newest generation.
        ckpt_barrier("interrupt")
        if not already_saved:
            if coord.distributed:
                # The coordinated save embeds the guard: the two-phase
                # commit gate skips the generation GLOBALLY when any
                # rank's shards are non-finite, so the flush needs no
                # separate (collective) verdict here.
                save(cur, done)
                ckpt_barrier("interrupt")
            elif grid_all_finite(cur):
                save(cur, done)
                ckpt_barrier("interrupt")
            else:
                say(f"Supervisor: state at step {done} is non-finite; "
                    f"keeping previous generation instead of flushing")
        name = (signal.Signals(why).name if isinstance(why, int)
                else str(why))
        cmd = _resume_command(ckpt_cfg, stem, total_abs, policy,
                              resume_extra_flags)
        say(f"Supervisor: caught {name}; newest checkpoint "
            f"{last_path}. Resume with:\n  {cmd}")
        emit("signal", name=name, step=done)
        if telemetry is not None:
            telemetry.run_end(outcome="interrupted", steps_done=done,
                              signal=name, retries=retries,
                              rollbacks=rollbacks, guard_trips=trips,
                              checkpoints_written=n_ckpt,
                              wall_s=clock() - t0)
        return _mk(None, done, True, signame=name, resume_cmd=cmd)

    done = start_step
    # Materialize the start state once (default init / host resume array
    # -> placed, donation-protected device grid) so generation zero can
    # be written before any step runs: rollback ALWAYS has a target,
    # even for a fault in the very first chunk.
    state = _prepare_initial(run_base, initial)
    stop = _StopFlag()

    def _stop_why():
        # Preemption signals win, then the caller's flag-only interrupt
        # hook (service deadlines/cancellation). Both are only ever
        # observed here, at chunk boundaries — the hook must be cheap
        # and must not raise (it is polled on the hot path).
        if stop.signum is not None:
            return stop.signum
        if interrupt is not None:
            why = interrupt()
            if why:
                return str(why)
        return None

    final: Optional[HeatResult] = None

    drift_env = None
    if policy.drift_tolerance is not None:
        # The drift envelope comes from the START state via the same
        # fused stats reduction diagnostics use. Two independent
        # physics bounds, both invisible to the isfinite guard:
        #
        # 1. Extrema: the explicit scheme's maximum principle
        #    (sum(c) <= 1/2 makes every update a convex combination of
        #    neighbors) confines all future values to the initial
        #    range — a bit flip into a huge-but-finite float escapes.
        # 2. Heat-content RATE: total interior heat changes only by
        #    flux through the Dirichlet boundary; telescoping the
        #    update sum leaves two value-differences per boundary
        #    column/face, each bounded by the initial range, so
        #    |d(heat)/step| <= 2 * range0 * sum_a(c_a * interior face
        #    area normal to axis a). Region-scale corruption that
        #    stays inside the extrema envelope (half the grid zeroed
        #    by a buggy exchange) jumps the heat faster than any
        #    physical boundary flux can. (A bound on heat's LEVEL
        #    would be implied by the extrema check — the rate bound is
        #    the one that adds information.)
        from parallel_heat_tpu.utils import profiling

        s0 = _global_stats(coord, state)
        cells = profiling.cell_count(config)
        range0 = s0["max"] - s0["min"]
        scale = max(range0, abs(s0["max"]), abs(s0["min"]), 1e-30)
        band = policy.drift_tolerance * scale
        interior = [max(n - 2, 0) for n in config.shape]
        flux = 0.0
        for a, c in enumerate(config.coefficients):
            face = 1.0
            for b, m in enumerate(interior):
                if b != a:
                    face *= m
            flux += abs(c) * face
        drift_env = {"min": s0["min"] - band, "max": s0["max"] + band,
                     "flux_per_step": 2.0 * range0 * flux,
                     # Absolute slack: f32 sum rounding + tolerance,
                     # scaled to the grid (a zero-slack bound would
                     # flag accumulation noise on large grids).
                     "slack": policy.drift_tolerance * cells * scale}

    def _drift_violation(st, prev_heat, steps_between) -> Optional[str]:
        if st["min"] < drift_env["min"] or st["max"] > drift_env["max"]:
            return (f"grid range [{st['min']:g}, {st['max']:g}] escaped "
                    f"the initial envelope [{drift_env['min']:g}, "
                    f"{drift_env['max']:g}] (maximum principle)")
        if prev_heat is not None and steps_between > 0:
            limit = (drift_env["flux_per_step"] * steps_between
                     + drift_env["slack"])
            moved = st["heat"] - prev_heat
            if abs(moved) > limit:
                return (f"total heat content moved {moved:+g} over "
                        f"{steps_between} steps, past the boundary-flux "
                        f"bound {limit:g} "
                        f"({drift_env['flux_per_step']:g}/step + slack)")
        return None

    try:
        with _signal_handlers(stop), \
                _saver_cleanup(saver if own_saver else None):
            save(state, done)
            while done < total_abs and final is None:
                seg_base = done
                last_guarded = done  # guard-verified (or checkpoint-loaded)
                # Stall tracker, reset per segment: a rollback replays from
                # a verified state, so the residual trajectory restarts.
                best_res = math.inf
                stall_run = 0
                stall_from = seg_base
                # Heat-rate baseline, reset per segment (a rollback reloads
                # verified state; its heat restarts the rate window).
                if drift_env is not None:
                    seg_heat = _global_stats(coord, state)["heat"]
                    seg_heat_step = done
                if telemetry is not None:
                    # Chunk events carry absolute steps: the stream counts
                    # from its own start, each segment's base is added here.
                    telemetry.step_offset = seg_base
                stream = solve_stream(run_base.replace(steps=total_abs - done),
                                      initial=state, chunk_steps=chunk,
                                      telemetry=telemetry)
                cur = state  # freshest NOT-yet-donated grid
                res = None
                try:
                    while True:
                        local_fault = None
                        if faults is not None:
                            try:
                                faults.before_chunk()
                            except InjectedTransientError as fe:
                                # Deferred into the boundary consensus: on
                                # a single-rank injection every OTHER rank
                                # must also roll back (instead of
                                # dispatching into a wedged collective).
                                local_fault = str(fe)
                        # Pre-dispatch consensus: stop flags (signals, the
                        # caller's interrupt hook) and pre-dispatch faults.
                        # Single-process this is the identity — the merged
                        # verdict IS the local one, bitwise the old loop.
                        pre_verdicts, pre_wait = coord.exchange_timed(
                            "pre", {"stop": _stop_why(),
                                    "fault": local_fault})
                        pre = coordination.merge_boundary(pre_verdicts)
                        if pre["fault"] is not None:
                            emit_consensus("transient", done, pre)
                            raise InjectedTransientError(pre["fault"])
                        if pre["stop"] is not None:
                            if coord.distributed:
                                emit_consensus("interrupt", done, pre)
                            return interrupted(cur, done, pre["stop"],
                                               already_saved=False)
                        local_err = None
                        try:
                            # (a raise leaves `res` holding the
                            # previous chunk's result — the stream-
                            # exhausted `break` relies on that,
                            # exactly as before)
                            res = next(stream)
                        except StopIteration:
                            break
                        except Exception as e:
                            if coord.distributed \
                                    and _is_transient_dispatch_error(e):
                                # Hold the local transient for the boundary
                                # consensus below so every rank leaves this
                                # chunk through the same rollback; non-
                                # transient errors crash this rank and the
                                # peers detect the corpse by heartbeat.
                                local_err = e
                            else:
                                raise
                        if local_err is None:
                            cur = res.grid
                            step_abs = seg_base + res.steps_run
                            ckpt_due = step_abs >= (
                                (done // every + 1) * every) \
                                or step_abs >= total_abs
                            guard_due = ckpt_due or step_abs >= (
                                (done // guard_iv + 1) * guard_iv)
                            if res.converged:
                                ckpt_due = guard_due = True
                            if faults is not None:
                                # observed=guard_due: an injection landing
                                # on a boundary the guard never inspects
                                # would be silently dropped with the next
                                # chunk's `cur = res.grid` — the plan
                                # defers it to the first guarded boundary
                                # instead.
                                cur = faults.corrupt(cur, step_abs,
                                                     observed=guard_due)
                            local = {"err": None, "stop": _stop_why()}
                            if guard_due:
                                local["finite"] = _local_finite(coord, cur)
                                if (drift_env is not None
                                        and coord.distributed
                                        and local["finite"]):
                                    # Ride the drift partials (3
                                    # floats) on the post payload —
                                    # a second blocking exchange per
                                    # guarded boundary would double
                                    # the straggler-amplified
                                    # consensus latency for nothing.
                                    local["stats"] = \
                                        _local_shard_stats(cur)
                        else:
                            step_abs = done
                            ckpt_due = guard_due = False
                            local = {"err": str(local_err),
                                     "stop": _stop_why()}
                        # Post-chunk consensus: the guard verdict (each
                        # rank's LOCAL observation under a distributed
                        # coordinator), mid-chunk transients, stop flags.
                        post_verdicts, post_wait = coord.exchange_timed(
                            "post", local)
                        post = coordination.merge_boundary(post_verdicts)
                        if coord.distributed:
                            emit("barrier_wait", step=step_abs,
                                 wait_s=pre_wait + post_wait)
                        if post["err"] is not None:
                            emit_consensus("transient", step_abs, post)
                            if local_err is not None:
                                raise local_err
                            raise coordination.PeerTransientError(
                                post["err"])
                        if guard_due:
                            if post["finite"] is False:
                                trips += 1
                                trip_steps.append(step_abs)
                                trip_windows.append((last_guarded, step_abs))
                                emit("guard_trip", step=step_abs,
                                     window=[last_guarded, step_abs])
                                emit_consensus("nan", step_abs, post)
                                raise _GuardTrip((last_guarded, step_abs))
                            if drift_env is not None:
                                # Reuse the chunk's own diagnostics sample
                                # when it exists (cur IS res.grid whenever
                                # no fault plan rewrote it) — no second
                                # full-grid sweep at shared boundaries.
                                # Distributed: host partials rode the
                                # post payload (never a collective, and
                                # no second exchange) — the merged
                                # finite==True consensus above implies
                                # every rank included its stats.
                                if coord.distributed:
                                    st = coordination.merge_stats(
                                        [v["stats"]
                                         for v in post_verdicts
                                         if "stats" in v])
                                else:
                                    st = (res.diagnostics
                                          if faults is None
                                          and res.diagnostics is not None
                                          else grid_stats(cur))
                                why = _drift_violation(
                                    st, seg_heat, step_abs - seg_heat_step)
                                if why is not None:
                                    progress += 1
                                    emit("progress_trip", kind="drift",
                                         step=step_abs,
                                         window=[last_guarded, step_abs],
                                         detail=why)
                                    emit_consensus("drift", step_abs, post)
                                    raise _GuardTrip(
                                        (last_guarded, step_abs),
                                        kind="drift")
                                seg_heat = st["heat"]
                                seg_heat_step = step_abs
                            last_guarded = step_abs
                        if (policy.stall_windows is not None
                                and config.converge
                                and res.residual is not None
                                and not res.converged):
                            # Progress guard, stall classifier: a new
                            # residual minimum resets the window count; K
                            # consecutive observations without one is a
                            # plateau retrying cannot fix (the same program
                            # replays the same residuals).
                            if (math.isfinite(res.residual)
                                    and res.residual < best_res):
                                best_res = res.residual
                                stall_run = 0
                                stall_from = step_abs
                            else:
                                stall_run += 1
                                if stall_run >= policy.stall_windows:
                                    progress += 1
                                    # Commit in-flight saves first (the
                                    # diagnosis names the newest
                                    # checkpoint) — swallowed like fail()'s
                                    # barrier: a failed async save must not
                                    # mask the stall verdict being raised.
                                    try:
                                        ckpt_barrier("failure")
                                    except Exception:  # noqa: BLE001
                                        pass
                                    emit("progress_trip", kind="stalled",
                                         step=step_abs,
                                         window=[stall_from, step_abs],
                                         windows=stall_run,
                                         residual=res.residual,
                                         best_residual=best_res,
                                         eps=config.eps)
                                    raise fail(
                                        f"progress guard: residual stalled "
                                        f"at {res.residual:g} (best "
                                        f"{best_res:g}, eps {config.eps:g})"
                                        f" — no new minimum across "
                                        f"{stall_run} consecutive windows, "
                                        f"steps ({stall_from}, {step_abs}]."
                                        f" The iteration has hit its "
                                        f"precision floor above eps; "
                                        f"retrying replays the same "
                                        f"plateau. Raise eps, use a wider "
                                        f"dtype, or cap steps. Newest "
                                        f"checkpoint: {last_path}.",
                                        kind="stalled", drained=True)
                        done = step_abs
                        if ckpt_due:
                            save(cur, step_abs)
                        if res.converged:
                            final = res
                            break
                        if post["stop"] is not None:
                            # Signal/interrupt landed during this chunk
                            # (sampled into the post consensus, so every
                            # rank flushes together): flush the fresh
                            # (guard-verified above) state rather than
                            # waiting for the pre-dispatch check.
                            if coord.distributed:
                                emit_consensus("interrupt", done, post)
                            return interrupted(cur, done, post["stop"],
                                               already_saved=ckpt_due)
                    if final is None:
                        # Stream exhausted: complete (done == total_abs), or
                        # a defensive under-run — either way `res` is the
                        # last verified chunk (None only when steps == 0,
                        # which never enters this loop).
                        final = res
                except Exception as e:
                    if isinstance(e, _GuardTrip):
                        lo, hi = e.window
                        if e.kind == "drift":
                            # Finite-value corruption: retryable (a flipped
                            # bit replays clean); a boundary bug persists
                            # and exhausts the budget into a drift-kind
                            # PermanentFailure below.
                            kind = (f"progress guard: heat-content drift "
                                    f"in steps ({lo}, {hi}]")
                        elif config.scheme == "explicit" \
                                and config.stability_margin() < 0:
                            raise fail(
                                f"non-finite grid values in steps ({lo}, "
                                f"{hi}]: coefficient sum "
                                f"{sum(config.coefficients):g} exceeds the "
                                f"stability bound 1/2 (margin "
                                f"{config.stability_margin():g}) — the "
                                f"explicit scheme diverges deterministically; "
                                f"retrying cannot help. Reduce the "
                                f"coefficients (cx/cy/cz) below a sum of "
                                f"1/2, or switch to the implicit "
                                f"integrator (--scheme backward_euler), "
                                f"which is unconditionally stable at any "
                                f"step size. Last good checkpoint: step "
                                f"{lo}.",
                                kind="unstable",
                            ) from None
                        else:
                            kind = (f"guard trip: non-finite values in "
                                    f"steps ({lo}, {hi}]")
                    elif _is_transient_dispatch_error(e):
                        kind = f"transient dispatch error: {e}"
                    else:
                        raise
                    # The rollback barrier: a trip must drain in-flight
                    # saves BEFORE anything reads the generation set — the
                    # exhausted-budget diagnosis below names the newest
                    # COMMITTED checkpoint, and the rollback load can never
                    # restore a generation whose rename has not landed.
                    ckpt_barrier("rollback")
                    retries += 1
                    if retries > policy.max_retries:
                        # The window comes from the guard's own records
                        # (the (last-verified, detected] span), never
                        # reconstructed from the chunk size: the current
                        # trip's window when this failure IS a trip, else
                        # the first recorded one (labelled as such, since a
                        # dispatch-error exhaustion may follow an earlier
                        # recovered trip).
                        if isinstance(e, _GuardTrip):
                            lo, hi = e.window
                            first = f" First bad chunk: steps ({lo}, {hi}]."
                        elif trip_windows:
                            lo, hi = trip_windows[0]
                            first = (f" Earlier guard trip window: steps "
                                     f"({lo}, {hi}].")
                        else:
                            first = ""
                        raise fail(
                            f"{kind} — fault persisted through "
                            f"{policy.max_retries} rollback retr"
                            f"{'y' if policy.max_retries == 1 else 'ies'}."
                            f"{first} Newest verified checkpoint: "
                            f"{last_path}.",
                            kind=("drift" if isinstance(e, _GuardTrip)
                                  and e.kind == "drift" else "exhausted"),
                            drained=True,
                        ) from None
                    delay = min(policy.backoff_max_s,
                                policy.backoff_base_s * 2 ** (retries - 1))
                    emit("retry", retry=retries,
                         max_retries=policy.max_retries, kind=kind,
                         backoff_s=delay)
                    say(f"Supervisor: {kind}; retry {retries}/"
                        f"{policy.max_retries} after {delay:g}s backoff")
                    if delay > 0:
                        policy.sleep_fn(delay)
                    src = ckpt.latest_checkpoint(stem)
                    if coord.distributed:
                        # Rollback-target consensus: rank 0's discovery is
                        # authoritative, so every rank loads the SAME
                        # generation even if a shared-filesystem view is
                        # momentarily inconsistent — the mp chaos cells
                        # assert the per-rank rollback events name one
                        # path.
                        picked = coord.exchange(
                            "rollback",
                            {"path": str(src) if src is not None else None})
                        src = picked[0]["path"]
                    if src is None:  # pragma: no cover (gen0 always exists)
                        raise fail(
                            f"{kind} — and no checkpoint generation of "
                            f"{stem!r} survives to roll back to.",
                            drained=True) from None
                    t_load = clock()
                    grid0, step0, _ = ckpt.load_checkpoint(src, ckpt_cfg)
                    rollbacks += 1
                    state, done = grid0, int(step0)
                    emit("rollback", step=done, path=str(src),
                         load_wall_s=clock() - t_load)
                    say(f"Supervisor: rolled back to {src} (step {done})")
                    continue
            # Completion barrier: the final retained generation must be
            # committed before run_end is recorded and the result's
            # checkpoint counts are read.
            ckpt_barrier("final")
            if final is not None and done < total_abs and not final.converged:
                # Defensive stream under-run: record reality, don't loop.
                say(f"Supervisor: stream under-ran at step {done} of "
                    f"{total_abs} without converging; stopping")
            if telemetry is not None:
                telemetry.run_end(outcome="complete", steps_done=done,
                                  retries=retries, rollbacks=rollbacks,
                                  guard_trips=trips,
                                  checkpoints_written=n_ckpt,
                                  wall_s=clock() - t0)
            if final is None:
                # config.steps == 0 (or resume already at/past the target):
                # nothing ran; generation zero was still written.
                return _mk(None, done, False)
            return _mk(final, done, False)
    except coordination.PeerLostError as e:
        # A peer process died (SIGKILL/OOM/host loss): the bounded
        # barrier detected it instead of wedging inside a collective.
        # Exit preempted with an ELASTIC resume command — a mesh the
        # surviving hosts can actually build, resuming bit-exactly
        # through the checkpoint reshard-on-load path (the newest
        # COMMITTED generation; the two-phase protocol guarantees no
        # partially-committed one is discoverable).
        import jax

        survivors = coord.process_count - len(e.lost)
        n_dev = jax.local_device_count() * survivors
        emit("peer_lost", step=done, lost=list(e.lost),
             survivors=survivors, waited_s=e.waited_s,
             timeout_s=e.timeout_s)
        mesh = coordination.surviving_mesh_shape(config.shape, n_dev)
        cmd = _resume_command(ckpt_cfg, stem, total_abs, policy,
                              resume_extra_flags, mesh_override=mesh)
        say(f"Supervisor: peer process(es) {sorted(e.lost)} lost "
            f"(heartbeat static past the {e.timeout_s:g}s barrier "
            f"timeout); newest committed checkpoint "
            f"{ckpt.latest_checkpoint(stem)}. Resume on the "
            f"{survivors} surviving host(s) with:\n  {cmd}")
        if telemetry is not None:
            telemetry.run_end(outcome="interrupted", signal="peer_lost",
                              steps_done=done, retries=retries,
                              rollbacks=rollbacks, guard_trips=trips,
                              checkpoints_written=n_ckpt,
                              wall_s=clock() - t0)
        return _mk(None, done, True, signame="peer_lost",
                   resume_cmd=cmd)
