"""The 2D heated-plate model: initial condition, boundary, coefficients.

Reference semantics (``inidat``, identical in both reference programs —
``mpi/mpi_heat_improved_persistent_stat.c:315-321``,
``cuda/cuda_heat.cu:274-280``):

    u0(ix, iy) = ix * (nx - ix - 1) * iy * (ny - iy - 1)

which is zero on the whole boundary, and the boundary is never written by
the stencil (Dirichlet). The model object owns this problem definition;
the ops/ and parallel/ layers own how it is computed.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


class HeatPlate2D:
    """2D plate with polynomial initial condition and fixed boundary."""

    ndim = 2

    def __init__(self, nx: int, ny: int, cx: float = 0.1, cy: float = 0.1):
        self.nx = int(nx)
        self.ny = int(ny)
        self.cx = float(cx)
        self.cy = float(cy)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.nx, self.ny)

    @property
    def coefficients(self) -> Tuple[float, float]:
        return (self.cx, self.cy)

    def init_grid_np(self, dtype=np.float32) -> np.ndarray:
        """NumPy initial grid (host-side; the float64 semantics oracle).

        Note: the C reference evaluates the formula in *int* arithmetic,
        which silently overflows int32 for nx >= ~215 (benchmark sizes
        included) — a quirk we deliberately do not replicate.
        """
        nx, ny = self.nx, self.ny
        ix = np.arange(nx, dtype=np.float64)[:, None]
        iy = np.arange(ny, dtype=np.float64)[None, :]
        u = ix * (nx - ix - 1) * iy * (ny - iy - 1)
        return u.astype(dtype)

    def init_grid(self, dtype=jnp.float32) -> jnp.ndarray:
        """Device-side initial grid (built on-device; no host transfer).

        Computed as the outer product of the per-axis factors
        ``fx = ix*(nx-ix-1)``: each factor is an integer < 2^24 for
        nx <= 8192 (exact in f32), so the single product rounding makes
        this bit-identical to the float64-then-cast oracle at those
        sizes; beyond that it may differ by 1 ulp.
        """
        nx, ny = self.nx, self.ny
        ix = jnp.arange(nx, dtype=jnp.float32)
        iy = jnp.arange(ny, dtype=jnp.float32)
        fx = ix * (nx - ix - 1)
        fy = iy * (ny - iy - 1)
        return (fx[:, None] * fy[None, :]).astype(dtype)

    def init_block(self, block_shape, block_index, dtype=jnp.float32):
        """Initial condition for one mesh block, built shard-locally.

        Replaces the reference's master-scatter (``mpi/...stat.c:86-127``):
        every device materializes its own block from global coordinates,
        so no full grid ever exists on one device.
        """
        bx, by = block_shape
        gx0 = block_index[0] * bx
        gy0 = block_index[1] * by
        nx, ny = self.nx, self.ny
        ix = gx0 + jnp.arange(bx, dtype=jnp.float32)
        iy = gy0 + jnp.arange(by, dtype=jnp.float32)
        fx = ix * (nx - ix - 1)
        fy = iy * (ny - iy - 1)
        return (fx[:, None] * fy[None, :]).astype(dtype)
