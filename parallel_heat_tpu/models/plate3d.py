"""The 3D heated-volume model — 7-point stencil extension.

The reference is strictly 2D; this is the planned 3D extension from the
build plan (BASELINE.json config 5: 512^3, 7-point). The initial condition
generalizes the reference's separable polynomial (``inidat``,
``mpi/...stat.c:315-321``) to three axes, again vanishing on the boundary.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np


class HeatPlate3D:
    """3D volume with separable polynomial initial condition."""

    ndim = 3

    def __init__(self, nx: int, ny: int, nz: int,
                 cx: float = 0.1, cy: float = 0.1, cz: float = 0.1):
        self.nx = int(nx)
        self.ny = int(ny)
        self.nz = int(nz)
        self.cx = float(cx)
        self.cy = float(cy)
        self.cz = float(cz)

    @property
    def shape(self) -> Tuple[int, int, int]:
        return (self.nx, self.ny, self.nz)

    @property
    def coefficients(self) -> Tuple[float, float, float]:
        return (self.cx, self.cy, self.cz)

    def init_grid_np(self, dtype=np.float32) -> np.ndarray:
        nx, ny, nz = self.shape
        ix = np.arange(nx, dtype=np.float64)[:, None, None]
        iy = np.arange(ny, dtype=np.float64)[None, :, None]
        iz = np.arange(nz, dtype=np.float64)[None, None, :]
        u = ix * (nx - ix - 1) * iy * (ny - iy - 1) * iz * (nz - iz - 1)
        return u.astype(dtype)

    def init_grid(self, dtype=jnp.float32) -> jnp.ndarray:
        """Outer product of exact per-axis f32 factors (two roundings —
        may differ from the float64 oracle by ~1 ulp; see plate2d)."""
        nx, ny, nz = self.shape
        fx = jnp.arange(nx, dtype=jnp.float32)
        fy = jnp.arange(ny, dtype=jnp.float32)
        fz = jnp.arange(nz, dtype=jnp.float32)
        fx = fx * (nx - fx - 1)
        fy = fy * (ny - fy - 1)
        fz = fz * (nz - fz - 1)
        u = fx[:, None, None] * fy[None, :, None] * fz[None, None, :]
        return u.astype(dtype)

    def init_block(self, block_shape, block_index, dtype=jnp.float32):
        bx, by, bz = block_shape
        g0 = [bi * bs for bi, bs in zip(block_index, block_shape)]
        nx, ny, nz = self.shape
        fx = g0[0] + jnp.arange(bx, dtype=jnp.float32)
        fy = g0[1] + jnp.arange(by, dtype=jnp.float32)
        fz = g0[2] + jnp.arange(bz, dtype=jnp.float32)
        fx = fx * (nx - fx - 1)
        fy = fy * (ny - fy - 1)
        fz = fz * (nz - fz - 1)
        u = fx[:, None, None] * fy[None, :, None] * fz[None, None, :]
        return u.astype(dtype)
