from parallel_heat_tpu.models.plate2d import HeatPlate2D
from parallel_heat_tpu.models.plate3d import HeatPlate3D

__all__ = ["HeatPlate2D", "HeatPlate3D"]
