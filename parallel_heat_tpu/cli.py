"""Command-line interface — the runtime replacement for the reference's
compile-time macro matrix.

The reference builds one binary per configuration (``mpi/Makefile:12-21``
bakes ``SIZE``/``STEPS``/``STEP``/``CONVERGE``/``OMPCH`` into four binary
variants; the binaries take no arguments). Here every knob is a flag, and
the output mirrors the reference's console report: startup banner
(``mpi/...stat.c:90-96``), converged-at (``:300-305``), elapsed time
(``:306``), plus ``initial_im.dat`` / ``final_im.dat`` dumps (``:98,299``).
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="parallel_heat_tpu",
        description="TPU-native Jacobi heat-diffusion solver",
    )
    ap.add_argument("--nx", type=int, default=20, help="grid rows (NXPROB)")
    ap.add_argument("--ny", type=int, default=20, help="grid cols (NYPROB)")
    ap.add_argument("--nz", type=int, default=None,
                    help="grid depth; enables the 3D 7-point stencil")
    ap.add_argument("--steps", type=int, default=10_000,
                    help="step count (exact in fixed mode, cap in converge)")
    ap.add_argument("--converge", action="store_true",
                    help="stop when max |du| < eps (CONVERGE build flag)")
    ap.add_argument("--eps", type=float, default=1e-3)
    ap.add_argument("--check-interval", type=int, default=20,
                    help="steps between convergence checks (STEP macro)")
    ap.add_argument("--cx", type=float, default=0.1)
    ap.add_argument("--cy", type=float, default=0.1)
    ap.add_argument("--cz", type=float, default=0.1)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "float64"],
                    help="storage dtype (float64 enables jax x64 mode and "
                         "always runs the XLA-fused jnp path: Mosaic has "
                         "no 64-bit types)")
    ap.add_argument("--backend", default="auto",
                    choices=["auto", "jnp", "pallas"])
    ap.add_argument("--mesh", default=None,
                    help="device mesh, e.g. '2,4' (default: single device; "
                         "'auto' factorizes over all local devices)")
    ap.add_argument("--no-overlap", action="store_true",
                    help="disable the interior/edge comm-compute overlap")
    ap.add_argument("--halo-depth", default="auto", metavar="K",
                    help="exchange K-deep halos once per K steps instead "
                         "of 1-deep every step (sharded runs). The "
                         "default 'auto' picks the Mosaic block kernel's "
                         "depth (the dtype's sublane count) when the "
                         "resolved backend is pallas, a mesh is set and "
                         "the geometry admits, else 1 — see --explain "
                         "for the resolved value")
    ap.add_argument("--halo-overlap", default="auto",
                    choices=("auto", "phase", "overlap", "pipeline"),
                    help="exchange/compute schedule of the sharded "
                         "K-deep rounds (SEMANTICS.md 'Overlapped "
                         "exchange'; bitwise-invariant): 'phase' "
                         "serializes every ppermute phase before the "
                         "round's compute, 'overlap' defers the last "
                         "phase behind the bulk update, 'pipeline' "
                         "double-buffers the next round's edge strips "
                         "so BOTH phases stream during the bulk kernel "
                         "(2D kernel-G rounds). 'auto' prices pipeline "
                         "vs overlap with the TpuParams ICI model — "
                         "see --explain for the resolved schedule")
    ap.add_argument("--scheme", default="explicit",
                    choices=("explicit", "backward_euler",
                             "crank_nicolson"),
                    help="time integrator (SEMANTICS.md 'Implicit "
                         "stepping'): the reference's explicit Jacobi "
                         "update (dt capped by the stability bound), "
                         "or an unconditionally stable implicit "
                         "scheme whose per-step linear solve is a "
                         "sharded geometric-multigrid V-cycle — "
                         "cx/cy may exceed the explicit bound by "
                         "orders of magnitude (100-1000x larger "
                         "steps)")
    ap.add_argument("--mg-tol", type=float, default=None,
                    help="implicit schemes: per-step relative "
                         "residual target of the V-cycle iteration "
                         "(default 1e-3)")
    ap.add_argument("--mg-cycles", type=int, default=None,
                    help="implicit schemes: V-cycle cap per step "
                         "(default 50)")
    ap.add_argument("--mg-smooth", type=int, default=None,
                    help="implicit schemes: weighted-Jacobi pre/post "
                         "sweeps per level (default 1)")
    ap.add_argument("--mg-levels", type=int, default=None,
                    help="implicit schemes: hierarchy depth cap "
                         "(default: coarsen fully)")
    ap.add_argument("--mg-partition", default=None,
                    choices=("auto", "replicated", "partitioned"),
                    help="sharded implicit schemes: how the V-cycle "
                         "executes over the mesh (SEMANTICS.md "
                         "'Partitioned V-cycle') — per-level "
                         "shard_map blocks with coarse-level "
                         "agglomeration ('partitioned'), the "
                         "full-grid-per-device spelling "
                         "('replicated'), or the profitability "
                         "model's pick ('auto', default)")
    ap.add_argument("--accumulate", default="storage",
                    choices=("storage", "f32chunk"),
                    help="sub-f32 accumulation semantics (SEMANTICS.md): "
                         "'storage' rounds the state to the storage "
                         "dtype every step; 'f32chunk' (bfloat16, 2D "
                         "single-device) carries f32 across each K-step "
                         "kernel chunk and rounds once per chunk — "
                         "measurably lower drift at a measured "
                         "throughput cost")
    ap.add_argument("--pipeline-depth", default="auto", metavar="D",
                    help="stream dispatch pipelining (SEMANTICS.md "
                         "'Pipelined stream'): keep D chunks in flight "
                         "— chunk n+1 is dispatched before chunk n's "
                         "observers (guard, diagnostics, telemetry, "
                         "checkpoints) drain, so the device never "
                         "idles through them. Dispatch-order only: "
                         "grids, observations, compiled programs and "
                         "checkpoint bytes are identical to a "
                         "synchronous run. 'auto' (default) = 2 for "
                         "fixed-step runs on an accelerator backend, "
                         "1 otherwise (converge runs cannot dispatch "
                         "past their convergence verdict; on CPU "
                         "there is no idle device to keep busy); "
                         "D > 1 with --converge is an error")
    ap.add_argument("--ensemble", type=int, default=None, metavar="B",
                    help="run B independent members of this config as "
                         "ONE batched ensemble program (SEMANTICS.md "
                         "'Ensemble'): per-member epsilon verdicts in "
                         "converge mode, finished members frozen and "
                         "compacted away, per-member results bitwise "
                         "the solo runs. Composes with --supervise "
                         "(ensemble generations + rollback), --metrics "
                         "and --explain; excludes --mesh/--resume")
    ap.add_argument("--out", default=None, metavar="FILE",
                    help="write final grid (.dat for 2D, .npy otherwise)")
    ap.add_argument("--initial-out", default=None, metavar="FILE",
                    help="write initial grid (reference: initial_im.dat)")
    ap.add_argument("--checkpoint", default=None, metavar="FILE",
                    help="write a checkpoint of the final state (.npz, "
                         "or a per-shard .ckpt directory for large "
                         "sharded grids — see --checkpoint-layout)")
    ap.add_argument("--checkpoint-layout", default="auto",
                    choices=["auto", "gathered", "sharded"],
                    help="gathered = one host-gathered .npz; sharded = "
                         "per-process shard files, no host gather; "
                         "auto picks sharded for large sharded grids")
    ap.add_argument("--checkpoint-every", type=int, default=None,
                    metavar="N",
                    help="also checkpoint every N steps during the run "
                         "(requires --checkpoint; the file is overwritten "
                         "each time, so --resume always sees the latest)")
    ap.add_argument("--resume", default=None, metavar="FILE",
                    help="resume from a checkpoint (.npz file or "
                         "per-shard .ckpt directory), or 'auto' to "
                         "resume from the newest retained generation "
                         "of --checkpoint (starts fresh when none "
                         "exists — safe to put in a restart loop)")
    ap.add_argument("--supervise", action="store_true",
                    help="run under the fault-tolerant supervisor: "
                         "periodic retained checkpoint generations, "
                         "on-device non-finite guard, retry-with-"
                         "rollback on faults, SIGTERM/SIGINT-safe exit "
                         "with a printed resume command (requires "
                         "--checkpoint; cadence from --checkpoint-every, "
                         "default steps/10)")
    ap.add_argument("--guard-interval", type=int, default=None,
                    metavar="N",
                    help="steps between on-device isfinite-all guard "
                         "checks (observation-only, never changes "
                         "numerics — SEMANTICS.md). Unsupervised runs "
                         "warn on a trip; --supervise rolls back and "
                         "retries. Default: off unsupervised, every "
                         "checkpoint under --supervise")
    ap.add_argument("--diag-interval", type=int, default=None,
                    metavar="N",
                    help="steps between fused on-device grid-stats "
                         "samples (min/max/total heat content, L2/L-inf "
                         "update residual — observation-only like the "
                         "guard, never changes numerics). Emitted as "
                         "'diagnostics' telemetry events when --metrics "
                         "is set; watch live with tools/monitor.py")
    ap.add_argument("--stall-windows", type=int, default=None,
                    metavar="K",
                    help="supervised converge runs: classify the run "
                         "STALLED (permanent failure, kind 'stalled') "
                         "after K consecutive chunk residuals without "
                         "a new minimum — catches eps set below the "
                         "dtype's reachable precision floor")
    ap.add_argument("--drift-tolerance", type=float, default=None,
                    metavar="F",
                    help="supervised runs: trip the progress guard "
                         "when grid min/max/heat content escapes the "
                         "initial envelope by more than fraction F "
                         "(maximum principle — catches finite "
                         "corruption the NaN guard is blind to)")
    ap.add_argument("--max-retries", type=int, default=3, metavar="N",
                    help="supervisor rollback-retry budget for "
                         "transient faults (guard trips, retryable "
                         "dispatch errors); exceeding it halts with a "
                         "permanent-failure diagnosis")
    ap.add_argument("--barrier-timeout", type=float, default=60.0,
                    metavar="S",
                    help="multi-process supervised runs: seconds a "
                         "chunk-boundary consensus exchange waits on a "
                         "peer whose heartbeat has gone static before "
                         "declaring it lost (peer_lost preemption with "
                         "an elastic resume command); single-process "
                         "runs ignore it")
    ap.add_argument("--keep-checkpoints", type=int, default=3,
                    metavar="N",
                    help="checkpoint generations the supervisor "
                         "retains (older ones are pruned)")
    ap.add_argument("--no-async-checkpoint", action="store_true",
                    help="supervised runs: save checkpoints "
                         "synchronously at the boundary instead of "
                         "through the background writer (async is the "
                         "default: the gather + finite-verify + atomic "
                         "commit overlap the next chunks' compute, and "
                         "rollback/exit barriers drain in-flight saves "
                         "— committed bytes are identical either way)")
    ap.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace of the run")
    ap.add_argument("--trace", dest="profile", metavar="DIR",
                    help="alias for --profile (the run-book name: view "
                         "with XProf/Perfetto/TensorBoard; compiled "
                         "phases appear under heat:* annotations and "
                         "the heat_* kernel names)")
    ap.add_argument("--metrics", default=None, metavar="FILE",
                    help="append one JSONL telemetry event per stream "
                         "chunk / supervisor action to FILE (schema-"
                         "versioned: run-header, per-chunk throughput, "
                         "checkpoint latency, guard/retry lifecycle — "
                         "summarize with tools/metrics_report.py). "
                         "Observation-only: compiled programs and "
                         "results are bitwise the uninstrumented "
                         "run's")
    ap.add_argument("--heartbeat", default=None, metavar="FILE",
                    help="atomically rewrite FILE with a small liveness "
                         "JSON document ({step, last_event, residual, "
                         "...}) on every telemetry event, for external "
                         "probes of supervised runs")
    ap.add_argument("--monitor-hint", action="store_true",
                    help="print the tools/monitor.py invocation that "
                         "watches this run's --heartbeat/--metrics "
                         "files (also rides the printed resume "
                         "command of supervised runs)")
    ap.add_argument("--explain", action="store_true",
                    help="print the resolved execution path (backend, "
                         "kernel pick, mesh) and exit without running")
    ap.add_argument("--quiet", action="store_true")
    return ap


def _parse_mesh(arg: Optional[str], ndim: int, grid_shape=None,
                dtype="float32"):
    if arg is None:
        return None
    import jax

    if arg == "auto":
        from parallel_heat_tpu.parallel.mesh import (
            pick_mesh_shape, pick_mesh_shape_scored)

        if grid_shape is not None and ndim in (2, 3):
            # Grid-aware factorization: in 3D the kernel cost model
            # prefers z-free meshes (the lane-pad asymmetry; measured
            # +20-40% per device at 512^3/8 — REPORT §4d); in 2D it
            # breaks near-ties toward the narrower block shape
            # (measured +7% at the 32768^2 bf16 decompositions —
            # REPORT §4b.1 follow-up, round 4).
            return pick_mesh_shape_scored(len(jax.devices()),
                                          grid_shape, dtype)
        return pick_mesh_shape(len(jax.devices()), ndim)
    try:
        shape = tuple(int(t) for t in arg.split(","))
    except ValueError:
        raise SystemExit(f"invalid --mesh {arg!r}: expected e.g. '2,4'")
    return shape


# Service subcommands forwarded to the heatd CLI: `python -m
# parallel_heat_tpu serve/submit/status/cancel/drain ...` is the same
# surface as the `heatd` console script (service/cli.py).
_SERVICE_COMMANDS = ("serve", "submit", "status", "cancel", "drain",
                     "fleet-init", "fleet-serve", "fleet-submit",
                     "fleet-status", "metrics-serve")


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "tune":
        # Offline measured autotuning (tune/search.py): searches are
        # driven here, never inside a solve.
        from parallel_heat_tpu.tune.search import main as tune_main

        return tune_main(argv[1:])
    if argv and argv[0] in _SERVICE_COMMANDS:
        from parallel_heat_tpu.service.cli import main as heatd_main

        return heatd_main(argv)
    args = build_parser().parse_args(argv)

    from parallel_heat_tpu import HeatConfig, solve
    from parallel_heat_tpu.solver import make_initial_grid

    if args.dtype == "float64":
        # Must happen before any trace; validate() rejects f64 without
        # x64 mode (JAX would silently compute in f32 otherwise).
        import jax

        jax.config.update("jax_enable_x64", True)
    ndim = 3 if args.nz is not None else 2
    grid = ((args.nx, args.ny, args.nz) if ndim == 3
            else (args.nx, args.ny))
    mesh_shape = _parse_mesh(args.mesh, ndim, grid_shape=grid,
                             dtype=args.dtype)
    if args.halo_depth == "auto":
        # Thin alias for the library default: halo_depth=None lets the
        # solver resolve the depth (solver._resolve_halo_depth); the
        # resolution is visible via --explain.
        halo_depth = None
    else:
        try:
            halo_depth = int(args.halo_depth)
        except ValueError:
            print(f"error: --halo-depth must be an integer or 'auto', "
                  f"got {args.halo_depth!r}", file=sys.stderr)
            return 2
    if args.pipeline_depth == "auto":
        # Same alias pattern as --halo-depth: None lets solve_stream
        # resolve (solver.resolved_pipeline_depth: 2 fixed-step on an
        # accelerator, 1 otherwise).
        pipeline_depth = None
    else:
        try:
            pipeline_depth = int(args.pipeline_depth)
        except ValueError:
            print(f"error: --pipeline-depth must be an integer or "
                  f"'auto', got {args.pipeline_depth!r}",
                  file=sys.stderr)
            return 2
    config = HeatConfig(
        nx=args.nx, ny=args.ny, nz=args.nz,
        cx=args.cx, cy=args.cy, cz=args.cz,
        steps=args.steps, converge=args.converge, eps=args.eps,
        check_interval=args.check_interval, dtype=args.dtype,
        backend=args.backend, mesh_shape=mesh_shape,
        overlap=not args.no_overlap, halo_depth=halo_depth,
        halo_overlap=(None if args.halo_overlap == "auto"
                      else args.halo_overlap),
        accumulate=args.accumulate, guard_interval=args.guard_interval,
        diag_interval=args.diag_interval, pipeline_depth=pipeline_depth,
        scheme=args.scheme,
        # mg_* flags default to the config's own defaults — only
        # explicit CLI values override (validate() rejects non-default
        # mg knobs on explicit-scheme runs, so the None-passthrough
        # keeps `--scheme explicit` clean).
        **{k: v for k, v in (("mg_tol", args.mg_tol),
                             ("mg_cycles", args.mg_cycles),
                             ("mg_smooth", args.mg_smooth),
                             ("mg_levels", args.mg_levels),
                             ("mg_partition", args.mg_partition))
           if v is not None},
    )
    try:
        config.validate()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.explain:
        from parallel_heat_tpu.solver import explain

        for key, val in explain(config, ensemble=args.ensemble).items():
            print(f"{key}: {val}")
        return 0
    if args.checkpoint_every is not None:
        # Validate before any side effect (banner, resume load, file
        # writes) so a pure argument error leaves nothing behind.
        if not args.checkpoint:
            print("error: --checkpoint-every requires --checkpoint",
                  file=sys.stderr)
            return 2
        if args.checkpoint_every < 1:
            print(f"error: --checkpoint-every must be >= 1, got "
                  f"{args.checkpoint_every}", file=sys.stderr)
            return 2
    if args.supervise and not args.checkpoint:
        print("error: --supervise requires --checkpoint (the retained-"
              "generation stem)", file=sys.stderr)
        return 2
    if args.keep_checkpoints < 1:
        print(f"error: --keep-checkpoints must be >= 1, got "
              f"{args.keep_checkpoints}", file=sys.stderr)
        return 2
    if args.max_retries < 0:
        print(f"error: --max-retries must be >= 0, got "
              f"{args.max_retries}", file=sys.stderr)
        return 2
    if args.barrier_timeout <= 0:
        print(f"error: --barrier-timeout must be > 0 seconds, got "
              f"{args.barrier_timeout}", file=sys.stderr)
        return 2
    if (args.stall_windows is not None
            or args.drift_tolerance is not None) and not args.supervise:
        print("error: --stall-windows/--drift-tolerance configure the "
              "supervisor's progress guard and require --supervise",
              file=sys.stderr)
        return 2
    if args.stall_windows is not None and not args.converge:
        # The stall classifier reads chunk residuals, which only
        # converge mode computes — accepting the flag on a fixed-step
        # run would leave the guard silently inert.
        print("error: --stall-windows classifies residual stalls and "
              "requires --converge (fixed-step runs compute no "
              "residual to classify)", file=sys.stderr)
        return 2
    if args.monitor_hint and not (args.metrics or args.heartbeat):
        print("error: --monitor-hint requires --metrics and/or "
              "--heartbeat (the files the monitor watches)",
              file=sys.stderr)
        return 2
    if args.resume == "auto" and not args.checkpoint:
        print("error: --resume auto requires --checkpoint (the stem "
              "whose newest generation to resume)", file=sys.stderr)
        return 2
    if args.ensemble is not None:
        return _run_ensemble(args, config)

    say = (lambda *a: None) if args.quiet else print
    mesh = config.mesh_or_unit()
    n_dev = 1
    for d in mesh:
        n_dev *= d
    say(f"Starting parallel_heat_tpu on {n_dev} device(s), mesh {mesh}.")
    if config.converge:
        say(f"Grid size: {'x'.join(map(str, config.shape))}  "
            f"Time steps: - (converge, eps={config.eps:g})")
    else:
        say(f"Grid size: {'x'.join(map(str, config.shape))}  "
            f"Time steps: {config.steps}")

    initial = None
    start_step = 0
    resume_src = args.resume
    if resume_src == "auto":
        from parallel_heat_tpu.utils.checkpoint import latest_checkpoint

        resume_src = latest_checkpoint(args.checkpoint)
        if resume_src is None:
            say("No checkpoint found for --resume auto; starting fresh.")
    if resume_src:
        from parallel_heat_tpu.utils.checkpoint import load_checkpoint

        try:
            initial, start_step, _ = load_checkpoint(resume_src, config)
        except (OSError, ValueError, EOFError, KeyError) as e:
            print(f"error: cannot resume from {resume_src}: {e}",
                  file=sys.stderr)
            return 2
        say(f"Resumed from {resume_src} at step {start_step}.")
        remaining = max(0, config.steps - start_step)
        config = config.replace(steps=remaining)

    if args.initial_out:
        written = _write_grid(args.initial_out, initial if initial is not None
                              else make_initial_grid(config))
        say(f"Initial grid written to {written}")

    telemetry = None
    if args.metrics or args.heartbeat:
        from parallel_heat_tpu.utils.telemetry import Telemetry

        # Append mode: a resumed invocation continues the same JSONL
        # stream (tools/metrics_report.py reads multi-segment files).
        # async_io: event serialization + heartbeat renames go through
        # the bounded-queue writer thread, so the run loop (and the
        # device behind it) never blocks on the metrics filesystem.
        telemetry = Telemetry(args.metrics, heartbeat=args.heartbeat,
                              async_io=True)
        # Resumed segments report ABSOLUTE steps, continuing the first
        # segment's numbering (the supervisor re-sets this per rollback
        # segment itself).
        telemetry.step_offset = start_step
        if args.monitor_hint:
            import shlex

            hint = ["python", "tools/monitor.py"]
            if args.heartbeat:
                # The sink may have sharded the paths (.pN suffix on
                # multi-process runs) — point the monitor at the files
                # actually written.
                hint += ["--heartbeat", telemetry.heartbeat_path]
            if args.metrics:
                hint += ["--metrics", telemetry.path]
            # Quote each token (paths with spaces) so the printed line
            # survives a copy-paste, like the supervisor's resume
            # command does. print, not say: the flag is an explicit
            # request for this one line, and --quiet must not swallow
            # it (scripted launches pair exactly these two flags).
            print("Monitor with: " + " ".join(shlex.quote(t)
                                              for t in hint))

    sup_state = {}

    def _run():
        if args.supervise:
            from parallel_heat_tpu.supervisor import (
                SupervisorPolicy, default_checkpoint_every,
                run_supervised)

            # The default cadence satisfies the supervisor's f32chunk
            # K-alignment requirement; explicit misaligned flags still
            # fail loudly below.
            every = (args.checkpoint_every
                     or default_checkpoint_every(config))
            policy = SupervisorPolicy(
                checkpoint_every=every,
                keep_checkpoints=args.keep_checkpoints,
                guard_interval=args.guard_interval,
                max_retries=args.max_retries,
                layout=args.checkpoint_layout,
                stall_windows=args.stall_windows,
                drift_tolerance=args.drift_tolerance,
                async_checkpoint=not args.no_async_checkpoint,
                barrier_timeout_s=args.barrier_timeout,
            )
            # Flags the resumed invocation must repeat to deliver what
            # this one promised. NOT --initial-out: the t=0 grid was
            # already written by this invocation, and a resumed run's
            # `initial` is the checkpoint state — repeating the flag
            # would overwrite the true initial condition with it.
            extra = []
            if args.out:
                extra += ["--out", args.out]
            if args.metrics:
                # The sink appends, so the resumed run continues the
                # same event stream (and liveness probe).
                extra += ["--metrics", args.metrics]
            if args.heartbeat:
                extra += ["--heartbeat", args.heartbeat]
            if args.monitor_hint:
                extra += ["--monitor-hint"]
            if args.quiet:
                extra += ["--quiet"]
            sres = run_supervised(config, args.checkpoint, policy=policy,
                                  initial=initial, start_step=start_step,
                                  say=say, resume_extra_flags=tuple(extra),
                                  telemetry=telemetry)
            sup_state["sres"] = sres
            if sres.result is None and not sres.interrupted:
                # Zero steps remaining (e.g. --resume auto of a finished
                # run): produce the grid for reporting/--out anyway.
                return solve(config, initial=initial)
            return sres.result
        if args.checkpoint_every is None:
            if telemetry is None:
                return solve(config, initial=initial)
            # One-chunk stream: same compiled program as solve()
            # (bitwise — SEMANTICS.md stream-boundary contract), but
            # the run leaves its header + chunk telemetry behind.
            from parallel_heat_tpu.solver import solve_stream

            result = None
            for result in solve_stream(config, initial=initial,
                                       telemetry=telemetry):
                pass
            if result is None:  # steps == 0
                result = solve(config, initial=initial)
            return result
        # Periodic-checkpoint driver: chunked solve, snapshot after
        # every chunk (overwriting, so a crash resumes from the latest).
        from parallel_heat_tpu.solver import solve_stream
        from parallel_heat_tpu.utils.checkpoint import save_checkpoint

        import time as _time

        result = None
        n_saves = 0
        for result in solve_stream(config, initial=initial,
                                   chunk_steps=args.checkpoint_every,
                                   telemetry=telemetry):
            t_save = _time.perf_counter()
            written = save_checkpoint(args.checkpoint, result.grid,
                                      start_step + result.steps_run, config,
                                      layout=args.checkpoint_layout)
            n_saves += 1
            if telemetry is not None:
                # kept=1: this driver overwrites one snapshot (the
                # supervisor's retained generations report their real
                # keep count).
                telemetry.emit("checkpoint_save",
                               step=start_step + result.steps_run,
                               path=str(written),
                               wall_s=_time.perf_counter() - t_save,
                               kept=1, generation=n_saves)
            say(f"Checkpoint at step {start_step + result.steps_run} "
                f"-> {written}")
        if result is None:  # steps == 0
            result = solve(config, initial=initial)
        return result

    from parallel_heat_tpu.supervisor import (
        EXIT_PERMANENT_FAILURE, EXIT_PREEMPTED, PermanentFailure)

    try:
        try:
            if args.profile:
                import jax

                with jax.profiler.trace(args.profile):
                    result = _run()
                say(f"Profiler trace written to {args.profile}")
            else:
                result = _run()
        except PermanentFailure as e:
            # The supervisor's no-retry verdict: diagnosis on stderr,
            # the newest verified checkpoint is still on disk for
            # inspection (run_end telemetry was already emitted).
            print(f"error: permanent failure: {e.diagnosis}",
                  file=sys.stderr)
            return EXIT_PERMANENT_FAILURE
        except ValueError as e:
            if not args.supervise:
                raise
            # Bad supervisor flag combination (e.g. a cadence that
            # breaks the f32chunk K-alignment contract): one-line CLI
            # error like every other argument problem, not a traceback.
            print(f"error: {e}", file=sys.stderr)
            return 2

        sres = sup_state.get("sres")
        if sres is not None and sres.interrupted:
            # Preemption-style exit: the supervisor flushed a checkpoint
            # and `say` printed the resume command. Distinct exit code
            # so restart loops can tell "preempted, resume me" from
            # success.
            return EXIT_PREEMPTED
        if telemetry is not None and sres is None:
            # Unsupervised runs end here (the supervisor emits its own
            # run_end, in every outcome).
            telemetry.run_end(outcome="complete",
                              steps_done=start_step + result.steps_run,
                              wall_s=result.elapsed_s)
    finally:
        if telemetry is not None:
            telemetry.close()

    # Supervised runs report the supervisor's absolute count (a rollback
    # segment's stream restarts its own steps_run from 0).
    total_steps = (sres.steps_done if sres is not None
                   else start_step + result.steps_run)
    if config.converge:
        if result.converged:
            say(f"Converged after {total_steps} steps")
        else:
            say(f"Did not converge (ran {total_steps} steps, "
                f"residual {result.residual:g})")
    say(f"Elapsed time {result.elapsed_s:.6f} secs")

    if args.out:
        written = _write_grid(args.out, result.grid)
        say(f"Final grid written to {written}")
    if args.checkpoint and not args.supervise:
        # Supervised runs already wrote their final retained generation;
        # a plain-stem save here would shadow the generation family.
        from parallel_heat_tpu.utils.checkpoint import save_checkpoint

        written = save_checkpoint(args.checkpoint, result.grid,
                                  total_steps, config,
                                  layout=args.checkpoint_layout)
        say(f"Checkpoint written to {written}")
    return 0


def _run_ensemble(args, config) -> int:
    """The --ensemble B path: one batched program, per-member results.
    Ensemble generations (not solo checkpoints) back --supervise, so
    the whole ensemble rolls back / resumes bit-exactly per member."""
    from parallel_heat_tpu.supervisor import (
        EXIT_PERMANENT_FAILURE, EXIT_PREEMPTED, PermanentFailure)

    say = (lambda *a: None) if args.quiet else print
    if args.ensemble < 1:
        print(f"error: --ensemble must be >= 1, got {args.ensemble}",
              file=sys.stderr)
        return 2
    if args.mesh:
        print("error: --ensemble is single-device per member "
              "(--mesh runs solo)", file=sys.stderr)
        return 2
    if args.resume or args.initial_out:
        print("error: --ensemble does not take --resume/--initial-out "
              "(a supervised ensemble resumes from its own ensemble "
              "generations automatically)", file=sys.stderr)
        return 2
    telemetry = None
    if args.metrics or args.heartbeat:
        from parallel_heat_tpu.utils.telemetry import Telemetry

        telemetry = Telemetry(args.metrics, heartbeat=args.heartbeat,
                              async_io=True)
    say(f"Starting parallel_heat_tpu ensemble: {args.ensemble} "
        f"member(s) of {'x'.join(map(str, config.shape))}, "
        + (f"converge eps={config.eps:g}" if config.converge
           else f"{config.steps} steps"))
    try:
        try:
            if args.supervise:
                from parallel_heat_tpu.ensemble.supervised import (
                    run_ensemble_supervised)
                from parallel_heat_tpu.supervisor import (
                    SupervisorPolicy, default_checkpoint_every)

                policy = SupervisorPolicy(
                    checkpoint_every=(args.checkpoint_every
                                      or default_checkpoint_every(config)),
                    keep_checkpoints=args.keep_checkpoints,
                    guard_interval=args.guard_interval,
                    max_retries=args.max_retries)
                sres = run_ensemble_supervised(
                    config, args.ensemble, args.checkpoint,
                    policy=policy, telemetry=telemetry, say=say)
                if sres.interrupted:
                    return EXIT_PREEMPTED
                result = sres.result
            else:
                from parallel_heat_tpu.ensemble.engine import (
                    EnsembleSolver)

                result = EnsembleSolver(config, args.ensemble).solve(
                    telemetry=telemetry)
                if telemetry is not None:
                    telemetry.run_end(
                        outcome="complete",
                        steps_done=int(result.steps_run.max()),
                        wall_s=result.elapsed_s)
        except PermanentFailure as e:
            print(f"error: permanent failure: {e.diagnosis}",
                  file=sys.stderr)
            return EXIT_PERMANENT_FAILURE
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    finally:
        if telemetry is not None:
            telemetry.close()
    for i in range(result.members):
        line = f"member {i}: {int(result.steps_run[i])} steps"
        if result.converged is not None:
            line += (f", converged={bool(result.converged[i])}, "
                     f"residual={float(result.residual[i]):g}")
        say(line)
    if result.compactions:
        say("compactions: " + ", ".join(
            f"step {k}: {a}->{b}" for k, a, b in result.compactions))
    say(f"Elapsed time {result.elapsed_s:.6f} secs")
    if args.out:
        import numpy as np

        path = args.out
        if not path.endswith(".npy"):
            path += ".npy"
        np.save(path, np.asarray(result.grids))
        say(f"Stacked member grids written to {path}")
    return 0


def _write_grid(path: str, grid) -> str:
    """Write the grid; returns the path actually written (3D grids have
    no .dat representation and are stored as .npy)."""
    import numpy as np

    path = str(path)
    arr = np.asarray(grid)
    if path.endswith(".npy") or arr.ndim != 2:
        if not path.endswith(".npy"):
            path += ".npy"
        np.save(path, arr)
        return path
    from parallel_heat_tpu.utils.io import write_dat

    write_dat(path, arr)
    return path


if __name__ == "__main__":
    raise SystemExit(main())
