"""Batched ensemble engine: many independent grids per chip.

ROADMAP item 1's throughput lever: B independent member grids sharing
one semantic :class:`~parallel_heat_tpu.config.HeatConfig` are stacked
on a leading member axis and advanced by ONE compiled program per
dispatch — vmap over the solver's jnp multistep family on the general
path, the member-batched Pallas kernel M (``ops/batched.py``) on the
hot single-chip path. Converge mode computes per-member epsilon
verdicts with a fused batched reduction, freezes finished members by
masked update, and compacts the live batch when the live fraction
drops below the configured threshold (``EnsembleConfig``), so
stragglers stop paying for finished work.

Contracts (SEMANTICS.md "Ensemble"):

- **member independence / parity** — a member of a batched run is
  bitwise the single-grid ``solve()`` of the same spec on the same
  resolved path (pinned by ``tests/test_ensemble.py``);
- **compaction invariance** — a member's trajectory does not depend on
  when (or whether) other members finish;
- **observation-only batched diagnostics** — per-member guard verdicts
  and grid stats read between dispatches and never join the compiled
  programs (the solver's guard contract, member-axis extended).

``ensemble/checkpoint.py`` persists per-member manifests under one
generation; ``ensemble/supervised.py`` wraps the engine in the
checkpoint/guard/rollback loop; ``service/`` packs compatible queued
jobs into one ensemble dispatch (``heatd serve --pack``).
"""

from parallel_heat_tpu.ensemble.engine import (  # noqa: F401
    EnsembleResult,
    EnsembleSolver,
    ensemble_all_finite,
    ensemble_grid_stats,
    ensemble_path,
    packable,
)
from parallel_heat_tpu.ensemble.checkpoint import (  # noqa: F401
    latest_ensemble_checkpoint,
    load_ensemble_checkpoint,
    save_ensemble_generation,
)
from parallel_heat_tpu.ensemble.supervised import (  # noqa: F401
    EnsembleSupervisorResult,
    run_ensemble_supervised,
)
