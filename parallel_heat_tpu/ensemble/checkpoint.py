"""Ensemble checkpoint generations: per-member manifests, one commit.

One ensemble generation is ONE rename-committed ``.npz`` holding the
full-order member state at a global step boundary — the stacked
``(B, *shape)`` grids plus the per-member manifest (steps, converged,
residual for every member), the solver config and the ensemble config.
The write discipline is ``utils/checkpoint.py``'s exactly: pid-unique
dotted temp names that discovery can never match, fsync + rename +
dirsync publish, so a SIGKILL at any point leaves either the previous
complete generation or the new complete one — never a torn file.

Because a generation stores the FULL-ORDER state (parked members
included, bit-exact), ``ensemble/supervised.py`` can roll back or
resume the whole ensemble from any retained generation and every
member continues its trajectory bit-exactly, regardless of the
compaction history at save time (SEMANTICS.md "Ensemble").
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Optional, Tuple

import numpy as np

from parallel_heat_tpu.config import EnsembleConfig, HeatConfig
from parallel_heat_tpu.utils.checkpoint import _fsync_replace

_FORMAT_VERSION = 1
# stem.eg<step>.npz — 12 digits zero-padded so lexicographic order is
# numeric order (the same trick utils/checkpoint's generations use).
_GEN_RE = re.compile(r"\.eg(\d{12})\.npz$")


def _gen_path(stem: str, k: int) -> str:
    return f"{stem}.eg{int(k):012d}.npz"


def ensemble_generation_paths(stem: str) -> list:
    """Committed generation files of ``stem``, oldest first. Temps
    (dotted names) never match the pattern — a SIGKILLed writer's
    debris is invisible here."""
    out = []
    for p in glob.glob(f"{stem}.eg*.npz"):
        m = _GEN_RE.search(os.path.basename(p))
        if m and not os.path.basename(p).startswith("."):
            out.append((int(m.group(1)), p))
    return [p for _k, p in sorted(out)]


def latest_ensemble_checkpoint(stem: str) -> Optional[str]:
    """Newest committed generation of ``stem``, or None."""
    paths = ensemble_generation_paths(stem)
    return paths[-1] if paths else None


def save_ensemble_generation(stem: str, state: dict,
                             config: HeatConfig,
                             ensemble: EnsembleConfig,
                             keep: int = 3) -> str:
    """Commit one generation from an assembled engine state
    (``{"k", "grids", "done", "res", "steps"}`` — the
    ``EnsembleBoundary.assemble()`` payload) and prune generations
    beyond the newest ``keep``. Returns the committed path."""
    if keep < 1:
        raise ValueError(f"keep must be >= 1, got {keep}")
    stem = str(stem)
    parent = os.path.dirname(os.path.abspath(stem))
    if parent:
        os.makedirs(parent, exist_ok=True)
    k = int(state["k"])
    grids = np.asarray(state["grids"])
    steps = np.asarray(state["steps"], np.int64)
    done = np.asarray(state["done"], bool)
    res = np.asarray(state["res"], np.float64)
    manifest = [{"member": i, "steps": int(steps[i]),
                 "converged": bool(done[i]),
                 "residual": (None if not np.isfinite(res[i])
                              else float(res[i]))}
                for i in range(grids.shape[0])]
    path = _gen_path(stem, k)
    tmp = os.path.join(parent or ".",
                       f".tmp-{os.getpid()}-{os.path.basename(path)}")
    try:
        np.savez(
            tmp,
            grids=grids,
            member_steps=steps,
            member_done=done,
            member_residual=res,
            k=np.int64(k),
            manifest=np.frombuffer(
                json.dumps(manifest).encode(), dtype=np.uint8),
            config=np.frombuffer(
                config.to_json().encode(), dtype=np.uint8),
            ensemble=np.frombuffer(
                ensemble.to_json().encode(), dtype=np.uint8),
            version=np.int64(_FORMAT_VERSION),
        )
        # np.savez appends .npz to names without it; the dotted tmp
        # already ends in .npz via the basename.
        _fsync_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    for old in ensemble_generation_paths(stem)[:-keep]:
        try:
            os.unlink(old)
        except OSError:
            pass
    return path


def load_ensemble_checkpoint(path: str,
                             expect_config: Optional[HeatConfig] = None
                             ) -> Tuple[dict, HeatConfig,
                                        EnsembleConfig, list]:
    """Load one generation -> ``(state, config, ensemble, manifest)``
    with ``state`` in the engine's resumable shape. When
    ``expect_config`` is given, the SEMANTIC fields of the saved
    config must match (the same self-description check the solver
    checkpoints make — resuming a different simulation is an error,
    not a silent reinterpretation)."""
    with np.load(path) as z:
        grids = z["grids"]
        state = {"k": int(z["k"]),
                 "grids": grids,
                 "done": np.asarray(z["member_done"], bool),
                 "res": np.asarray(z["member_residual"], np.float64),
                 "steps": np.asarray(z["member_steps"], np.int64)}
        config = HeatConfig.from_json(bytes(z["config"]).decode())
        ensemble = EnsembleConfig.from_json(bytes(z["ensemble"]).decode())
        manifest = json.loads(bytes(z["manifest"]).decode())
    if grids.shape[0] != ensemble.members:
        raise ValueError(
            f"ensemble checkpoint {path!r} holds {grids.shape[0]} "
            f"members but its manifest says {ensemble.members}")
    if expect_config is not None:
        from parallel_heat_tpu.config import SEMANTIC_FIELDS

        for f in SEMANTIC_FIELDS:
            if f == "steps":
                continue  # the target may legitimately differ on resume
            if getattr(config, f) != getattr(expect_config, f):
                raise ValueError(
                    f"ensemble checkpoint {path!r} was written for "
                    f"{f}={getattr(config, f)!r}, the resuming config "
                    f"has {f}={getattr(expect_config, f)!r}")
    return state, config, ensemble, manifest
