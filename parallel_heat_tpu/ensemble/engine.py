"""The batched ensemble engine proper (see the package docstring).

Batched runner construction mirrors ``solver._build_runner``'s
discipline: runners are lru_cached on the OBSERVER-FREE solver config
(``solver._observer_free``) plus the ORCHESTRATION-FREE ensemble
extent (``EnsembleConfig.orchestration_free`` — in practice just B),
so telemetry, guard/diag intervals, compaction thresholds and window
cadences can never fork a compiled batched program (heatlint HL101
audits both partitions).
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from parallel_heat_tpu.config import EnsembleConfig, HeatConfig
from parallel_heat_tpu.solver import (
    _observer_free,
    _resolve_backend,
    _single_multistep,
    make_initial_grid,
)


class EnsembleInterrupted(Exception):
    """Raised by an ``on_boundary`` callback to stop the run at a
    consistent boundary; carries the assembled full-order state so the
    caller (the supervised loop) can flush it. ``reason`` is the
    interrupt vocabulary of the solo supervisor (a signal name or a
    flag-hook string such as ``"deadline"``)."""

    def __init__(self, reason: str, state: dict):
        super().__init__(reason)
        self.reason = reason
        self.state = state


# ---------------------------------------------------------------------------
# Batched observation reductions (member-axis analogues of
# solver.grid_all_finite / solver.grid_stats — observation-only)
# ---------------------------------------------------------------------------

@jax.jit
def _ens_all_finite(u):
    # One fused reduction pass, per member: (B, ...) -> (B,) bools.
    return jnp.isfinite(u).reshape(u.shape[0], -1).all(axis=1)


def ensemble_all_finite(grids) -> np.ndarray:
    """Per-member non-finite guard: ``(B,)`` bools, one fused pass.
    Observation-only, exactly like :func:`solver.grid_all_finite`."""
    with jax.profiler.TraceAnnotation("heat:ens_guard"):
        return np.asarray(_ens_all_finite(grids))


@jax.jit
def _ens_stats_solo(u):
    B = u.shape[0]
    flat = u.reshape(B, -1)
    acc = (flat if jnp.dtype(u.dtype).itemsize >= 4
           else flat.astype(jnp.float32))
    return (jnp.min(flat, axis=1), jnp.max(flat, axis=1),
            jnp.sum(acc, axis=1))


@jax.jit
def _ens_stats_delta(u, prev):
    B = u.shape[0]
    flat = u.reshape(B, -1)
    acc = (flat if jnp.dtype(u.dtype).itemsize >= 4
           else flat.astype(jnp.float32))
    d = flat.astype(acc.dtype) - prev.reshape(B, -1).astype(acc.dtype)
    return (jnp.min(flat, axis=1), jnp.max(flat, axis=1),
            jnp.sum(acc, axis=1),
            jnp.sqrt(jnp.sum(d * d, axis=1)),
            jnp.max(jnp.abs(d), axis=1))


def ensemble_grid_stats(grids, prev=None) -> List[dict]:
    """Per-member fused grid diagnostics: a list of B dicts with the
    :func:`solver.grid_stats` keys. Observation-only; note the batched
    ``heat`` sums may differ in rounding from a solo ``grid_stats``
    (reduction order) — diagnostics are observational floats, never
    part of the bitwise member contract (SEMANTICS.md "Ensemble")."""
    with jax.profiler.TraceAnnotation("heat:ens_diag"):
        if prev is None:
            mn, mx, heat = _ens_stats_solo(grids)
            l2 = linf = None
        else:
            mn, mx, heat, l2, linf = _ens_stats_delta(grids, prev)
        out = []
        for i in range(int(grids.shape[0])):
            out.append({"min": float(mn[i]), "max": float(mx[i]),
                        "heat": float(heat[i]),
                        "update_l2": (float(l2[i]) if l2 is not None
                                      else None),
                        "update_linf": (float(linf[i])
                                        if linf is not None else None)})
        return out


# ---------------------------------------------------------------------------
# Path selection
# ---------------------------------------------------------------------------

def ensemble_path(config: HeatConfig) -> str:
    """``"M"`` (member-batched Pallas kernel) or ``"vmap"`` (vmap over
    the jnp multistep family) for ``config``'s resolved backend. The
    ONE decision site — the runner builder executes it and
    ``solver.explain(..., ensemble=B)`` reports it."""
    if config.scheme != "explicit":
        # Implicit V-cycle steps batch over members via vmap — the
        # per-member while_loop latches each member's iterate at ITS
        # convergence cycle (jax's while batching rule applies the
        # select that freezes finished members), so the batched
        # member is bitwise the solo member; kernel M is an explicit
        # Jacobi kernel and does not apply.
        return "vmap"
    backend = _resolve_backend(config)
    if backend == "pallas" and config.ndim == 2:
        from parallel_heat_tpu.ops import batched

        return batched.pick_ensemble_2d(config.shape, config.dtype,
                                        config.accumulate)
    return "vmap"


def packable(config: HeatConfig):
    """``(ok, reason)`` — may ``heatd`` coalesce jobs of this config
    into one ensemble dispatch under the bitwise member-parity
    contract? True exactly when the batched path computes the same
    kernel the solo ``solve()`` would: the jnp backend (vmap is
    member-bitwise by construction), or the Pallas backend where the
    solo picker chooses the VMEM-resident kernel A (kernel M mirrors
    it operation for operation). Everything else — sharded meshes,
    streaming Pallas kernels with no batched twin — runs solo."""
    try:
        config = config.validate()
    except ValueError as e:
        return False, f"invalid config: {e}"
    if any(d > 1 for d in config.mesh_or_unit()):
        return False, "sharded configs run solo (no member axis across a mesh)"
    backend = _resolve_backend(config)
    if config.scheme != "explicit":
        # Same backend discipline as the explicit arm below: the
        # batched implicit path is vmap over the JNP V-cycle, so the
        # member-bitwise claim holds only where the solo solve uses
        # that spelling too. A pallas-backend solo implicit solve
        # takes the pallas transfer kernels — bitwise the jnp
        # spelling in interpreter mode but NOT pinned on hardware —
        # so those jobs run solo rather than lean on unpinned
        # cross-backend parity.
        if backend == "jnp":
            return True, ("vmap over the implicit V-cycle multistep "
                          "(member-bitwise: the while batching rule "
                          "latches each member at its own cycle "
                          "verdict)")
        return False, ("solo pallas-backend implicit solves use the "
                       "pallas transfer kernels; the batched vmap "
                       "path's jnp spelling has no pinned bitwise "
                       "twin on hardware — runs solo")
    if backend == "jnp":
        return True, "vmap over the jnp multistep family (member-bitwise)"
    path = ensemble_path(config)
    if path == "M":
        return True, "member-batched kernel M (bitwise the solo kernel A)"
    return False, ("solo Pallas path has no member-bitwise batched "
                   "twin (streaming kernel, or kernel M's tighter "
                   "VMEM budget declined the geometry)")


@functools.lru_cache(maxsize=64)
def _batched_multistep(config: HeatConfig, batch: int):
    """(multi_step(u, k), multi_step_residual(u, k)) on a member-
    batched ``(B, *shape)`` state, plus the path label. ``config``
    must be observer-free and validated (the cache keys on it)."""
    path = ensemble_path(config)
    if path == "M":
        from parallel_heat_tpu.ops import batched

        ms, msr = batched.ensemble_multistep(
            batch, config.shape, config.dtype, config.cx, config.cy)
        return ms, msr, "M"
    ms1, msr1 = _single_multistep(config, "jnp")

    def ms(u, k):
        return jax.vmap(lambda uu: ms1(uu, k))(u)

    def msr(u, k):
        return jax.vmap(lambda uu: msr1(uu, k))(u)

    return ms, msr, "vmap"


# ---------------------------------------------------------------------------
# Runner builders (cached per observer-free config + member extent)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=128)
def _build_fixed_runner(config: HeatConfig, batch: int, steps: int):
    """jitted ``run(u) -> u`` advancing every member ``steps`` steps
    (one donated dispatch — the member-axis analogue of the solver's
    fixed-mode runner)."""
    ms, _, _ = _batched_multistep(config, batch)

    def run(u):
        return ms(u, steps) if steps > 0 else u

    return jax.jit(run, donate_argnums=0)


@functools.lru_cache(maxsize=128)
def _build_converge_runner(config: HeatConfig, batch: int, windows: int):
    """jitted ``run(u, done, res, steps_at, k) -> same`` advancing up
    to ``windows`` check windows with per-member freeze.

    Per window: ``multi_step_residual`` over the live batch, one fused
    per-member residual vector, members whose residual drops below eps
    latch their (residual, step) verdict and freeze (masked update —
    their grid bits never change again). The loop exits early when
    every member in the batch is done, so a fully-converged batch does
    not burn its remaining windows. ``k`` is the absolute step count
    the live members share (they advance in lockstep).
    """
    ms, msr, _ = _batched_multistep(config, batch)
    ci = config.check_interval
    eps = config.eps
    mask_shape = (batch,) + (1,) * config.ndim

    def cond(c):
        _u, done, _res, _steps_at, _k, w = c
        return jnp.logical_not(done.all()) & (w < windows)

    def body(c):
        u, done, res, steps_at, k, w = c
        u_new, r = msr(u, ci)
        k2 = k + ci
        keep = done.reshape(mask_shape)
        u = jnp.where(keep, u, u_new)       # frozen members keep their bits
        res = jnp.where(done, res, r)       # latch at the converging window
        steps_at = jnp.where(done, steps_at, k2)
        done = done | (r < eps)
        return u, done, res, steps_at, k2, w + 1

    def run(u, done, res, steps_at, k):
        u, done, res, steps_at, k, _ = lax.while_loop(
            cond, body, (u, done, res, steps_at, k, jnp.int32(0)))
        return u, done, res, steps_at, k

    return jax.jit(run, donate_argnums=0)


@functools.lru_cache(maxsize=128)
def _build_masked_tail_runner(config: HeatConfig, batch: int, rem: int):
    """jitted masked tail: members not yet done run the ``rem``
    leftover steps past the last full check window (the solo loop's
    uninspected tail), frozen members pass through untouched."""
    ms, _, _ = _batched_multistep(config, batch)
    mask_shape = (batch,) + (1,) * config.ndim

    def run(u, done):
        u_new = ms(u, rem)
        return jnp.where(done.reshape(mask_shape), u, u_new)

    return jax.jit(run, donate_argnums=0)


# ---------------------------------------------------------------------------
# Results
# ---------------------------------------------------------------------------

@dataclass
class EnsembleResult:
    """Outcome of one ensemble run, in ORIGINAL member order (member i
    of the result is member i of the input, regardless of compaction
    history)."""

    grids: jax.Array                 # (B, *shape)
    steps_run: np.ndarray            # (B,) int64
    converged: Optional[np.ndarray]  # (B,) bool, converge mode only
    residual: Optional[np.ndarray]   # (B,) float, converge mode only
    elapsed_s: float
    # Per-member guard verdicts / diagnostics samples (observation-
    # only; None when the respective interval is unset).
    finite: Optional[np.ndarray] = None
    diagnostics: Optional[List[dict]] = None
    # (step, from_members, to_members) per compaction event.
    compactions: List[tuple] = field(default_factory=list)

    @property
    def members(self) -> int:
        return int(self.grids.shape[0])

    def member(self, i: int):
        """Member ``i``'s view as a solver :class:`HeatResult` — how
        the service fans packed results back to individual jobs."""
        from parallel_heat_tpu.solver import HeatResult

        return HeatResult(
            grid=self.grids[i], steps_run=int(self.steps_run[i]),
            converged=(bool(self.converged[i])
                       if self.converged is not None else None),
            residual=(float(self.residual[i])
                      if self.residual is not None else None),
            elapsed_s=self.elapsed_s,
            finite=(bool(self.finite[i])
                    if self.finite is not None else None),
            diagnostics=(self.diagnostics[i]
                         if self.diagnostics is not None else None))


@dataclass
class EnsembleBoundary:
    """What an ``on_boundary`` callback sees after each dispatch:
    global progress plus an ``assemble()`` hook producing the
    full-order resumable state (the supervised loop checkpoints it)."""

    step: int          # absolute steps the live members have run
    batch: int         # current (possibly compacted) batch extent
    live: int          # members still advancing
    done_total: int    # members finished (parked or frozen in-batch)
    live_grids: jax.Array  # the current (batch, *shape) state
    assemble: Callable[[], dict]  # full-order {"k","grids","done","res","steps"}
    # ORIGINAL member index of each position of the current batch —
    # after a compaction, position i is NOT member i; anything that
    # names members to a human (guard trips, diagnoses) must map
    # positions through this.
    order: tuple = ()


# ---------------------------------------------------------------------------
# The solver
# ---------------------------------------------------------------------------

class EnsembleSolver:
    """B independent members of one semantic config, one compiled
    program per dispatch. See the package docstring for the contracts
    and ``solver.explain(config, ensemble=B)`` for the resolved path.
    """

    def __init__(self, config: HeatConfig,
                 ensemble: Union[EnsembleConfig, int, None] = None):
        if ensemble is None:
            ensemble = EnsembleConfig()
        elif isinstance(ensemble, int):
            ensemble = EnsembleConfig(members=ensemble)
        self.config = config.validate()
        self.ensemble = ensemble.validate()
        if any(d > 1 for d in self.config.mesh_or_unit()):
            raise ValueError(
                "EnsembleSolver is single-device per member: sharded "
                "mesh_shape configs run solo (the member axis does not "
                "span a mesh)")
        # The observer-free config every runner cache keys on (HL101's
        # contract, member-axis edition).
        self._run_cfg = _observer_free(self.config)
        self.batch = self.ensemble.members

    # -- introspection ---------------------------------------------------

    def explain(self) -> dict:
        from parallel_heat_tpu.solver import explain

        return explain(self.config, ensemble=self.ensemble.members)

    @property
    def path(self) -> str:
        return ensemble_path(self._run_cfg)

    # -- state construction ----------------------------------------------

    def initial_grids(self, initials=None) -> jax.Array:
        """The stacked ``(B, *shape)`` start state. ``initials`` may be
        None (every member gets the model's initial condition), a
        single grid (broadcast to every member), or a stacked
        ``(B, *shape)`` array of per-member grids. Caller arrays are
        copied (runners donate their input)."""
        B = self.batch
        shape = self.config.shape
        dtype = jnp.dtype(self.config.dtype)
        if initials is None:
            one = make_initial_grid(self._run_cfg)
            return jax.block_until_ready(jnp.copy(
                jnp.broadcast_to(one.astype(dtype), (B,) + shape)))
        arr = initials
        if not isinstance(arr, jax.Array):
            arr = np.asarray(arr)
        if tuple(arr.shape) == shape:
            return jax.block_until_ready(jnp.copy(jnp.broadcast_to(
                jnp.asarray(arr).astype(dtype), (B,) + shape)))
        if tuple(arr.shape) != (B,) + shape:
            raise ValueError(
                f"initials shape {tuple(arr.shape)} matches neither the "
                f"member shape {shape} nor the stacked shape "
                f"{(B,) + shape}")
        return jax.block_until_ready(
            jnp.copy(jnp.asarray(arr).astype(dtype)))

    # -- the run ---------------------------------------------------------

    def solve(self, initials=None, telemetry=None,
              chunk_steps: Optional[int] = None,
              on_boundary: Optional[Callable] = None,
              state: Optional[dict] = None) -> EnsembleResult:
        """Run every member to completion; returns an
        :class:`EnsembleResult` in original member order.

        Fixed mode runs ONE dispatch (the whole step budget fused)
        unless ``chunk_steps`` is given, in which case the loop runs
        host-visible chunks with ``on_boundary`` called after each —
        the supervised loop's checkpoint/guard hook. Converge mode
        always runs host windows (``EnsembleConfig.window_rounds``
        check windows per dispatch): per-member verdicts are read at
        each boundary, finished members freeze, and the batch compacts
        when the live fraction drops below
        ``EnsembleConfig.compact_threshold``.

        ``state`` resumes from an assembled boundary state (the
        ensemble checkpoint's payload): ``config.steps`` is the
        ABSOLUTE step target and ``state["k"]`` the absolute step the
        grids correspond to. ``on_boundary`` may raise
        :class:`EnsembleInterrupted` (via its own logic) to stop at a
        consistent boundary.
        """
        config = self.config
        run_cfg = self._run_cfg
        B = self.batch
        guard_interval = config.guard_interval
        diag_interval = config.diag_interval

        if state is not None:
            u = self.initial_grids(state["grids"])
            k0 = int(state["k"])
        else:
            u = self.initial_grids(initials)
            k0 = 0
        total = config.steps

        if telemetry is not None:
            telemetry.run_header(
                config, ensemble={"members": B, "path": self.path,
                                  "window_rounds":
                                      self.ensemble.window_rounds,
                                  "compact_threshold":
                                      self.ensemble.compact_threshold})

        diag_prev = jnp.copy(u) if diag_interval is not None else None

        t0 = time.perf_counter()
        if not config.converge:
            out = self._solve_fixed(run_cfg, u, k0, total, chunk_steps,
                                    telemetry, on_boundary)
        else:
            out = self._solve_converge(run_cfg, u, k0, total, state,
                                       telemetry, on_boundary)
        grids, steps_run, converged, residual, compactions = out
        elapsed = time.perf_counter() - t0

        finite = None
        if guard_interval is not None:
            finite = ensemble_all_finite(grids)
            if not finite.all():
                import warnings

                bad = [int(i) for i in np.where(~finite)[0]]
                warnings.warn(
                    f"runtime guard: non-finite grid values in ensemble "
                    f"member(s) {bad} (coefficient sum past the "
                    f"stability bound? see HeatConfig.stability_margin)",
                    RuntimeWarning)
        diagnostics = None
        if diag_interval is not None:
            diagnostics = ensemble_grid_stats(grids, prev=diag_prev)
            for i, d in enumerate(diagnostics):
                d["step"] = int(steps_run[i])
                d["steps_since"] = int(steps_run[i]) - k0
                if telemetry is not None:
                    telemetry.diagnostics(member=i, **d)
        if telemetry is not None:
            for i in range(B):
                telemetry.emit(
                    "member_end", member=i, step=int(steps_run[i]),
                    steps=int(steps_run[i]) - k0,
                    converged=(bool(converged[i])
                               if converged is not None else None),
                    residual=(float(residual[i])
                              if residual is not None else None),
                    finite=(bool(finite[i]) if finite is not None
                            else None))
        return EnsembleResult(
            grids=grids, steps_run=steps_run, converged=converged,
            residual=residual, elapsed_s=elapsed, finite=finite,
            diagnostics=diagnostics, compactions=compactions)

    # -- fixed mode ------------------------------------------------------

    def _solve_fixed(self, run_cfg, u, k0, total, chunk_steps,
                     telemetry, on_boundary):
        B = self.batch
        remaining = total - k0
        if remaining < 0:
            raise ValueError(
                f"resume state at step {k0} is past the target {total}")
        chunk = chunk_steps if chunk_steps else max(1, remaining)
        if run_cfg.accumulate == "f32chunk" and chunk_steps:
            from parallel_heat_tpu.config import sublane_count

            sub = sublane_count(run_cfg.dtype)
            # Stream boundaries are rounding points (SEMANTICS.md):
            # same round-up rule as solve_stream.
            chunk = ((chunk + sub - 1) // sub) * sub
        k = k0
        while k < total:
            c = min(chunk, total - k)
            runner = _build_fixed_runner(run_cfg, B, c)
            with jax.profiler.TraceAnnotation("heat:ens_chunk"):
                u = runner(u)
            k += c
            if telemetry is not None:
                telemetry.emit("ensemble_window", step=k, batch=B,
                               live=(B if k < total else 0),
                               done=(0 if k < total else B))
            if on_boundary is not None:
                uu = u

                def assemble(_u=uu, _k=k):
                    return {"k": _k, "grids": _u,
                            "done": np.zeros(B, bool),
                            "res": np.full(B, np.inf, np.float64),
                            "steps": np.full(B, _k, np.int64)}

                on_boundary(EnsembleBoundary(
                    step=k, batch=B, live=B if k < total else 0,
                    done_total=0 if k < total else B, live_grids=u,
                    assemble=assemble, order=tuple(range(B))))
        steps_run = np.full(B, total, np.int64)
        return u, steps_run, None, None, []

    # -- converge mode ---------------------------------------------------

    def _solve_converge(self, run_cfg, u, k0, total, state,
                        telemetry, on_boundary):
        B = self.batch
        ci = run_cfg.check_interval
        eps = run_cfg.eps
        n_full = total // ci
        rem = total % ci
        full_steps = n_full * ci
        W = self.ensemble.window_rounds
        thresh = self.ensemble.compact_threshold

        # Original-order member bookkeeping. `order[pos]` is the
        # original index of position `pos` of the current batch;
        # parked members live outside the batch entirely.
        order = list(range(B))
        parked: dict = {}  # orig idx -> (grid, steps, res, converged)
        compactions: List[tuple] = []

        if state is not None:
            done_h = np.asarray(state["done"], bool).copy()
            res_h = np.asarray(state["res"], np.float64).copy()
            steps_h = np.asarray(state["steps"], np.int64).copy()
        else:
            done_h = np.zeros(B, bool)
            res_h = np.full(B, np.inf, np.float64)
            steps_h = np.full(B, k0, np.int64)
        # Members already done on entry are parked immediately (a
        # resumed ensemble must not re-dispatch finished members).
        if done_h.any():
            for i in np.where(done_h)[0]:
                parked[int(i)] = (u[int(i)], int(steps_h[i]),
                                  float(res_h[i]), True)
            live0 = [int(i) for i in np.where(~done_h)[0]]
            order = live0
            if live0:
                u = jnp.take(u, jnp.asarray(live0), axis=0)

        k = k0

        def assemble_state(u_cur, done_cur, res_cur, steps_cur, k_cur,
                           order_cur):
            """Full-order resumable snapshot (host-side)."""
            slices = {}
            for pos, orig in enumerate(order_cur):
                slices[orig] = (
                    u_cur[pos], int(steps_cur[pos]),
                    float(res_cur[pos]), bool(done_cur[pos]))
            slices.update(parked)
            grids = jnp.stack([slices[i][0] for i in range(B)])
            return {"k": k_cur,
                    "grids": grids,
                    "done": np.array([slices[i][3] for i in range(B)]),
                    "res": np.array([slices[i][2] for i in range(B)],
                                    np.float64),
                    "steps": np.array([slices[i][1] for i in range(B)],
                                      np.int64)}

        # In-batch per-member verdict state (device). Frozen members
        # ride along (masked update) until a compaction parks them.
        done_d = jnp.asarray(np.zeros(len(order), bool))
        res_d = jnp.asarray(
            np.array([res_h[i] for i in order], np.float32))
        steps_d = jnp.asarray(
            np.array([steps_h[i] for i in order], np.int32))

        while order and k < full_steps:
            cur_B = len(order)
            w = min(W, (full_steps - k) // ci)
            if w <= 0:
                break
            runner = _build_converge_runner(run_cfg, cur_B, w)
            with jax.profiler.TraceAnnotation("heat:ens_chunk"):
                u, done_d, res_d, steps_d, k_d = runner(
                    u, done_d, res_d, steps_d, jnp.int32(k))
            k = int(k_d)
            done = np.asarray(done_d)
            res_w = np.asarray(res_d, np.float64)
            steps_w = np.asarray(steps_d, np.int64)
            newly = [pos for pos in range(cur_B)
                     if done[pos] and not done_h[order[pos]]]
            for pos, orig in enumerate(order):
                res_h[orig] = res_w[pos]
                steps_h[orig] = steps_w[pos]
                done_h[orig] = done[pos]
            live = int((~done).sum())
            if telemetry is not None:
                telemetry.emit("ensemble_window", step=k, batch=cur_B,
                               live=live, done=B - live)
                for pos in newly:
                    telemetry.emit("member_converged",
                                   member=order[pos],
                                   step=int(steps_w[pos]),
                                   residual=float(res_w[pos]))
            if on_boundary is not None:
                on_boundary(EnsembleBoundary(
                    step=k, batch=cur_B, live=live,
                    done_total=B - live, live_grids=u,
                    assemble=functools.partial(
                        assemble_state, u, done, res_w, steps_w, k,
                        list(order)),
                    order=tuple(order)))
            if live == 0:
                break
            if thresh is not None and live < cur_B and \
                    live / cur_B < thresh:
                # Compaction: park finished members, keep the live ones
                # in a smaller batch. Member trajectories are invariant
                # to this (masked freeze vs physical removal — pinned
                # by tests/test_ensemble.py). At the default threshold
                # 0.5 each compaction at least halves the batch, so a
                # run compiles at most O(log B) batch extents.
                live_pos = [int(p) for p in np.where(~done)[0]]
                for pos in np.where(done)[0]:
                    orig = order[int(pos)]
                    parked[orig] = (u[int(pos)], int(steps_w[pos]),
                                    float(res_w[pos]), True)
                u = jnp.take(u, jnp.asarray(live_pos), axis=0)
                new_order = [order[p] for p in live_pos]
                compactions.append((k, cur_B, len(new_order)))
                if telemetry is not None:
                    telemetry.emit("ensemble_compaction", step=k,
                                   from_members=cur_B,
                                   to_members=len(new_order))
                order = new_order
                done_d = jnp.asarray(np.zeros(len(order), bool))
                res_d = jnp.asarray(
                    np.array([res_h[i] for i in order], np.float32))
                steps_d = jnp.asarray(
                    np.array([steps_h[i] for i in order], np.int32))

        # Drain the batch: converged members park with their latched
        # verdicts; the rest run the rem leftover steps past the last
        # full window (solo's uninspected tail) and park unconverged.
        if order:
            done = np.array([done_h[i] for i in order])
            # The tail only applies to members that ran out of full
            # windows without converging, and only when this invocation
            # actually reached the end of the window budget (a resumed
            # already-complete state must not re-run it).
            if rem > 0 and k < total and not done.all():
                cur_B = len(order)
                runner = _build_masked_tail_runner(run_cfg, cur_B, rem)
                u = runner(u, jnp.asarray(done))
                for orig in (o for pos, o in enumerate(order)
                             if not done[pos]):
                    steps_h[orig] = full_steps + rem
            for pos, orig in enumerate(order):
                parked[orig] = (u[pos], int(steps_h[orig]),
                                float(res_h[orig]), bool(done_h[orig]))
            order = []

        grids = jnp.stack([parked[i][0] for i in range(B)])
        steps_run = np.array([parked[i][1] for i in range(B)], np.int64)
        residual = np.array([parked[i][2] for i in range(B)], np.float64)
        converged = np.array([parked[i][3] for i in range(B)], bool)
        if np.any(~np.isfinite(residual) & (steps_run >= ci)):
            import warnings

            warnings.warn(
                "simulation diverged: non-finite residual in at least "
                "one ensemble member (coefficient sum past the "
                "stability bound? see HeatConfig.stability_margin)",
                RuntimeWarning)
        return grids, steps_run, converged, residual, compactions
