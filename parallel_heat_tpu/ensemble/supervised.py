"""Supervised ensemble runs: guard + retained generations + rollback.

The member-axis edition of ``supervisor.run_supervised``, reusing its
vocabulary wholesale — :class:`supervisor.SupervisorPolicy` for the
cadences/budgets, :class:`supervisor.PermanentFailure` for terminal
verdicts, the checkpoint stem lock, the flag-only SIGTERM/interrupt
discipline — around :class:`ensemble.engine.EnsembleSolver`:

- every ``checkpoint_every`` boundary commits one ensemble generation
  (the FULL-ORDER member state — ``ensemble/checkpoint.py``), keeping
  the newest ``keep_checkpoints``;
- every guard boundary runs the fused per-member isfinite reduction
  over the live batch; a trip rolls the WHOLE ensemble back to the
  newest retained generation and retries under the policy's bounded
  exponential backoff (member independence makes per-member rollback
  unnecessary: a clean member's replayed trajectory is bitwise the
  one it already ran — pinned by tests/test_ensemble.py);
- SIGTERM/SIGINT (or the caller's flag-only ``interrupt`` hook, the
  service deadline path) flushes a final generation at the boundary
  and returns an interrupted result; resume continues every member
  bit-exactly.

``member_stems`` additionally flushes each member's state as a
REGULAR per-member solver generation (``utils.checkpoint.
save_generation``) at every checkpoint boundary — how the packed
``heatd`` worker keeps every job solo-resumable: an orphaned pack's
members requeue and continue as ordinary solo jobs from their own
checkpoint lineage, bit-exactly (the parity contract makes the two
paths interchangeable).
"""

from __future__ import annotations

import math
import signal
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence

import numpy as np

from parallel_heat_tpu.config import EnsembleConfig, HeatConfig
from parallel_heat_tpu.ensemble import checkpoint as ens_ckpt
from parallel_heat_tpu.ensemble.engine import (
    EnsembleInterrupted,
    EnsembleResult,
    EnsembleSolver,
    ensemble_all_finite,
)
from parallel_heat_tpu.supervisor import (
    PermanentFailure,
    SupervisorPolicy,
    _signal_handlers,
    _StopFlag,
)
from parallel_heat_tpu.utils import checkpoint as ckpt


class _EnsGuardTrip(Exception):
    def __init__(self, step: int, bad: List[int]):
        super().__init__(f"non-finite members {bad} at step {step}")
        self.step = step
        self.bad = bad


@dataclass
class EnsembleSupervisorResult:
    """Outcome of one supervised ensemble invocation."""

    result: Optional[EnsembleResult]
    steps_done: int            # global boundary step of the newest state
    interrupted: bool
    retries: int
    rollbacks: int
    guard_trips: int
    checkpoints_written: int
    last_checkpoint: Optional[str]
    signal_name: Optional[str] = None
    wall_s: float = 0.0
    # Per-member absolute steps of the newest flushed state (filled on
    # both completion and interruption — the packed worker fans these
    # into per-job result records).
    member_steps: Optional[np.ndarray] = None


def run_ensemble_supervised(config: HeatConfig,
                            ensemble,
                            stem,
                            policy: Optional[SupervisorPolicy] = None,
                            initials=None,
                            telemetry=None,
                            interrupt: Optional[Callable] = None,
                            member_stems: Optional[Sequence[str]] = None,
                            say=None) -> EnsembleSupervisorResult:
    """Run the ensemble to completion under supervision; resumes from
    the newest committed ensemble generation of ``stem`` when one
    exists (``initials`` is then ignored — the checkpoint is the
    authoritative state). ``ensemble`` is an
    :class:`EnsembleConfig` or an int member count."""
    if isinstance(ensemble, int):
        ensemble = EnsembleConfig(members=ensemble)
    config = config.validate()
    ensemble = ensemble.validate()
    policy = (policy or SupervisorPolicy()).validate()
    say = say or (lambda *a: None)
    stem = ckpt.checkpoint_stem(stem)
    if member_stems is not None and len(member_stems) != ensemble.members:
        raise ValueError(
            f"member_stems has {len(member_stems)} entries for "
            f"{ensemble.members} members")

    release = ckpt.acquire_stem_lock(stem)
    try:
        return _run(config, ensemble, stem, policy, initials, telemetry,
                    interrupt, member_stems, say)
    finally:
        release()


def _run(config, ensemble, stem, policy, initials, telemetry,
         interrupt, member_stems, say):
    solver = EnsembleSolver(config, ensemble)
    total = config.steps
    guard_iv = policy.guard_interval or config.guard_interval
    every = policy.checkpoint_every
    # Checkpoint/guard boundaries must land on engine boundaries. In
    # converge mode the engine's boundary grain is a dispatch window
    # (window_rounds * check_interval steps); in fixed mode the chunk
    # is chosen here, exactly like the solo supervisor's gcd rule.
    chunk = math.gcd(every, guard_iv) if guard_iv else every
    if config.accumulate == "f32chunk":
        from parallel_heat_tpu.config import sublane_count

        sub = sublane_count(config.dtype)
        if every % sub or (guard_iv or sub) % sub:
            # Same loud rule as the solo supervisor: stream boundaries
            # are rounding points under f32chunk (SEMANTICS.md).
            raise ValueError(
                f"accumulate='f32chunk' requires checkpoint_every and "
                f"guard_interval to be multiples of the chunk depth "
                f"K={sub} (stream boundaries are rounding points)")

    retries = rollbacks = trips = n_ckpt = 0
    last_path: Optional[str] = None
    clock = policy.clock
    t0 = clock()
    stop = _StopFlag()

    state = None
    src = ens_ckpt.latest_ensemble_checkpoint(stem)
    if src is not None:
        state, saved_cfg, saved_ens, _m = ens_ckpt.load_ensemble_checkpoint(
            src, expect_config=config)
        if saved_ens.members != ensemble.members:
            raise ValueError(
                f"ensemble checkpoint {src!r} holds {saved_ens.members} "
                f"members; this run has {ensemble.members}")
        say(f"Ensemble supervisor: resuming from {src} at step "
            f"{state['k']}")

    def emit(event, **fields):
        if telemetry is not None:
            telemetry.emit(event, **fields)

    def save(st: dict) -> str:
        nonlocal n_ckpt, last_path
        t_save = clock()
        last_path = ens_ckpt.save_ensemble_generation(
            stem, st, config.replace(steps=total), ensemble,
            keep=policy.keep_checkpoints)
        n_ckpt += 1
        emit("checkpoint_save", step=st["k"], path=str(last_path),
             wall_s=clock() - t_save, kept=policy.keep_checkpoints,
             generation=n_ckpt, ensemble=True)
        say(f"Ensemble supervisor: generation at step {st['k']} -> "
            f"{last_path}")
        if member_stems is not None:
            # Per-member solo-resumable generations (the packed-worker
            # path): each member's grid is a perfectly ordinary solver
            # checkpoint of its own job, stamped with ITS step.
            for i, mstem in enumerate(member_stems):
                ckpt.save_generation(
                    mstem, st["grids"][i], int(st["steps"][i]),
                    config.replace(steps=total),
                    keep=policy.keep_checkpoints)
        return last_path

    next_ckpt = [0]  # next boundary at-or-after which to checkpoint
    next_guard = [0]

    def on_boundary(b):
        # Interrupt first (flag-only; the flushed state must be the
        # boundary state), then guard, then the periodic checkpoint.
        why = None
        if stop.signum is not None:
            why = signal.Signals(stop.signum).name
        elif interrupt is not None:
            w = interrupt()
            if w:
                why = str(w)
        if why is not None:
            raise EnsembleInterrupted(why, b.assemble())
        if guard_iv is not None and b.step >= next_guard[0]:
            fin = ensemble_all_finite(b.live_grids)
            while next_guard[0] <= b.step:
                next_guard[0] += guard_iv
            if not fin.all():
                # Map batch positions to ORIGINAL member ids: after a
                # compaction position i is not member i, and the trip
                # telemetry / quarantine diagnosis name members to a
                # human.
                order = b.order or tuple(range(len(fin)))
                bad = [int(order[p]) for p in np.where(~fin)[0]]
                raise _EnsGuardTrip(b.step, bad)
        if b.step >= next_ckpt[0] or b.live == 0:
            save(b.assemble())
            while next_ckpt[0] <= b.step:
                next_ckpt[0] += every

    def _interrupted(why: str, st: dict) -> EnsembleSupervisorResult:
        save(st)
        emit("signal", name=why, step=st["k"], ensemble=True)
        if telemetry is not None:
            telemetry.run_end(outcome="interrupted", steps_done=st["k"],
                              signal=why, retries=retries,
                              rollbacks=rollbacks, guard_trips=trips,
                              checkpoints_written=n_ckpt,
                              wall_s=clock() - t0)
        say(f"Ensemble supervisor: caught {why}; newest generation "
            f"{last_path}")
        return EnsembleSupervisorResult(
            result=None, steps_done=st["k"], interrupted=True,
            retries=retries, rollbacks=rollbacks, guard_trips=trips,
            checkpoints_written=n_ckpt, last_checkpoint=last_path,
            signal_name=why, wall_s=clock() - t0,
            member_steps=np.asarray(st["steps"], np.int64))

    with _signal_handlers(stop):
        # Generation zero before any step: rollback always has a
        # target, even for a first-chunk fault (solo discipline).
        if state is None:
            u0 = solver.initial_grids(initials)
            B = ensemble.members
            state = {"k": 0, "grids": u0,
                     "done": np.zeros(B, bool),
                     "res": np.full(B, np.inf, np.float64),
                     "steps": np.zeros(B, np.int64)}
            save(state)
            next_ckpt[0] = every
        else:
            next_ckpt[0] = (state["k"] // every + 1) * every
        if guard_iv is not None:
            next_guard[0] = (state["k"] // guard_iv + 1) * guard_iv

        while True:
            try:
                result = solver.solve(
                    telemetry=telemetry,
                    chunk_steps=None if config.converge else chunk,
                    on_boundary=on_boundary,
                    state=state)
                break
            except EnsembleInterrupted as e:
                return _interrupted(e.reason, e.state)
            except _EnsGuardTrip as e:
                trips += 1
                emit("guard_trip", step=e.step, members=e.bad,
                     ensemble=True)
                if config.scheme == "explicit" \
                        and config.stability_margin() < 0:
                    raise _fail(
                        telemetry, clock, t0, retries, rollbacks, trips,
                        n_ckpt,
                        f"non-finite ensemble members {e.bad} at step "
                        f"{e.step}: coefficient sum "
                        f"{sum(config.coefficients):g} exceeds the "
                        f"stability bound 1/2 — deterministic "
                        f"divergence; retrying cannot help. Reduce the "
                        f"coefficients or switch to the implicit "
                        f"integrator (--scheme backward_euler).",
                        kind="unstable") from None
                retries += 1
                if retries > policy.max_retries:
                    raise _fail(
                        telemetry, clock, t0, retries, rollbacks, trips,
                        n_ckpt,
                        f"ensemble guard trip (members {e.bad}, step "
                        f"{e.step}) persisted through "
                        f"{policy.max_retries} rollback retries. "
                        f"Newest verified generation: {last_path}.",
                        kind="exhausted") from None
                delay = min(policy.backoff_max_s,
                            policy.backoff_base_s * 2 ** (retries - 1))
                emit("retry", retry=retries,
                     max_retries=policy.max_retries,
                     kind=f"ensemble guard trip at step {e.step}",
                     backoff_s=delay, ensemble=True)
                say(f"Ensemble supervisor: guard trip (members "
                    f"{e.bad}); retry {retries}/{policy.max_retries} "
                    f"after {delay:g}s")
                if delay > 0:
                    policy.sleep_fn(delay)
                src = ens_ckpt.latest_ensemble_checkpoint(stem)
                if src is None:  # pragma: no cover (gen0 always exists)
                    raise _fail(
                        telemetry, clock, t0, retries, rollbacks, trips,
                        n_ckpt,
                        f"no ensemble generation of {stem!r} survives "
                        f"to roll back to.") from None
                state, _c, _e, _m = ens_ckpt.load_ensemble_checkpoint(
                    src, expect_config=config)
                rollbacks += 1
                emit("rollback", step=state["k"], path=str(src),
                     ensemble=True)
                say(f"Ensemble supervisor: rolled back to {src} "
                    f"(step {state['k']})")
                if guard_iv is not None:
                    next_guard[0] = (state["k"] // guard_iv + 1) * guard_iv
                next_ckpt[0] = (state["k"] // every + 1) * every
                continue

        # Final generation: the completed full-order state, stamped
        # with the furthest member step (converge runs may finish the
        # whole ensemble well before the step budget).
        k_final = int(result.steps_run.max()) if ensemble.members else 0
        final_state = {
            "k": k_final, "grids": result.grids,
            "done": (result.converged if result.converged is not None
                     else np.ones(ensemble.members, bool)),
            "res": (result.residual if result.residual is not None
                    else np.full(ensemble.members, np.inf, np.float64)),
            "steps": result.steps_run}
        save(final_state)
        if telemetry is not None:
            telemetry.run_end(outcome="complete", steps_done=k_final,
                              retries=retries, rollbacks=rollbacks,
                              guard_trips=trips,
                              checkpoints_written=n_ckpt,
                              wall_s=clock() - t0)
        return EnsembleSupervisorResult(
            result=result, steps_done=k_final, interrupted=False,
            retries=retries, rollbacks=rollbacks, guard_trips=trips,
            checkpoints_written=n_ckpt, last_checkpoint=last_path,
            wall_s=clock() - t0,
            member_steps=np.asarray(result.steps_run, np.int64))


def _fail(telemetry, clock, t0, retries, rollbacks, trips, n_ckpt,
          diagnosis: str, kind: str = "exhausted") -> PermanentFailure:
    if telemetry is not None:
        telemetry.emit("permanent_failure", diagnosis=diagnosis,
                       kind=kind, ensemble=True)
        telemetry.run_end(outcome="permanent_failure", kind=kind,
                          retries=retries, rollbacks=rollbacks,
                          guard_trips=trips, checkpoints_written=n_ckpt,
                          wall_s=clock() - t0)
    return PermanentFailure(diagnosis, kind=kind)
