"""Admission control: reject loudly at the door instead of thrashing.

A serving daemon that accepts everything eventually accepts the job
that OOMs the device or buries the queue; both failure modes look like
"the service got slow and then fell over". The gate bounds two
resources *at submission time*, jax-free (the daemon must admit — and
refuse — without initializing an accelerator backend):

- **queue depth**: accepted-but-not-terminal jobs (queued + running +
  awaiting-requeue) versus ``max_queue_depth``;
- **estimated HBM**: a static per-job device-memory estimate versus an
  operator-set budget, summed over every admitted non-terminal job —
  the service-level analogue of ``TpuParams.vmem_limit_bytes``'s
  in-kernel check (heatlint HL402).

A rejection is a first-class, journaled verdict carrying a
``retry_after_s`` hint scaled by the current backlog — clients back
off instead of hammering, and nothing is ever accepted-then-dropped.
"""

from __future__ import annotations

from typing import Optional, Tuple

# Storage dtype widths, mirrored from the solver's config vocabulary
# WITHOUT importing jax/numpy (config.py is jax-free for exactly this
# kind of consumer; the byte widths are a stable contract of the dtype
# names themselves).
_DTYPE_BYTES = {"float32": 4, "bfloat16": 2, "float16": 2, "float64": 8}

# Resident buffers per job: the double-buffered state pair plus one
# snapshot/donation-protection copy (checkpoint gather source or
# pipelined-yield copy — SEMANTICS.md "Pipelined stream"). A deliberate
# slight over-estimate: admission must err toward refusing, not toward
# the OOM it exists to prevent.
_RESIDENT_BUFFERS = 3


# Resident f32 arrays per multigrid level of an implicit solve: the
# iterate, the RHS and the restricted residual live per level across
# the V-cycle (ops/multigrid.py), on top of the storage-dtype state
# pair the explicit estimate already prices.
_MG_LEVEL_BUFFERS = 3


def estimate_job_hbm_bytes(config: dict) -> int:
    """Static device-memory estimate for one job's grid state, from the
    job spec's config dict (``HeatConfig`` field names). Conservative
    by construction (see ``_RESIDENT_BUFFERS``); halo/reduction
    scratch is second-order at the grid sizes the budget matters for.

    Implicit specs (``scheme`` != "explicit") additionally price the
    multigrid level hierarchy: ``_MG_LEVEL_BUFFERS`` float32 arrays
    per level, with the level shapes from the SAME jax-free
    ``config.multigrid_level_shapes`` the V-cycle builder allocates
    from — the admitted estimate cannot disagree with the solve."""
    nx, ny = int(config.get("nx", 20)), int(config.get("ny", 20))
    cells = nx * ny
    if config.get("nz") is not None:
        cells *= int(config["nz"])
    itemsize = _DTYPE_BYTES.get(str(config.get("dtype", "float32")), 4)
    est = cells * itemsize * _RESIDENT_BUFFERS
    if str(config.get("scheme", "explicit")) != "explicit":
        from parallel_heat_tpu.config import multigrid_level_shapes

        mg_levels = config.get("mg_levels")
        for mx, my in multigrid_level_shapes(
                (nx, ny),
                int(mg_levels) if mg_levels is not None else None):
            est += mx * my * 4 * _MG_LEVEL_BUFFERS
    return est


def estimate_pack_hbm_bytes(configs) -> int:
    """Device-memory estimate of one PACKED ensemble dispatch: the sum
    of the members' individual estimates. The batched engine's
    resident set is linear in B (stacked double-buffer pair plus the
    donation-protection/checkpoint copy per member — the same
    ``_RESIDENT_BUFFERS`` model), so a pack of individually-admitted
    jobs is automatically inside whatever ``hbm_budget_bytes`` the
    admission gate already enforced member by member: packing changes
    WHEN the memory is resident (one dispatch instead of ``slots``
    staggered ones), never HOW MUCH the service committed to."""
    return sum(estimate_job_hbm_bytes(c) for c in configs)


def admission_verdict(config: dict, active_jobs: int,
                      active_hbm_bytes: int, max_queue_depth: int,
                      hbm_budget_bytes: Optional[int],
                      retry_after_base_s: float, slots: int,
                      draining: bool = False
                      ) -> Tuple[bool, Optional[str], float, int]:
    """One admission decision -> ``(accept, reason, retry_after_s,
    est_hbm_bytes)``. Pure function of the queue state so the gate is
    unit-testable and the daemon's journal record carries exactly what
    was decided and why."""
    est = estimate_job_hbm_bytes(config)
    # Backlog-scaled hint: an empty queue says "come right back", a
    # deep one says so honestly. Never zero — "retry immediately"
    # would re-create the thundering herd the gate exists to absorb.
    retry_after = retry_after_base_s * (1.0 + active_jobs
                                        / max(1, slots))
    if draining:
        return (False, "daemon is draining (shutdown in progress); "
                       "resubmit to the restarted daemon", retry_after,
                est)
    if active_jobs >= max_queue_depth:
        return (False, f"queue depth {active_jobs} at the admission "
                       f"limit ({max_queue_depth})", retry_after, est)
    if hbm_budget_bytes is not None \
            and active_hbm_bytes + est > hbm_budget_bytes:
        return (False, f"estimated HBM {est} B would take the admitted "
                       f"total to {active_hbm_bytes + est} B, past the "
                       f"budget {hbm_budget_bytes} B", retry_after, est)
    return True, None, 0.0, est
