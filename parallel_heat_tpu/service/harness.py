"""Inline worker harness: the one spelling of the fake Popen handle.

Benches (``bench.py --row serve_cache``), chaos cells
(``tools/chaos_matrix.py``) and tests drive :class:`~parallel_heat_tpu.
service.daemon.Heatd` with in-process workers — real
``worker.execute_job`` runs, real checkpoints land, no subprocess.
They all need the same Popen-shaped handle (``poll``/``terminate``/
``kill``/``pid``); private copies of it had started to drift across
the suites, and this module is the shared spelling every
inline-EXECUTION driver now uses (``defer`` covers the
deferred-occupancy variant too). Handles with genuinely different
semantics stay local to their suites: ``test_service``'s scripted
fakes (outcomes written by the test, nothing executes) and
``test_ensemble``'s pack-routing launcher (``execute_pack`` at launch
time). Deliberately tiny and dependency-free: production-adjacent
test plumbing, not a service feature.
"""

from __future__ import annotations

import os
from typing import Callable, List, Optional


class InlineHandle:
    """Popen-shaped handle that runs its job on the first ``poll``.
    ``defer`` keeps it 'running' for that many polls first —
    deterministic queue occupancy for overload/packing scenarios."""

    def __init__(self, run: Callable[[], int], defer: int = 1):
        self._run = run
        self._defer = int(defer)
        self._polls = 0
        self._rc: Optional[int] = None
        self.pid = os.getpid()

    def poll(self) -> Optional[int]:
        self._polls += 1
        if self._polls < self._defer:
            return None
        if self._rc is None:
            self._rc = self._run()
        return self._rc

    def terminate(self) -> None:
        pass

    kill = terminate


def inline_launcher(root: str, spawns: Optional[List[str]] = None,
                    defer: int = 1) -> Callable:
    """A ``HeatdConfig.launcher`` running solo jobs in-process via
    ``worker.execute_job``. ``spawns`` (when given) records the job
    ids actually launched — the zero-spawn assertion of an exact
    cache hit reads it."""
    from parallel_heat_tpu.service import worker as svc_worker

    def launcher(job_id, worker_id, attempt, deadline_t):
        if spawns is not None:
            spawns.append(job_id)
        return InlineHandle(
            lambda: svc_worker.execute_job(str(root), job_id,
                                           worker_id, attempt,
                                           deadline_t=deadline_t),
            defer=defer)

    return launcher
