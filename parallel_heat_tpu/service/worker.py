"""Service worker: one process, one job attempt.

``heatd`` dispatches each attempt as ``python -m
parallel_heat_tpu.service.worker`` — a real OS process, so real death
(SIGKILL, OOM) is exactly what the daemon's orphan detection faces.
The worker is a thin adapter around the machinery earlier PRs built:

- it **resumes before it runs**: ``latest_checkpoint`` on the job's
  checkpoint stem finds the newest COMMITTED generation (a predecessor
  killed mid-save left only complete generations — the checkpoint
  protocol's torn-write invisibility), so a re-dispatched attempt
  continues the same trajectory bit-exactly;
- the job executes under :func:`supervisor.run_supervised` — guard,
  retained generations, in-worker retry-with-rollback, SIGTERM flush —
  with a per-job telemetry sink that APPENDS across attempts (one
  JSONL stream per job, absolute steps via ``step_offset``, exactly
  like a CLI ``--resume`` continuation);
- deadlines ride the supervisor's flag-only interrupt hook; daemon
  SIGTERM (cancel/drain) rides its signal handler — both exit
  ``EXIT_PREEMPTED`` with a rename-committed outcome record saying
  which;
- liveness is a tiny heartbeat thread atomically rewriting
  ``hb/<worker>.json`` — self-contained (an Event and a file write, no
  shared mutable state), so a wedged run loop stops beating and the
  daemon's staleness threshold catches it.

Exit codes are the supervisor's own vocabulary: 0 completed,
``EXIT_PREEMPTED`` (3) interrupted-with-resume-state,
``EXIT_PERMANENT_FAILURE`` (4) with the kind in the outcome record.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Optional

from parallel_heat_tpu.config import HeatConfig
from parallel_heat_tpu.service import cache
from parallel_heat_tpu.service.store import JobStore
from parallel_heat_tpu.supervisor import (
    EXIT_PERMANENT_FAILURE,
    EXIT_PREEMPTED,
    PermanentFailure,
    SupervisorPolicy,
    default_checkpoint_every,
    run_supervised,
)
from parallel_heat_tpu.utils import checkpoint as ckpt
from parallel_heat_tpu.utils.faults import FaultPlan
from parallel_heat_tpu.utils.telemetry import Telemetry
from parallel_heat_tpu.utils.tracing import (
    TraceContext,
    dispatch_span_id,
    worker_span_id,
)


class _HeartbeatWriter(threading.Thread):
    """Atomic liveness beats on a fixed cadence. Deliberately owns no
    shared state beyond its stop Event: the run loop cannot block it,
    and it cannot race the run loop."""

    def __init__(self, store: JobStore, worker_id: str, job_id: str,
                 attempt: int, interval_s: float):
        super().__init__(name=f"heartbeat-{worker_id}", daemon=True)
        self._store = store
        self._worker_id = worker_id
        self._job_id = job_id
        self._attempt = attempt
        self._interval_s = interval_s
        self._stop_event = threading.Event()

    def run(self) -> None:
        while True:
            self._store.write_worker_hb(self._worker_id, {
                "pid": os.getpid(), "t_wall": time.time(),
                "job_id": self._job_id, "attempt": self._attempt,
                "interval_s": self._interval_s})
            if self._stop_event.wait(self._interval_s):
                return

    def stop(self) -> None:
        self._stop_event.set()
        self.join(timeout=5.0)


def _worker_trace(spec, job_id: str, attempt: int
                  ) -> Optional[TraceContext]:
    """This attempt's span context: the daemon's env inheritance when
    spawned as a subprocess, else the spec's committed trace (inline
    launchers in tests call execute_job directly — no env crossing).
    The worker runs as a CHILD span of the dispatch span, so the chain
    reads submit -> dispatch -> worker -> run/chunk in heattrace."""
    parent = TraceContext.from_env()
    if parent is None and getattr(spec, "trace", None):
        root = TraceContext.from_dict(spec.trace)
        if root is not None:
            parent = TraceContext(root.trace_id,
                                  dispatch_span_id(job_id, attempt),
                                  root.span_id)
    if parent is None:
        return None
    return parent.child(worker_span_id(job_id, attempt))


def execute_job(root: str, job_id: str, worker_id: str, attempt: int,
                deadline_t: Optional[float] = None,
                hb_interval_s: Optional[float] = None,
                say=None) -> int:
    """Run one job attempt to an exit code + outcome record. The
    daemon's inline-launcher tests call this directly; ``main`` wraps
    it for the subprocess path."""
    say = say or (lambda *a: None)
    store = JobStore(root, create=False)
    t0 = time.perf_counter()

    def record(outcome: str, **fields) -> None:
        doc = {"outcome": outcome, "worker": worker_id,
               "attempt": attempt, "job_id": job_id,
               "wall_s": time.perf_counter() - t0}
        doc.update(fields)
        store.write_result(job_id, attempt, doc)

    try:
        spec = store.load_spec(job_id)
        config = HeatConfig.from_json(json.dumps(spec.config)).validate()
    except Exception as e:  # noqa: BLE001 — any unloadable spec is terminal
        # An accepted spec the worker cannot materialize is
        # deterministic poison: record it (so the daemon fail-fast
        # quarantines with THIS diagnosis) instead of dying recordless
        # and churning through orphan/requeue to a mislabeled verdict.
        record("permanent_failure", kind="bad_spec",
               diagnosis=f"cannot materialize job spec: {e}")
        return EXIT_PERMANENT_FAILURE
    stem = store.checkpoint_stem(job_id)
    total = config.steps

    hb = None
    if hb_interval_s:
        hb = _HeartbeatWriter(store, worker_id, job_id, attempt,
                              hb_interval_s)
        hb.start()
    # job_id + trace ride the envelope: fleet aggregation joins a run
    # to its job by content (not path convention), and heattrace joins
    # it to the submit's causal chain.
    telemetry = Telemetry(store.telemetry_path(job_id), async_io=True,
                          job_id=job_id,
                          trace=_worker_trace(spec, job_id, attempt))

    try:
        # Resume-before-run: the newest COMMITTED generation of this
        # job's stem (None on attempt 1 — run_supervised writes
        # generation zero before any step, so even a first-chunk death
        # leaves a resume target).
        initial = None
        start_step = 0
        src = ckpt.latest_checkpoint(stem)
        if src is not None:
            initial, start_step, _ = ckpt.load_checkpoint(src, config)
            say(f"worker {worker_id}: resuming {job_id} from {src} "
                f"at step {start_step}")
        telemetry.step_offset = start_step
        if src is not None:
            # Cache-seeded resume (SEMANTICS.md "Cache soundness"):
            # the daemon dropped a marker next to the generation it
            # linked from a donor lineage — journal the provenance
            # into this run's stream so heattrace can attribute the
            # skipped prefix. Only when the marker names the step we
            # actually resumed at: a later own checkpoint (retry,
            # orphan re-dispatch) supersedes the seed.
            seed = cache.read_seed_marker(stem)
            if seed and seed.get("generation_step") == start_step:
                telemetry.emit("cache_prefix_resume",
                               key=seed.get("key"),
                               donor=seed.get("donor"),
                               generation_step=start_step)
        run_cfg = config.replace(steps=max(0, total - start_step))

        faults = None
        if spec.faults and attempt == int(spec.faults_on_attempt or 1):
            d = dict(spec.faults)
            if d.get("transient_on_chunks") is not None:
                d["transient_on_chunks"] = tuple(d["transient_on_chunks"])
            faults = FaultPlan(**d)

        policy = SupervisorPolicy(
            checkpoint_every=(spec.checkpoint_every
                              or default_checkpoint_every(config)),
            guard_interval=spec.guard_interval,
            max_retries=spec.max_retries,
            backoff_base_s=spec.backoff_base_s)
        interrupt = None
        if deadline_t is not None:
            # The flag-only deadline: polled at chunk boundaries, the
            # supervisor flushes a checkpoint and returns interrupted
            # with this reason — no second signal vocabulary.
            interrupt = (lambda: "deadline"
                         if time.time() >= deadline_t else None)

        try:
            sres = run_supervised(run_cfg, stem, policy=policy,
                                  initial=initial, start_step=start_step,
                                  faults=faults, telemetry=telemetry,
                                  interrupt=interrupt, say=say)
        except ckpt.StemLockError as e:
            # A predecessor the daemon believed dead still holds the
            # stem (pid reuse / a misjudged adoption): refuse rather
            # than race its generations. Not a fail-fast kind — the
            # daemon requeues with backoff and the next attempt finds
            # the lock stale or released.
            record("permanent_failure", kind="stem_locked",
                   diagnosis=str(e))
            return EXIT_PERMANENT_FAILURE
        except PermanentFailure as e:
            record("permanent_failure", kind=e.kind,
                   diagnosis=e.diagnosis)
            return EXIT_PERMANENT_FAILURE

        if sres.interrupted:
            record("preempted", reason=sres.signal_name,
                   steps_done=sres.steps_done,
                   last_checkpoint=(str(sres.last_checkpoint)
                                    if sres.last_checkpoint else None))
            return EXIT_PREEMPTED
        record("completed", steps_done=sres.steps_done,
               retries=sres.retries,
               # Converge verdict (None for fixed runs): the cache's
               # converge admissibility rules key on it — a
               # budget-exhausted run's generations are provably
               # verdict-free, a converged run dominates any larger
               # budget (SEMANTICS.md "Cache soundness").
               converged=(bool(sres.result.converged)
                          if config.converge and sres.result is not None
                          and sres.result.converged is not None
                          else None),
               last_checkpoint=(str(sres.last_checkpoint)
                                if sres.last_checkpoint else None))
        return 0
    finally:
        telemetry.close()
        if hb is not None:
            hb.stop()


def execute_pack(root: str, job_ids, worker_id: str,
                 hb_interval_s: Optional[float] = None,
                 say=None) -> int:
    """Run one PACKED dispatch: N compatible fresh jobs as one
    ensemble program (``heatd serve --pack``). The contract that makes
    this safe is bitwise member parity (SEMANTICS.md "Ensemble"): a
    member's results — grids, checkpoints, verdicts — are exactly what
    its solo run would produce, so per-member results fan back to the
    individual job records and any member can later resume SOLO from
    its own per-job checkpoint lineage (flushed every boundary via
    ``member_stems``). Anything that breaks the pack's assumptions —
    mismatched specs, an unpackable resolved path, a member with
    pre-existing checkpoints — demotes gracefully: every member gets a
    ``preempted`` record, the daemon requeues, and the non-fresh retry
    dispatches solo."""
    say = say or (lambda *a: None)
    store = JobStore(root, create=False)
    job_ids = list(job_ids)
    t0 = time.perf_counter()

    def record_all(outcome: str, per_member=None, **fields) -> None:
        for i, jid in enumerate(job_ids):
            doc = {"outcome": outcome, "worker": worker_id,
                   "attempt": 1, "job_id": jid, "pack": job_ids[0],
                   "pack_size": len(job_ids),
                   "wall_s": time.perf_counter() - t0}
            doc.update(fields)
            if per_member is not None:
                doc.update(per_member[i])
            store.write_result(jid, 1, doc)

    def demote(why: str) -> int:
        # Not a failure: the members are fine, the PACK was wrong.
        # Preempted records requeue every member; non-fresh members
        # never pack again, so the retry runs the proven solo path.
        say(f"pack {worker_id}: demoting to solo — {why}")
        record_all("preempted", reason=f"unpackable: {why}",
                   steps_done=0)
        return EXIT_PREEMPTED

    hb = None
    if hb_interval_s:
        hb = _HeartbeatWriter(store, worker_id, job_ids[0], 1,
                              hb_interval_s)
        hb.start()
    # The pack's shared stream traces under the LEADER's context (the
    # daemon's env carries exactly one); `job_id` is the leader, which
    # matches the `pack` field on every member's dispatched journal
    # line — heattrace renders per-member lanes from the stream's
    # `member` fields and keeps each member's own trace in the journal.
    # The spec-trace fallback is wired after the specs load below
    # (inline launchers cross no env boundary, same as execute_job).
    pack_trace = TraceContext.from_env()
    telemetry = Telemetry(store.telemetry_path(f"pack-{worker_id}"),
                          async_io=True, job_id=job_ids[0],
                          trace=(pack_trace.child(
                              worker_span_id(job_ids[0], 1))
                              if pack_trace else None))
    try:
        try:
            specs = [store.load_spec(jid) for jid in job_ids]
            config = HeatConfig.from_json(
                json.dumps(specs[0].config)).validate()
        except Exception as e:  # noqa: BLE001 — any unloadable spec
            record_all("permanent_failure", kind="bad_spec",
                       diagnosis=f"cannot materialize pack spec: {e}")
            return EXIT_PERMANENT_FAILURE
        if telemetry.trace is None:
            # No env crossing (inline launcher): the leader's
            # committed spec trace, exactly execute_job's fallback —
            # nothing has been emitted yet, so the whole stream still
            # joins the chain.
            telemetry.trace = _worker_trace(specs[0], job_ids[0], 1)
        key0 = json.dumps(specs[0].config, sort_keys=True)
        for s in specs[1:]:
            # Everything the shared SupervisorPolicy below is built
            # from must match — a member silently running under the
            # leader's knobs would be a semantics change, not a fast
            # path.
            if json.dumps(s.config, sort_keys=True) != key0 \
                    or s.checkpoint_every != specs[0].checkpoint_every \
                    or s.guard_interval != specs[0].guard_interval \
                    or s.max_retries != specs[0].max_retries \
                    or s.backoff_base_s != specs[0].backoff_base_s:
                return demote("member specs diverged after dispatch")
        from parallel_heat_tpu.ensemble.engine import packable

        ok, reason = packable(config)
        if not ok:
            return demote(reason)
        from parallel_heat_tpu.service.admission import (
            estimate_pack_hbm_bytes)

        telemetry.emit("pack_header", pack=job_ids[0],
                       members=len(job_ids), job_ids=job_ids,
                       est_hbm_bytes=estimate_pack_hbm_bytes(
                           [s.config for s in specs]))
        member_stems = [store.checkpoint_stem(jid) for jid in job_ids]
        if any(ckpt.latest_checkpoint(st) is not None
               for st in member_stems):
            return demote("a member already has solo checkpoint lineage")

        policy = SupervisorPolicy(
            checkpoint_every=(specs[0].checkpoint_every
                              or default_checkpoint_every(config)),
            guard_interval=specs[0].guard_interval,
            max_retries=specs[0].max_retries,
            backoff_base_s=specs[0].backoff_base_s)
        from parallel_heat_tpu.ensemble.supervised import (
            run_ensemble_supervised)

        try:
            sres = run_ensemble_supervised(
                config, len(job_ids), store.pack_stem(worker_id),
                policy=policy, telemetry=telemetry,
                member_stems=member_stems, say=say)
        except ckpt.StemLockError as e:
            record_all("permanent_failure", kind="stem_locked",
                       diagnosis=str(e))
            return EXIT_PERMANENT_FAILURE
        except PermanentFailure as e:
            record_all("permanent_failure", kind=e.kind,
                       diagnosis=e.diagnosis)
            return EXIT_PERMANENT_FAILURE

        steps = sres.member_steps
        if sres.interrupted:
            record_all("preempted", reason=sres.signal_name,
                       per_member=[{"steps_done": int(steps[i])}
                                   for i in range(len(job_ids))],
                       last_checkpoint=(str(sres.last_checkpoint)
                                        if sres.last_checkpoint
                                        else None))
            return EXIT_PREEMPTED
        per = []
        for i in range(len(job_ids)):
            m = sres.result.member(i)
            per.append({"steps_done": int(steps[i]),
                        "converged": m.converged,
                        "residual": m.residual})
        record_all("completed", per_member=per, retries=sres.retries)
        return 0
    finally:
        telemetry.close()
        if hb is not None:
            hb.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="parallel_heat_tpu.service.worker",
        description="heatd worker: one process, one job attempt "
                    "(normally launched by the daemon, not by hand)")
    ap.add_argument("--root", required=True)
    ap.add_argument("--job", default=None)
    ap.add_argument("--jobs", default=None, metavar="ID,ID,...",
                    help="packed dispatch: run these compatible jobs "
                         "as one ensemble program")
    ap.add_argument("--worker", required=True)
    ap.add_argument("--attempt", type=int, default=1)
    ap.add_argument("--hb-interval", type=float, default=None)
    ap.add_argument("--deadline-t", type=float, default=None,
                    help="absolute unix deadline (daemon-computed)")
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args(argv)
    say = print if args.verbose else None
    if args.jobs:
        return execute_pack(args.root,
                            [j for j in args.jobs.split(",") if j],
                            args.worker, hb_interval_s=args.hb_interval,
                            say=say)
    if not args.job:
        ap.error("one of --job / --jobs is required")
    return execute_job(args.root, args.job, args.worker, args.attempt,
                       deadline_t=args.deadline_t,
                       hb_interval_s=args.hb_interval, say=say)


if __name__ == "__main__":
    sys.exit(main())
