"""Solver-as-a-service: the ``heatd`` daemon, its durable job queue,
and the client tooling (ROADMAP item 2).

Contract (SEMANTICS.md "Job durability"): an ACCEPTED job is never
silently lost — it ends ``completed`` / ``quarantined`` / ``cancelled``
/ ``deadline_expired``, or sits in the journal with its resume state
(queued, or requeued after a worker death / daemon drain) for the next
daemon to pick up. See ``service/store.py`` for the crash-safe disk
protocol, ``service/daemon.py`` for the scheduler, and
``service/worker.py`` for the per-attempt execution path.
"""

from parallel_heat_tpu.service.store import (
    EXIT_CANCELLED,
    EXIT_DEADLINE,
    EXIT_QUARANTINED,
    EXIT_REJECTED,
    FAILFAST_KINDS,
    TERMINAL_STATES,
    JobSpec,
    JobStore,
    JobView,
    Journal,
    reduce_journal,
)
from parallel_heat_tpu.service.admission import (
    admission_verdict,
    estimate_job_hbm_bytes,
)
from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig
from parallel_heat_tpu.service import client

__all__ = [
    "Heatd",
    "HeatdConfig",
    "JobSpec",
    "JobStore",
    "JobView",
    "Journal",
    "reduce_journal",
    "admission_verdict",
    "estimate_job_hbm_bytes",
    "client",
    "TERMINAL_STATES",
    "FAILFAST_KINDS",
    "EXIT_REJECTED",
    "EXIT_QUARANTINED",
    "EXIT_CANCELLED",
    "EXIT_DEADLINE",
]
