"""Durable job store: the crash-safe substrate under ``heatd``.

The serving layer's whole contract — **no accepted job is ever
silently lost** (SEMANTICS.md "Job durability") — reduces to two disk
disciplines, both inherited from ``utils/checkpoint.py``'s generation
protocol:

- **atomic rename commits** for every record a reader may race
  (job specs, spool submissions, result records, heartbeats): a file
  either exists complete or not at all; temp names never match what
  discovery scans for, so a SIGKILLed writer's torn file is invisible;
- an **append-only state journal** (``journal.jsonl``) as the single
  source of truth for job state: one fsynced JSON line per transition,
  replayed through the pure reducer :func:`reduce_journal` to rebuild
  the exact queue state after any crash. A torn final line (the writer
  died mid-append) is skipped on replay — everything before it is a
  valid prefix, exactly the torn-tail contract
  ``tools/metrics_report.py`` reads telemetry streams with.

The daemon (``service/daemon.py``) is the journal's only writer;
workers and clients communicate through rename-committed records the
daemon observes (spool submissions in, result records out), so "who
may write what" is one sentence and the no-double-terminal invariant
has a single enforcement point. State is *derived*, never cached: the
daemon replays the journal each scheduling pass, which is what makes
its own SIGKILL recoverable by construction — there is nothing in
memory to lose.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from parallel_heat_tpu.utils.checkpoint import _fsync_replace

JOURNAL_SCHEMA_VERSION = 1

# --- process exit codes (the service rows of the documented table;
# supervisor.py owns 3/EXIT_PREEMPTED and 4/EXIT_PERMANENT_FAILURE,
# argparse owns 2) ----------------------------------------------------
# EXIT_REJECTED: the admission gate refused the submission (queue depth
# or HBM budget); the verdict carries a retry-after hint — resubmit
# later, nothing was enqueued.
EXIT_REJECTED = 5
# EXIT_QUARANTINED / EXIT_CANCELLED / EXIT_DEADLINE: `heatd submit
# --wait` terminal-state mappings (the job itself reached a journaled
# terminal state; its checkpoints/telemetry remain on disk).
EXIT_QUARANTINED = 6
EXIT_CANCELLED = 7
EXIT_DEADLINE = 8

# Terminal journal states: every ACCEPTED job ends in exactly one of
# these (or sits durably queued/running with its resume state
# journaled). `rejected` is terminal too but pre-acceptance — the job
# was never owned by the service.
TERMINAL_STATES = ("completed", "quarantined", "cancelled",
                   "deadline_expired")
# PermanentFailure kinds that fail FAST to quarantine: deterministic
# verdicts (bad physics, eps below the dtype floor, persistent drift,
# a spec the worker cannot even materialize into a HeatConfig) that
# re-running on another worker cannot change. Everything else —
# exhausted retry budgets, orphaned workers, spawn errors — is treated
# as possibly-environmental and re-admitted under backoff until the
# distinct-worker quarantine threshold says the job itself is poison.
FAILFAST_KINDS = ("unstable", "stalled", "drift", "bad_spec")


@dataclass
class JobSpec:
    """One submission: the solver config plus service-level knobs.

    Committed to ``jobs/<job_id>.json`` by atomic rename at acceptance
    (before the ``accepted`` journal line — a crash between the two
    re-runs the idempotent handshake from the spool copy). ``config``
    is the ``HeatConfig`` dict (``to_json`` round trip); the worker
    materializes it with full validation."""

    job_id: str
    config: dict
    # Wall-seconds from ACCEPTANCE to the deadline; None = none. An
    # expired job is interrupted through the supervisor's flag-only
    # path and journaled `deadline_expired`.
    deadline_s: Optional[float] = None
    # In-worker supervisor knobs (service-level requeue is the layer
    # ABOVE this: a worker that exhausts max_retries exits with a
    # permanent-failure record and the daemon decides requeue vs
    # quarantine).
    max_retries: int = 3
    checkpoint_every: Optional[int] = None
    guard_interval: Optional[int] = None
    backoff_base_s: float = 0.5
    submitted_t: float = 0.0
    # Chaos harness: FaultPlan kwargs applied ONLY on attempt
    # `faults_on_attempt` (a re-dispatched attempt builds a fresh plan,
    # so an ungated one-shot fault would re-fire forever).
    faults: Optional[dict] = None
    faults_on_attempt: int = 1
    # Causal trace context born at client.submit (utils/tracing.py:
    # {"trace_id", "span_id"} — the root submit span). Rides the
    # rename-committed job record; the daemon stamps the trace_id on
    # every journal line for the job and hands the context to the
    # worker via env, so the worker's telemetry envelope joins the
    # same trace. None = an untraced submission (older clients).
    trace: Optional[dict] = None
    # Fleet-router provenance (service/fleet.py route_submission:
    # {"kind": "exact"|"prefix"|"capacity"|"load", "partition",
    # "donor_key", "gen_step"}) — rides the spool record so the
    # daemon's `accepted` line carries WHY the job landed on this
    # partition; metrics_report's peer-cache-hit rate and the
    # fleet_cache_route chaos cell read it back. None = a direct
    # (unrouted) submission.
    route: Optional[dict] = None

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "JobSpec":
        d = json.loads(s)
        known = {f for f in cls.__dataclass_fields__}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclass
class JobView:
    """Reduced state of one job — the output of :func:`reduce_journal`,
    never stored: always recomputed from the journal."""

    job_id: str
    state: str = "queued"
    accepted_t: Optional[float] = None
    deadline_t: Optional[float] = None
    hbm_bytes: int = 0
    attempts: int = 0
    worker: Optional[str] = None
    first_dispatch_t: Optional[float] = None
    last_dispatch_t: Optional[float] = None
    terminal_t: Optional[float] = None
    kind: Optional[str] = None
    diagnosis: Optional[str] = None
    # (worker_id, kind) per failure/orphaning — the quarantine
    # classifier counts DISTINCT workers here.
    failures: List[Tuple[str, str]] = field(default_factory=list)
    not_before: float = 0.0
    cancel_requested: bool = False
    requeues: int = 0
    steps_done: Optional[int] = None
    retry_after_s: Optional[float] = None
    reason: Optional[str] = None
    # Trace id from the `accepted` journal line (heattrace joins the
    # journal's queue spans to the worker telemetry by this id).
    trace_id: Optional[str] = None
    # Cache provenance from the `completed` line of a cache-served job
    # ({"hit": "exact"|"converged", "key", "donor",
    # "generation_step"}) — the client's round-trip proof that the
    # verdict came from a committed donor lineage, not a fresh solve.
    cached: Optional[dict] = None
    # Cross-host adoption lineage (service/fleet.py): one record per
    # `adopted` journal line — {"host", "from_host", "epoch", "t"}.
    # Pure provenance: adoption changes no job state (the ordinary
    # orphan/requeue machinery does the re-dispatching); heatq's
    # federated audit judges the lineage against host_lost lines.
    adoptions: List[dict] = field(default_factory=list)

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    @property
    def distinct_failed_workers(self) -> int:
        return len({w for w, _ in self.failures})


def reduce_journal(events, state=None
                   ) -> Tuple[Dict[str, JobView], List[str]]:
    """Pure reducer: journal events -> per-job views + anomalies.

    THE durability contract lives here: a job is whatever its journal
    prefix says it is, and a terminal state is absorbing — any further
    terminal/dispatch event for the job is reported as an anomaly
    (``double terminal``), which the chaos suite asserts stays empty
    across daemon kills and restarts. Unknown events and unknown
    fields are ignored (forward compatibility), never fatal.

    The reduction is a left fold, exposed as one: pass ``state`` (a
    previous call's ``(jobs, anomalies)``) to fold only the events
    appended since — ``reduce(prefix) then reduce(suffix, state)``
    equals ``reduce(prefix + suffix)``, which is how the daemon keeps
    each scheduling pass O(new events) instead of re-parsing the whole
    journal (pinned by ``test_reducer_incremental_fold_equivalence``).
    """
    jobs: Dict[str, JobView] = state[0] if state else {}
    anomalies: List[str] = state[1] if state else []
    for e in events:
        jid = e.get("job_id")
        ev = e.get("event")
        if jid is None or ev is None:
            continue  # daemon lifecycle / foreign line
        t = e.get("t_wall")
        v = jobs.get(jid)
        if v is None:
            v = jobs[jid] = JobView(job_id=jid)
            if ev not in ("accepted", "rejected"):
                anomalies.append(
                    f"{jid}: first journal event is {ev!r} (missing "
                    f"accepted record)")
        if ev == "accepted":
            if v.accepted_t is not None:
                anomalies.append(f"{jid}: duplicate accepted event")
                continue
            v.state = "queued"
            v.accepted_t = t
            v.hbm_bytes = int(e.get("hbm_bytes") or 0)
            if isinstance(e.get("trace_id"), str):
                v.trace_id = e["trace_id"]
            if e.get("deadline_s") is not None and t is not None:
                v.deadline_t = t + float(e["deadline_s"])
            continue
        if ev == "rejected":
            v.state = "rejected"
            v.reason = e.get("reason")
            v.retry_after_s = e.get("retry_after_s")
            v.terminal_t = t
            continue
        if ev == "cancel_requested":
            v.cancel_requested = True
            continue
        if ev == "adopted":
            # Fleet takeover lineage (recorded even for a terminal
            # job — the federated AUDIT flags that, the fold stays a
            # pure recorder): which host adopted the in-flight job at
            # which lease epoch. State is untouched; the adopting
            # daemon's reconcile pass drives the orphan->requeue->
            # re-dispatch transitions through the ordinary events.
            v.adoptions.append({"host": e.get("host"),
                                "from_host": e.get("from_host"),
                                "epoch": e.get("epoch"), "t": t})
            continue
        if v.terminal:
            if ev in TERMINAL_STATES or ev == "dispatched":
                anomalies.append(
                    f"{jid}: event {ev!r} after terminal state "
                    f"{v.state!r} (double terminal)")
            continue
        if ev == "dispatched":
            v.state = "running"
            v.attempts = int(e.get("attempt", v.attempts + 1))
            v.worker = e.get("worker")
            v.last_dispatch_t = t
            if v.first_dispatch_t is None:
                v.first_dispatch_t = t
        elif ev in ("worker_failed", "orphaned"):
            v.state = "failed"
            kind = e.get("kind") or ("orphaned" if ev == "orphaned"
                                     else "unknown")
            v.failures.append((e.get("worker") or "?", kind))
            v.kind = kind
            if e.get("diagnosis"):
                v.diagnosis = e["diagnosis"]
        elif ev == "requeued":
            v.state = "queued"
            v.requeues += 1
            v.not_before = float(e.get("not_before") or 0.0)
            v.reason = e.get("reason")
            if e.get("steps_done") is not None:
                # A drain/preemption requeue carries the flushed
                # checkpoint's progress — the journaled resume state.
                v.steps_done = e["steps_done"]
        elif ev in TERMINAL_STATES:
            v.state = ev
            v.terminal_t = t
            if isinstance(e.get("cache"), dict):
                v.cached = e["cache"]
            if e.get("kind"):
                v.kind = e["kind"]
            if e.get("diagnosis"):
                v.diagnosis = e["diagnosis"]
            if e.get("steps_done") is not None:
                v.steps_done = e["steps_done"]
            if e.get("reason"):
                v.reason = e["reason"]
    return jobs, anomalies


def read_journal_file(path) -> Tuple[list, int, bool]:
    """Tolerant journal parse -> ``(events, n_bad_lines, torn_tail)``.

    Same contract as ``tools/metrics_report.py::load_events`` (which
    cannot be imported from package code): a torn FINAL line — this
    reader racing the appender, or the appender SIGKILLed mid-write —
    is skipped, not counted bad; everything before it is a valid
    prefix. Missing file = empty journal (a fresh queue)."""
    events, bad, torn = [], 0, False
    try:
        with open(path, "rb") as f:
            text = f.read().decode("utf-8", errors="replace")
    except OSError:
        return events, bad, torn
    complete = text.endswith("\n")
    lines = text.split("\n")
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1 and not complete:
                torn = True
            else:
                bad += 1
            continue
        if isinstance(rec, dict) and "event" in rec:
            events.append(rec)
        else:
            bad += 1
    return events, bad, torn


class Journal:
    """Append-only fsynced JSONL journal (the daemon's write handle).

    Each :meth:`append` stamps the envelope (schema/event/t_wall/pid),
    serializes to ONE line, writes it through a single ``os.write`` on
    an ``O_APPEND`` descriptor and fsyncs — a SIGKILL between any two
    appends loses nothing, a SIGKILL mid-append leaves at most one
    torn tail line the replay skips. The lock serializes appends from
    the owning process; cross-process exclusion is by design upstream
    (one daemon per queue root — the daemon heartbeat names the owner).
    """

    def __init__(self, path):
        self.path = str(path)
        parent = os.path.dirname(os.path.abspath(self.path))
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        # Envelope fields stamped on EVERY append (the federated
        # daemon sets {"host": ...} here so per-host attribution —
        # adoption counters, per-host cache hit rates — needs no
        # per-call-site plumbing). Unknown fields are ignored by the
        # reducer; single-daemon roots leave this empty.
        self.extra: dict = {}
        self._fd = os.open(self.path,
                           os.O_CREAT | os.O_WRONLY | os.O_APPEND, 0o644)

    def append(self, event: str, **fields) -> dict:
        rec = {"schema": JOURNAL_SCHEMA_VERSION, "event": event,
               "t_wall": time.time(), "pid": os.getpid()}
        rec.update(self.extra)
        rec.update(fields)
        line = (json.dumps(rec) + "\n").encode()
        with self._lock:
            if self._fd < 0:
                raise RuntimeError("journal is closed")
            os.write(self._fd, line)
            os.fsync(self._fd)
        return rec

    def close(self) -> None:
        with self._lock:
            if self._fd >= 0:
                os.close(self._fd)
                self._fd = -1

    def __enter__(self) -> "Journal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class JobStore:
    """On-disk layout of one queue root + the atomic-record helpers.

    ::

        <root>/journal.jsonl        state journal (daemon-written)
        <root>/jobs/<id>.json       committed job specs
        <root>/spool/<id>.json      client submissions awaiting admission
        <root>/cancel/<id>          cancellation request markers
        <root>/results/<id>.a<N>.json  per-attempt worker outcome records
        <root>/hb/<worker>.json     worker liveness heartbeats
        <root>/heatd.json           daemon status heartbeat
        <root>/ck/<id>/ck*          per-job checkpoint generation family
        <root>/telemetry/<id>.jsonl per-job telemetry sink (appends
                                    across attempts — one stream per job)
        <root>/logs/<worker>.log    worker stdout/stderr
    """

    def __init__(self, root, create: bool = True):
        self.root = str(root)
        self.journal_path = os.path.join(self.root, "journal.jsonl")
        if create:
            for d in ("jobs", "spool", "cancel", "results", "hb", "ck",
                      "telemetry", "logs"):
                os.makedirs(os.path.join(self.root, d), exist_ok=True)
        self._journal: Optional[Journal] = None

    # -- journal ---------------------------------------------------------

    @property
    def journal(self) -> Journal:
        if self._journal is None:
            self._journal = Journal(self.journal_path)
        return self._journal

    def read_journal(self) -> Tuple[list, int, bool]:
        return read_journal_file(self.journal_path)

    def replay(self) -> Tuple[Dict[str, JobView], List[str]]:
        events, _bad, _torn = self.read_journal()
        return reduce_journal(events)

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    # -- atomic JSON records ---------------------------------------------

    def write_json_atomic(self, path: str, doc: dict) -> str:
        """Rename-committed JSON write (checkpoint.py discipline): the
        dotted temp name can never match a ``*.json`` discovery scan,
        and the publish is fsync + rename + dirsync."""
        tmp = os.path.join(os.path.dirname(path),
                           f".tmp-{os.getpid()}-{os.path.basename(path)}")
        with open(tmp, "w") as f:
            json.dump(doc, f)
        _fsync_replace(tmp, path)
        return path

    @staticmethod
    def read_json(path: str) -> Optional[dict]:
        """None on missing/torn/foreign — readers race writers by
        design and must degrade, never crash."""
        try:
            with open(path) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (OSError, ValueError):
            return None

    # -- spool (client -> daemon submissions) ----------------------------

    def spool_path(self, job_id: str) -> str:
        return os.path.join(self.root, "spool", f"{job_id}.json")

    def spool_submit(self, spec: JobSpec) -> str:
        return self.write_json_atomic(self.spool_path(spec.job_id),
                                      json.loads(spec.to_json()))

    def iter_spool(self) -> List[str]:
        d = os.path.join(self.root, "spool")
        try:
            names = sorted(os.listdir(d))
        except OSError:
            return []
        return [n[:-5] for n in names
                if n.endswith(".json") and not n.startswith(".")]

    def read_spool(self, job_id: str) -> Optional[JobSpec]:
        doc = self.read_json(self.spool_path(job_id))
        if doc is None:
            return None
        try:
            return JobSpec.from_json(json.dumps(doc))
        except (TypeError, ValueError):
            return None

    def drop_spool(self, job_id: str) -> None:
        try:
            os.unlink(self.spool_path(job_id))
        except OSError:
            pass

    # -- committed job specs ---------------------------------------------

    def job_record_path(self, job_id: str) -> str:
        return os.path.join(self.root, "jobs", f"{job_id}.json")

    def commit_job_record(self, spec: JobSpec) -> str:
        return self.write_json_atomic(self.job_record_path(spec.job_id),
                                      json.loads(spec.to_json()))

    def load_spec(self, job_id: str) -> JobSpec:
        doc = self.read_json(self.job_record_path(job_id))
        if doc is None:
            raise FileNotFoundError(
                f"no committed job record for {job_id!r} under "
                f"{self.root!r}")
        return JobSpec.from_json(json.dumps(doc))

    # -- cancellation markers --------------------------------------------

    def request_cancel(self, job_id: str) -> None:
        path = os.path.join(self.root, "cancel", job_id)
        fd = os.open(path, os.O_CREAT | os.O_WRONLY, 0o644)
        os.close(fd)

    def cancel_requests(self) -> List[str]:
        d = os.path.join(self.root, "cancel")
        try:
            return sorted(n for n in os.listdir(d)
                          if not n.startswith("."))
        except OSError:
            return []

    def clear_cancel(self, job_id: str) -> None:
        try:
            os.unlink(os.path.join(self.root, "cancel", job_id))
        except OSError:
            pass

    # -- per-job artifacts -----------------------------------------------

    def checkpoint_stem(self, job_id: str) -> str:
        d = os.path.join(self.root, "ck", job_id)
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, "ck")

    def pack_stem(self, worker_id: str) -> str:
        """Ensemble-generation stem of one packed dispatch (rollback
        targets while the pack runs). Distinct from every per-job
        ``checkpoint_stem`` so a member's later SOLO resume can never
        confuse the two generation families."""
        d = os.path.join(self.root, "ck", f"pack-{worker_id}")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, "ens")

    def telemetry_path(self, job_id: str) -> str:
        return os.path.join(self.root, "telemetry", f"{job_id}.jsonl")

    def worker_log_path(self, worker_id: str) -> str:
        return os.path.join(self.root, "logs", f"{worker_id}.log")

    def result_path(self, job_id: str, attempt: int) -> str:
        return os.path.join(self.root, "results",
                            f"{job_id}.a{int(attempt):04d}.json")

    def write_result(self, job_id: str, attempt: int, doc: dict) -> str:
        return self.write_json_atomic(self.result_path(job_id, attempt),
                                      doc)

    def read_result(self, job_id: str, attempt: int) -> Optional[dict]:
        return self.read_json(self.result_path(job_id, attempt))

    # -- heartbeats ------------------------------------------------------

    def worker_hb_path(self, worker_id: str) -> str:
        return os.path.join(self.root, "hb", f"{worker_id}.json")

    def write_worker_hb(self, worker_id: str, doc: dict) -> None:
        try:
            self.write_json_atomic(self.worker_hb_path(worker_id), doc)
        except OSError:
            pass  # liveness probe only; never kill the worker over it

    def read_worker_hb(self, worker_id: str) -> Optional[dict]:
        return self.read_json(self.worker_hb_path(worker_id))

    def daemon_status_path(self) -> str:
        return os.path.join(self.root, "heatd.json")

    def write_daemon_status(self, doc: dict) -> None:
        try:
            self.write_json_atomic(self.daemon_status_path(), doc)
        except OSError:
            pass

    def read_daemon_status(self) -> Optional[dict]:
        return self.read_json(self.daemon_status_path())
