"""Client side of the queue: submit / wait / status / cancel.

Everything is file-based against the queue root — the transport is the
same crash-safe store the daemon trusts, so there is no socket to
leak, no RPC schema to version, and a submission is durable the moment
its rename lands. The handshake:

- :func:`submit` rename-commits a spool record, then polls the JOURNAL
  for the daemon's verdict (``accepted`` or ``rejected`` + retry-after)
  — the journal is the single response channel, so a daemon crash
  mid-handshake can never tell the client one thing and disk another;
- :func:`wait` polls the journal until the job's terminal state;
- :func:`cancel` rename-creates a cancellation marker the daemon
  honors on its next pass;
- :func:`status` reads the journal replay + the daemon's status
  heartbeat.

A daemon that never answers is a loud ``TimeoutError`` naming the fix
(start ``heatd serve``), not a silent hang.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from dataclasses import asdict
from typing import Optional, Union

from parallel_heat_tpu.service.store import JobSpec, JobStore, JobView
from parallel_heat_tpu.utils.tracing import (
    TraceContext,
    new_trace_id,
    submit_span_id,
)

_job_seq = itertools.count()


def make_job_id(clock=time.time) -> str:
    """Collision-free without randomness: wall-millis + pid + an
    in-process counter (two clients on one host differ by pid; two
    submissions from one client differ by counter)."""
    return f"j{int(clock() * 1000):013d}-{os.getpid()}-{next(_job_seq)}"


def _spec_config(config) -> dict:
    if isinstance(config, dict):
        return dict(config)
    # A HeatConfig (or anything with its to_json contract).
    return json.loads(config.to_json())


def submit(root: str, config, *, job_id: Optional[str] = None,
           deadline_s: Optional[float] = None, max_retries: int = 3,
           checkpoint_every: Optional[int] = None,
           guard_interval: Optional[int] = None,
           backoff_base_s: float = 0.5,
           faults: Optional[dict] = None, faults_on_attempt: int = 1,
           accept_timeout_s: float = 15.0, poll_s: float = 0.1,
           route: Optional[dict] = None,
           clock=time.time, sleep_fn=time.sleep) -> dict:
    """Submit one job; block until the daemon's admission verdict.

    Returns ``{"job_id", "accepted": True, "trace_id"}`` or
    ``{"job_id", "accepted": False, "reason", "retry_after_s",
    "trace_id"}`` (``trace_id`` is the causal trace born here —
    ``tools/heattrace.py`` renders its end-to-end timeline). Raises
    ``TimeoutError`` when no verdict lands within
    ``accept_timeout_s`` — the daemon is not running (or not watching
    this root)."""
    store = JobStore(root)
    jid = job_id or make_job_id(clock)
    existing, _ = store.replay()
    if jid in existing:
        # The daemon dedupes spool entries against the journal (crash
        # idempotence), so a re-used id would be silently dropped and
        # the poll below would report the OLD job's verdict as if it
        # were this submission's. Refuse up front instead.
        raise ValueError(
            f"job_id {jid!r} already has journal history on this "
            f"queue root (state: {existing[jid].state}) — job ids are "
            f"single-use; omit --job-id for a generated one")
    # The trace is born HERE: the submit span is the causal root every
    # later hop (accept, dispatch, worker, chunk, barrier) hangs off.
    # Deterministic span id, entropy only in the trace id — heattrace
    # reconstructs the whole chain from artifacts alone.
    trace = TraceContext(new_trace_id(clock), submit_span_id(jid))
    spec = JobSpec(job_id=jid, config=_spec_config(config),
                   deadline_s=deadline_s, max_retries=max_retries,
                   checkpoint_every=checkpoint_every,
                   guard_interval=guard_interval,
                   backoff_base_s=backoff_base_s,
                   submitted_t=clock(), faults=faults,
                   faults_on_attempt=faults_on_attempt,
                   trace=trace.to_dict(), route=route)
    store.spool_submit(spec)
    deadline = clock() + accept_timeout_s
    while True:
        jobs, _ = store.replay()
        v = jobs.get(jid)
        if v is not None:
            if v.state == "rejected":
                return {"job_id": jid, "accepted": False,
                        "reason": v.reason,
                        "retry_after_s": v.retry_after_s,
                        "trace_id": trace.trace_id}
            return {"job_id": jid, "accepted": True,
                    "trace_id": trace.trace_id}
        if clock() >= deadline:
            raise TimeoutError(
                f"no admission verdict for {jid!r} within "
                f"{accept_timeout_s:g}s — is `heatd serve --queue "
                f"{root}` running? (the submission is spooled and will "
                f"be admitted when a daemon picks it up; cancel it by "
                f"removing {store.spool_path(jid)!r})")
        sleep_fn(poll_s)


def wait(root: str, job_id: str, timeout_s: Optional[float] = None,
         poll_s: float = 0.25, clock=time.time,
         sleep_fn=time.sleep) -> JobView:
    """Poll until ``job_id`` reaches a terminal (or rejected) state;
    returns its :class:`JobView`."""
    store = JobStore(root, create=False)
    t0 = clock()
    while True:
        jobs, _ = store.replay()
        v = jobs.get(job_id)
        if v is not None and (v.terminal or v.state == "rejected"):
            return v
        if timeout_s is not None and clock() - t0 >= timeout_s:
            raise TimeoutError(
                f"job {job_id!r} not terminal after {timeout_s:g}s "
                f"(state: {v.state if v is not None else 'unknown'})")
        sleep_fn(poll_s)


def cancel(root: str, job_id: str) -> bool:
    """Request cancellation; returns False when the job is unknown or
    already terminal (nothing to do). The daemon journals the actual
    ``cancelled`` transition on its next pass."""
    store = JobStore(root, create=False)
    jobs, _ = store.replay()
    v = jobs.get(job_id)
    if v is None or v.terminal or v.state == "rejected":
        return False
    store.request_cancel(job_id)
    return True


def status(root: str,
           job_id: Optional[str] = None) -> dict:
    """Queue snapshot: daemon heartbeat + per-job reduced views (all
    jobs, or one). Views are plain dicts (JSON-ready for --json)."""
    store = JobStore(root, create=False)
    jobs, anomalies = store.replay()
    if job_id is not None:
        jobs = {job_id: jobs[job_id]} if job_id in jobs else {}
    return {"daemon": store.read_daemon_status(),
            "jobs": {jid: _view_dict(v) for jid, v in
                     sorted(jobs.items())},
            "anomalies": anomalies}


def _view_dict(v: Union[JobView, dict]) -> dict:
    return asdict(v) if isinstance(v, JobView) else dict(v)


# ---------------------------------------------------------------------------
# Federated entry points (SEMANTICS.md "Fleet durability"): the same
# file-based handshake against a FLEET root — the router picks the
# partition, the spool record carries the routing provenance, and the
# partition's lease holder answers through that partition's journal.
# ---------------------------------------------------------------------------

def fleet_submit(fleet_root: str, config, *,
                 job_id: Optional[str] = None,
                 deadline_s: Optional[float] = None,
                 max_retries: int = 3,
                 checkpoint_every: Optional[int] = None,
                 guard_interval: Optional[int] = None,
                 backoff_base_s: float = 0.5,
                 faults: Optional[dict] = None,
                 faults_on_attempt: int = 1,
                 accept_timeout_s: float = 15.0, poll_s: float = 0.1,
                 clock=time.time, sleep_fn=time.sleep) -> dict:
    """Route one submission across the fleet
    (:func:`~parallel_heat_tpu.service.fleet.route_submission`:
    exact peer-cache hit > longest admissible checkpoint prefix >
    capacity fit > least loaded), then run the ordinary durable
    submit handshake against the chosen partition. The returned
    verdict adds ``partition`` and ``route`` (the decision, also
    journaled on the ``accepted`` line)."""
    from parallel_heat_tpu.service.fleet import route_submission

    decision = route_submission(fleet_root, _spec_config(config),
                                now=clock())
    route = {k: decision[k] for k in ("kind", "partition",
                                      "donor_key", "gen_step")}
    verdict = submit(decision["root"], config, job_id=job_id,
                     deadline_s=deadline_s, max_retries=max_retries,
                     checkpoint_every=checkpoint_every,
                     guard_interval=guard_interval,
                     backoff_base_s=backoff_base_s, faults=faults,
                     faults_on_attempt=faults_on_attempt,
                     accept_timeout_s=accept_timeout_s, poll_s=poll_s,
                     route=route, clock=clock, sleep_fn=sleep_fn)
    verdict["partition"] = decision["partition"]
    verdict["route"] = route
    return verdict


def _locate(fleet_root: str, job_id: str) -> str:
    from parallel_heat_tpu.service.fleet import find_job

    hit = find_job(fleet_root, job_id)
    if hit is None:
        raise KeyError(f"job {job_id!r} is on no partition under "
                       f"fleet root {fleet_root!r}")
    return hit[1]


def fleet_wait(fleet_root: str, job_id: str,
               timeout_s: Optional[float] = None, poll_s: float = 0.25,
               clock=time.time, sleep_fn=time.sleep) -> JobView:
    """Fleet-level :func:`wait`: locate the job's partition, then poll
    that partition's journal. Adoption keeps a job on its partition —
    the waiting client never needs to re-route mid-wait."""
    return wait(_locate(fleet_root, job_id), job_id,
                timeout_s=timeout_s, poll_s=poll_s, clock=clock,
                sleep_fn=sleep_fn)


def fleet_cancel(fleet_root: str, job_id: str) -> bool:
    try:
        return cancel(_locate(fleet_root, job_id), job_id)
    except KeyError:
        return False
