"""``heatd`` — the service command line.

Subcommands (also reachable as ``python -m parallel_heat_tpu serve
...`` etc.; the solver CLI forwards them here):

- ``serve``   run the daemon against a queue root (SIGTERM = graceful
  drain, exit ``EXIT_PREEMPTED``);
- ``submit``  enqueue one job (compact solver flags or ``--spec``
  JSON); ``--wait`` blocks to the terminal state and maps it onto the
  documented exit-code table;
- ``status``  queue + daemon snapshot (``--json`` for scripts;
  ``tools/heatq.py`` is the richer inspector);
- ``cancel``  request cancellation of a job;
- ``drain``   SIGTERM the daemon named in the queue's status heartbeat.

Federated subcommands (SEMANTICS.md "Fleet durability" — many heatds,
one durable service over a shared fleet root):

- ``fleet-init``    lay out a fleet root (queue partitions + lease/
  host coordination dirs + the ``fleet.json`` marker);
- ``fleet-serve``   run one federated host: claims partition leases,
  steps one ordinary daemon per held partition, reclaims stale peers'
  leases and adopts their in-flight jobs;
- ``fleet-submit``  route one job across the fleet (exact peer-cache
  hit > longest admissible checkpoint prefix > capacity > load) and
  run the ordinary durable submit handshake on the chosen partition;
- ``fleet-status``  federated snapshot: leases, hosts, per-partition
  job counts (``tools/heatq.py <fleet-root> --check`` is the auditor).

Observability (docs/OBSERVABILITY.md "Time series"):

- ``metrics-serve``  run the fleet flight recorder over a queue or
  fleet root: folds journals + telemetry into the durable series DB
  under ``<root>/obs/``, serves the live series as OpenMetrics on a
  stdlib HTTP endpoint, and trips journaled alerts (tuned-baseline
  ``perf_regression``, queue-wait growth, cache-hit collapse,
  heartbeat gaps). Strictly observation-only.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
from typing import Optional, Sequence

from parallel_heat_tpu.service.store import (
    EXIT_CANCELLED,
    EXIT_DEADLINE,
    EXIT_QUARANTINED,
    EXIT_REJECTED,
)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="heatd",
        description="fault-tolerant solver-as-a-service daemon for "
                    "parallel_heat_tpu (durable job queue, admission "
                    "control, orphan-job recovery)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    sv = sub.add_parser("serve", help="run the daemon")
    sv.add_argument("--queue", required=True, metavar="DIR",
                    help="queue root (created if missing; the journal, "
                         "job records, per-job checkpoints and "
                         "telemetry all live here)")
    sv.add_argument("--slots", type=int, default=2,
                    help="concurrent worker processes (default 2)")
    sv.add_argument("--poll-interval", type=float, default=0.25,
                    metavar="S")
    sv.add_argument("--worker-heartbeat", type=float, default=0.5,
                    metavar="S", help="worker liveness beat cadence")
    sv.add_argument("--heartbeat-timeout", type=float, default=3.0,
                    metavar="S",
                    help="silence past this declares a worker dead and "
                         "its job orphaned (requeued with its "
                         "checkpoint lineage intact)")
    sv.add_argument("--max-queue-depth", type=int, default=16,
                    metavar="N",
                    help="admission gate: reject (with retry-after) "
                         "past this many non-terminal jobs")
    sv.add_argument("--hbm-budget-gb", type=float, default=None,
                    metavar="F",
                    help="admission gate: reject when admitted jobs' "
                         "estimated device memory would exceed this "
                         "(default: gate off)")
    sv.add_argument("--quarantine-after", type=int, default=3,
                    metavar="N",
                    help="poison-job quarantine after failures on N "
                         "distinct workers (unstable/stalled/drift/"
                         "bad_spec "
                         "verdicts quarantine immediately)")
    sv.add_argument("--retry-after", type=float, default=2.0,
                    metavar="S",
                    help="base of the rejection retry-after hint")
    sv.add_argument("--drain-grace", type=float, default=60.0,
                    metavar="S",
                    help="drain: wait this long for workers to flush "
                         "before SIGKILL escalation")
    sv.add_argument("--max-seconds", type=float, default=None,
                    metavar="S",
                    help="serve for at most S seconds then drain "
                         "(harness/smoke use; default: until SIGTERM)")
    sv.add_argument("--pack", action="store_true",
                    help="ensemble packing: coalesce compatible fresh "
                         "queued jobs (identical config + supervisor "
                         "knobs, no deadline/faults) into one batched "
                         "ensemble dispatch — per-member results fan "
                         "back to the individual job records, bitwise "
                         "the solo runs (SEMANTICS.md 'Ensemble')")
    sv.add_argument("--pack-max", type=int, default=16, metavar="B",
                    help="max members per packed dispatch (default 16)")
    sv.add_argument("--pack-wait", type=float, default=0.0, metavar="S",
                    help="coalescing dwell: hold a lone packable job "
                         "this long before dispatching it solo, so "
                         "bursts of compatible submissions pack "
                         "together (default 0: greedy)")
    sv.add_argument("--no-cache", action="store_true",
                    help="disable the content-addressed result cache "
                         "(on by default: identical semantic specs "
                         "serve completed verdicts in O(1), and "
                         "larger-budget re-submissions resume from "
                         "cached checkpoint generations — SEMANTICS.md "
                         "'Cache soundness')")
    sv.add_argument("--cache-max-bytes", type=int, default=None,
                    metavar="B",
                    help="LRU-evict cache payloads past this many "
                         "bytes (default: unbounded; in-flight prefix "
                         "donors are pinned)")
    sv.add_argument("--cache-max-entries", type=int, default=None,
                    metavar="N",
                    help="LRU-evict cache entries past this count "
                         "(default: unbounded)")
    sv.add_argument("--chaos-kill-after-accept", type=int, default=None,
                    metavar="N",
                    help="CHAOS HARNESS ONLY: SIGKILL the daemon right "
                         "after journaling the Nth accepted job — the "
                         "crash window the durability contract is "
                         "certified against")
    sv.add_argument("--chaos-kill-before-cache-put", type=int,
                    default=None, metavar="N",
                    help="CHAOS HARNESS ONLY: SIGKILL the daemon on "
                         "the Nth completion's cache admission, after "
                         "the result commit but before the "
                         "cache-index append (the svc_cache_crash "
                         "window)")

    sb = sub.add_parser("submit", help="enqueue one job")
    sb.add_argument("--queue", required=True, metavar="DIR")
    _add_submit_flags(sb)

    st = sub.add_parser("status", help="queue + daemon snapshot")
    st.add_argument("--queue", required=True, metavar="DIR")
    st.add_argument("--job", default=None, metavar="ID")
    st.add_argument("--json", action="store_true")

    ca = sub.add_parser("cancel", help="request job cancellation")
    ca.add_argument("--queue", required=True, metavar="DIR")
    ca.add_argument("job_id")

    dr = sub.add_parser("drain", help="SIGTERM the serving daemon "
                                      "(graceful drain)")
    dr.add_argument("--queue", required=True, metavar="DIR")

    fi = sub.add_parser("fleet-init",
                        help="lay out a federated fleet root")
    fi.add_argument("--fleet", required=True, metavar="DIR")
    fi.add_argument("--partitions", type=int, default=2, metavar="N",
                    help="queue partitions (each a full single-daemon "
                         "queue root; a re-init can only grow the "
                         "count — default 2)")
    fi.add_argument("--lease-timeout", type=float, default=None,
                    metavar="S",
                    help="fleet default lease staleness threshold "
                         "(hosts may override; default 10)")

    fs = sub.add_parser("fleet-serve",
                        help="run one federated host (leases, "
                             "adoption, work stealing)")
    fs.add_argument("--fleet", required=True, metavar="DIR")
    fs.add_argument("--host", required=True, metavar="NAME",
                    help="this host's fleet-unique name (lease files "
                         "and journal lines carry it)")
    fs.add_argument("--slots", type=int, default=2,
                    help="concurrent workers PER PARTITION (default 2)")
    fs.add_argument("--poll-interval", type=float, default=0.25,
                    metavar="S")
    fs.add_argument("--worker-heartbeat", type=float, default=0.5,
                    metavar="S")
    fs.add_argument("--heartbeat-timeout", type=float, default=3.0,
                    metavar="S")
    fs.add_argument("--lease-timeout", type=float, default=None,
                    metavar="S",
                    help="lease staleness threshold this host writes "
                         "into its leases (default: fleet.json's)")
    fs.add_argument("--lease-renew", type=float, default=None,
                    metavar="S",
                    help="lease renewal cadence (default: timeout/4)")
    fs.add_argument("--max-partitions", type=int, default=None,
                    metavar="N",
                    help="most partitions to hold at once (default: "
                         "all claimable)")
    fs.add_argument("--platform", default="cpu",
                    help="capacity record: accelerator platform tag "
                         "(default cpu)")
    fs.add_argument("--max-cells", type=int, default=None, metavar="N",
                    help="capacity record: largest grid (cells) this "
                         "host volunteers for — the router sends "
                         "bigger meshes elsewhere (default: unbounded)")
    fs.add_argument("--no-steal", action="store_true",
                    help="disable work stealing (unleased backlog "
                         "partitions are still claimed, just not "
                         "counted as steals)")
    fs.add_argument("--no-cache", action="store_true")
    fs.add_argument("--max-seconds", type=float, default=None,
                    metavar="S")

    fb = sub.add_parser("fleet-submit",
                        help="route one job across the fleet and "
                             "enqueue it")
    fb.add_argument("--fleet", required=True, metavar="DIR")
    _add_submit_flags(fb)

    ft = sub.add_parser("fleet-status", help="federated snapshot "
                                             "(leases, hosts, "
                                             "partitions)")
    ft.add_argument("--fleet", required=True, metavar="DIR")
    ft.add_argument("--json", action="store_true")

    ms = sub.add_parser(
        "metrics-serve",
        help="run the flight recorder + OpenMetrics endpoint over a "
             "queue or fleet root")
    ms.add_argument("--root", required=True, metavar="DIR",
                    help="queue root or fleet root to observe (the "
                         "series DB lives under <root>/obs/)")
    ms.add_argument("--interval", type=float, default=2.0, metavar="S",
                    help="harvest cadence (default 2s)")
    ms.add_argument("--bind", default="127.0.0.1", metavar="ADDR",
                    help="HTTP bind address (default 127.0.0.1)")
    ms.add_argument("--port", type=int, default=0, metavar="N",
                    help="HTTP port (default 0: ephemeral; the bound "
                         "port is published in <root>/obs/expo.json)")
    ms.add_argument("--no-http", action="store_true",
                    help="recorder + textfile only, no endpoint")
    ms.add_argument("--textfile", default=None, metavar="FILE",
                    help="also rename-commit the exposition text here "
                         "each pass (default <root>/obs/metrics.prom)")
    ms.add_argument("--once", action="store_true",
                    help="one harvest + textfile + alert evaluation, "
                         "then exit (smoke/cron use)")
    ms.add_argument("--max-seconds", type=float, default=None,
                    metavar="S",
                    help="serve for at most S seconds then exit "
                         "(harness/smoke use; default: until SIGTERM)")
    ms.add_argument("--tune-db", default=None, metavar="DIR",
                    help="tuning DB whose measured winners become the "
                         "perf_regression baseline (default: "
                         "PHT_TUNE_DB; alerts need it)")
    ms.add_argument("--no-alerts", action="store_true",
                    help="disable alert evaluation (recorder + "
                         "exposition only)")
    ms.add_argument("--perf-fraction", type=float, default=0.5,
                    metavar="F",
                    help="perf_regression trips when a run sustains "
                         "below F x its tuned expectation "
                         "(default 0.5)")
    ms.add_argument("--perf-min-samples", type=int, default=3,
                    metavar="N",
                    help="chunk samples required before judging a "
                         "run's throughput (default 3)")
    return ap


def _add_submit_flags(sb: argparse.ArgumentParser) -> None:
    """The submission surface, shared verbatim by ``submit`` (one
    queue root) and ``fleet-submit`` (routed) — one flag vocabulary,
    two targets."""
    sb.add_argument("--nx", type=int, default=20)
    sb.add_argument("--ny", type=int, default=20)
    sb.add_argument("--nz", type=int, default=None)
    sb.add_argument("--steps", type=int, default=10_000)
    sb.add_argument("--converge", action="store_true")
    sb.add_argument("--eps", type=float, default=1e-3)
    sb.add_argument("--check-interval", type=int, default=20)
    sb.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "float64"])
    sb.add_argument("--backend", default="auto",
                    choices=["auto", "jnp", "pallas"])
    sb.add_argument("--spec", default=None, metavar="FILE",
                    help="JSON file of HeatConfig fields — overrides "
                         "the flags above (full config surface, e.g. "
                         "mesh_shape/accumulate)")
    sb.add_argument("--deadline", type=float, default=None, metavar="S",
                    help="wall-seconds from acceptance; past it the "
                         "job is interrupted (checkpoint flushed) and "
                         "journaled deadline_expired")
    sb.add_argument("--max-retries", type=int, default=3,
                    help="in-worker supervisor retry budget")
    sb.add_argument("--checkpoint-every", type=int, default=None,
                    metavar="N")
    sb.add_argument("--guard-interval", type=int, default=None,
                    metavar="N")
    sb.add_argument("--job-id", default=None)
    sb.add_argument("--faults", default=None, metavar="JSON",
                    help="fault-injection plan (FaultPlan kwargs) for "
                         "the chaos harness / smoke tests")
    sb.add_argument("--faults-on-attempt", type=int, default=1)
    sb.add_argument("--accept-timeout", type=float, default=15.0,
                    metavar="S")
    sb.add_argument("--wait", action="store_true",
                    help="block until the job's terminal state and "
                         "exit with the documented code (0 completed, "
                         f"{EXIT_QUARANTINED} quarantined, "
                         f"{EXIT_CANCELLED} cancelled, "
                         f"{EXIT_DEADLINE} deadline)")
    sb.add_argument("--timeout", type=float, default=None, metavar="S",
                    help="--wait: give up (exit 1) after S seconds")
    sb.add_argument("--quiet", action="store_true")


def _cmd_serve(args) -> int:
    from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig

    cfg = HeatdConfig(
        root=args.queue, slots=args.slots,
        poll_interval_s=args.poll_interval,
        worker_heartbeat_s=args.worker_heartbeat,
        heartbeat_timeout_s=args.heartbeat_timeout,
        max_queue_depth=args.max_queue_depth,
        hbm_budget_bytes=(int(args.hbm_budget_gb * 2**30)
                          if args.hbm_budget_gb is not None else None),
        quarantine_after=args.quarantine_after,
        retry_after_s=args.retry_after,
        drain_grace_s=args.drain_grace,
        pack_jobs=args.pack, pack_max=args.pack_max,
        pack_wait_s=args.pack_wait,
        cache_results=not args.no_cache,
        cache_max_bytes=args.cache_max_bytes,
        cache_max_entries=args.cache_max_entries,
        chaos_kill_after_accept=args.chaos_kill_after_accept,
        chaos_kill_before_cache_put=args.chaos_kill_before_cache_put)
    try:
        daemon = Heatd(cfg)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"heatd serving {args.queue} (pid {os.getpid()}, "
          f"{cfg.slots} slot(s)); SIGTERM drains gracefully")
    return daemon.serve(max_seconds=args.max_seconds)


def _submit_payload(args):
    """Shared submit/fleet-submit parse: flags -> ``(config, faults)``
    or an int exit code on a malformed --spec/--faults."""
    config = {"nx": args.nx, "ny": args.ny, "nz": args.nz,
              "steps": args.steps, "converge": args.converge,
              "eps": args.eps, "check_interval": args.check_interval,
              "dtype": args.dtype, "backend": args.backend}
    if args.spec:
        try:
            with open(args.spec) as f:
                config.update(json.load(f))
        except (OSError, ValueError) as e:
            print(f"error: cannot read --spec {args.spec}: {e}",
                  file=sys.stderr)
            return 2
    faults = None
    if args.faults:
        try:
            faults = json.loads(args.faults)
        except ValueError as e:
            print(f"error: bad --faults JSON: {e}", file=sys.stderr)
            return 2
    return config, faults


def _finish_submit(args, verdict, wait_fn, say) -> int:
    """Shared verdict/wait/exit-code tail of both submit commands."""
    jid = verdict["job_id"]
    if not verdict["accepted"]:
        retry = verdict.get("retry_after_s")
        print(f"rejected: {verdict.get('reason')}"
              + (f" — retry after {retry:.1f}s" if retry else ""),
              file=sys.stderr)
        return EXIT_REJECTED
    say(f"accepted {jid}"
        + (f" -> partition {verdict['partition']} "
           f"({verdict['route']['kind']})"
           if verdict.get("partition") else ""))
    if not args.wait:
        return 0
    try:
        v = wait_fn(jid)
    except TimeoutError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    say(f"{jid}: {v.state}"
        + (f" (steps_done={v.steps_done})"
           if v.steps_done is not None else "")
        + (f" kind={v.kind}" if v.kind else ""))
    return {"completed": 0, "quarantined": EXIT_QUARANTINED,
            "cancelled": EXIT_CANCELLED,
            "deadline_expired": EXIT_DEADLINE}.get(v.state, 1)


def _cmd_submit(args) -> int:
    from parallel_heat_tpu.service import client

    say = (lambda *a: None) if args.quiet else print
    payload = _submit_payload(args)
    if isinstance(payload, int):
        return payload
    config, faults = payload
    try:
        verdict = client.submit(
            args.queue, config, job_id=args.job_id,
            deadline_s=args.deadline, max_retries=args.max_retries,
            checkpoint_every=args.checkpoint_every,
            guard_interval=args.guard_interval, faults=faults,
            faults_on_attempt=args.faults_on_attempt,
            accept_timeout_s=args.accept_timeout)
    except TimeoutError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except ValueError as e:  # re-used --job-id
        print(f"error: {e}", file=sys.stderr)
        return 2
    return _finish_submit(
        args, verdict,
        lambda jid: client.wait(args.queue, jid,
                                timeout_s=args.timeout), say)


def _cmd_status(args) -> int:
    from parallel_heat_tpu.service import client

    doc = client.status(args.queue, job_id=args.job)
    if args.json:
        json.dump(doc, sys.stdout, indent=1)
        print()
        return 0
    d = doc.get("daemon")
    if d:
        print(f"daemon: pid {d.get('pid')} {d.get('state')} "
              f"slots={d.get('slots')} "
              f"running={d.get('running_workers')}")
    else:
        print("daemon: no status heartbeat (not running, or never "
              "started on this root)")
    for jid, v in doc["jobs"].items():
        extra = ""
        if v.get("kind"):
            extra += f" kind={v['kind']}"
        if v.get("steps_done") is not None:
            extra += f" steps={v['steps_done']}"
        if v.get("cached"):
            extra += (f" cache={v['cached'].get('hit')}"
                      f"<-{v['cached'].get('donor')}")
        print(f"  {jid}: {v['state']} attempts={v['attempts']}{extra}")
    for a in doc["anomalies"]:
        print(f"  ANOMALY: {a}")
    return 0


def _cmd_cancel(args) -> int:
    from parallel_heat_tpu.service import client

    if client.cancel(args.queue, args.job_id):
        print(f"cancellation requested for {args.job_id}")
        return 0
    print(f"error: job {args.job_id!r} unknown or already terminal",
          file=sys.stderr)
    return 2


def _cmd_drain(args) -> int:
    from parallel_heat_tpu.service.store import JobStore

    doc = JobStore(args.queue, create=False).read_daemon_status()
    pid = (doc or {}).get("pid")
    if not pid:
        print("error: no daemon status heartbeat under this queue "
              "root", file=sys.stderr)
        return 2
    try:
        os.kill(int(pid), signal.SIGTERM)
    except (ProcessLookupError, OSError) as e:
        print(f"error: cannot signal daemon pid {pid}: {e}",
              file=sys.stderr)
        return 2
    print(f"SIGTERM sent to heatd pid {pid} (graceful drain)")
    return 0


def _cmd_fleet_init(args) -> int:
    from parallel_heat_tpu.service import fleet

    try:
        doc = fleet.fleet_init(args.fleet, partitions=args.partitions,
                               lease_timeout_s=args.lease_timeout)
    except (ValueError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"fleet root {args.fleet}: {doc['partitions']} partition(s), "
          f"lease timeout {doc['lease_timeout_s']:g}s")
    return 0


def _cmd_fleet_serve(args) -> int:
    from parallel_heat_tpu.service.fleet import FleetHost, FleetHostConfig

    cfg = FleetHostConfig(
        fleet_root=args.fleet, host=args.host,
        platform=args.platform, max_cells=args.max_cells,
        lease_timeout_s=args.lease_timeout,
        lease_renew_s=args.lease_renew,
        max_partitions=args.max_partitions,
        steal=not args.no_steal, slots=args.slots,
        poll_interval_s=args.poll_interval,
        daemon_opts={"worker_heartbeat_s": args.worker_heartbeat,
                     "heartbeat_timeout_s": args.heartbeat_timeout,
                     "cache_results": not args.no_cache})
    try:
        host = FleetHost(cfg)
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(f"heatd fleet host {cfg.host!r} serving {args.fleet} "
          f"(pid {os.getpid()}, {cfg.slots} slot(s)/partition); "
          f"SIGTERM drains gracefully")
    return host.serve(max_seconds=args.max_seconds)


def _cmd_fleet_submit(args) -> int:
    from parallel_heat_tpu.service import client

    say = (lambda *a: None) if args.quiet else print
    payload = _submit_payload(args)
    if isinstance(payload, int):
        return payload
    config, faults = payload
    try:
        verdict = client.fleet_submit(
            args.fleet, config, job_id=args.job_id,
            deadline_s=args.deadline, max_retries=args.max_retries,
            checkpoint_every=args.checkpoint_every,
            guard_interval=args.guard_interval, faults=faults,
            faults_on_attempt=args.faults_on_attempt,
            accept_timeout_s=args.accept_timeout)
    except (TimeoutError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except ValueError as e:  # re-used --job-id, or not a fleet root
        print(f"error: {e}", file=sys.stderr)
        return 2
    return _finish_submit(
        args, verdict,
        lambda jid: client.fleet_wait(args.fleet, jid,
                                      timeout_s=args.timeout), say)


def _cmd_fleet_status(args) -> int:
    from parallel_heat_tpu.service import fleet

    try:
        doc = fleet.fleet_status(args.fleet)
    except (ValueError, FileNotFoundError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        json.dump(doc, sys.stdout, indent=1)
        print()
        return 0
    for name, h in doc["hosts"].items():
        print(f"host {name}: {h.get('state')} "
              f"platform={h.get('platform')} "
              f"leases={','.join(h.get('leases') or []) or '-'}")
    for p in doc["partitions"]:
        holder = (f"{p['host']} e{p['lease_epoch']}"
                  + (" STALE" if p["lease_stale"] else "")
                  if p["host"] else "unleased")
        counts = " ".join(f"{k}={v}" for k, v in
                          sorted(p["counts"].items()))
        print(f"  {p['partition']}: {holder} jobs={p['jobs']}"
              + (f" {counts}" if counts else "")
              + (f" ANOMALIES={p['anomalies']}"
                 if p["anomalies"] else ""))
    return 0


def _cmd_metrics_serve(args) -> int:
    from parallel_heat_tpu.obs.alerts import AlertEngine, AlertPolicy
    from parallel_heat_tpu.obs.expo import (
        ExpoServer, render_openmetrics, write_textfile)
    from parallel_heat_tpu.obs.series import Recorder

    if not os.path.isdir(args.root):
        print(f"error: {args.root}: not a directory", file=sys.stderr)
        return 2
    recorder = Recorder(args.root)
    tune_db = args.tune_db or os.environ.get("PHT_TUNE_DB") or None
    engine = None
    if not args.no_alerts:
        engine = AlertEngine(
            recorder.obs_dir,
            policy=AlertPolicy(perf_fraction=args.perf_fraction,
                               perf_min_samples=args.perf_min_samples))
    textfile = args.textfile or os.path.join(recorder.obs_dir,
                                             "metrics.prom")

    def _pass() -> int:
        n = recorder.poll()
        text = render_openmetrics(recorder.state)
        write_textfile(textfile, text)
        tripped = []
        if engine is not None:
            tripped = engine.evaluate(recorder.state,
                                      root=recorder.root,
                                      tune_db=tune_db)
        for a in tripped:
            print(f"ALERT {a.get('kind')}: key={a.get('key')} "
                  f"{a.get('detail')}", file=sys.stderr)
        recorder.write_heartbeat(args.interval)
        return n

    if args.once:
        n = _pass()
        print(f"obs: {n} new sample(s), "
              f"{recorder.state['n_samples']} folded, "
              f"{len(recorder.state['series'])} series -> {textfile}")
        recorder.close()
        if engine is not None:
            engine.close()
        return 0

    server = None
    if not args.no_http:
        try:
            server = ExpoServer(
                lambda: render_openmetrics(recorder.state),
                bind=args.bind, port=args.port).start()
        except OSError as e:
            print(f"error: cannot bind {args.bind}:{args.port}: {e}",
                  file=sys.stderr)
            return 2
        from parallel_heat_tpu.service.store import JobStore

        JobStore(recorder.obs_dir, create=False).write_json_atomic(
            os.path.join(recorder.obs_dir, "expo.json"),
            {"bind": server.bind, "port": server.port,
             "pid": os.getpid()})
        print(f"obs: serving OpenMetrics on "
              f"http://{server.bind}:{server.port}/metrics "
              f"(pid {os.getpid()}); SIGTERM exits cleanly")
    stop = {"flag": False}

    def _sigterm(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGTERM, _sigterm)
    import time as _time

    t0 = _time.time()
    try:
        while not stop["flag"]:
            _pass()
            if (args.max_seconds is not None
                    and _time.time() - t0 >= args.max_seconds):
                break
            deadline = _time.time() + max(args.interval, 0.05)
            while not stop["flag"] and _time.time() < deadline:
                _time.sleep(0.05)
    except KeyboardInterrupt:
        pass
    finally:
        if server is not None:
            server.stop()
        recorder.compact()
        recorder.close()
        if engine is not None:
            engine.close()
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return {"serve": _cmd_serve, "submit": _cmd_submit,
            "status": _cmd_status, "cancel": _cmd_cancel,
            "drain": _cmd_drain, "fleet-init": _cmd_fleet_init,
            "fleet-serve": _cmd_fleet_serve,
            "fleet-submit": _cmd_fleet_submit,
            "fleet-status": _cmd_fleet_status,
            "metrics-serve": _cmd_metrics_serve}[args.cmd](args)


if __name__ == "__main__":
    raise SystemExit(main())
