"""Content-addressed result cache with checkpoint-prefix reuse.

The serving-layer analogue of prefix caching in an inference stack
(SEMANTICS.md "Cache soundness"): heatd already proves byte-identical
spec identity — the ensemble pack key compares canonical config JSON —
and the bitwise contracts PRs 1–12 pinned (same semantic spec -> same
trajectory, bit for bit, and resume-from-checkpoint == uninterrupted)
make that identity a sound *memo key*. This module promotes it:

- the **cache key** is derived from the SEMANTIC half of the spec
  alone, via the same ``config.SEMANTIC_FIELDS`` partition heatlint
  HL101 audits: observation-only fields (guard/diag/pipeline) are
  dropped before hashing, so enabling an observer can never fork a
  cache entry — and an *unclassified* new ``HeatConfig`` field makes
  key derivation raise, exactly the way it fails HL101, instead of
  silently keying on (or silently ignoring) an unaudited field;
- an **exact hit** serves a completed, finite-verified result in O(1):
  the entry's payload is the donor run's final committed checkpoint
  generation, hardlinked into the new job's own lineage, so the served
  job is indistinguishable on disk from one that ran;
- a **prefix hit** seeds the new job's checkpoint stem with the
  newest admissible donor generation; the worker's ordinary
  resume-before-run path does the rest, and the grids are bitwise a
  from-scratch solve by the PR-2/PR-10 resume-parity contract;
- the **index** is an append-only fsynced journal
  (``<root>/cache/index.jsonl``) folded by the pure reducer
  :func:`reduce_cache_journal` — same discipline as the job journal:
  torn tails invisible, state always derivable after a daemon SIGKILL,
  fold law ``reduce(prefix) then reduce(suffix)`` == ``reduce(all)``.
  Payload directories are rename-committed BEFORE their index line, so
  a crash between the two leaves an unreferenced payload (garbage,
  swept later), never an entry naming torn bytes;
- **eviction** is LRU under a byte/entry budget
  (``heatd serve --cache-max-bytes``), with in-flight prefix donors
  pinned; the evict line lands before the payload is deleted, so a
  crash mid-eviction leaves an orphan payload, never a dangling entry.

Admissibility (the soundness core — every rule is justified by a
bitwise contract an earlier PR pinned, see SEMANTICS.md):

==========  =================  =======================================
target      donor entry        rule
==========  =================  =======================================
any         other scheme       NEVER: cross-scheme reuse (explicit
                               donor -> implicit target or vice
                               versa) is inadmissible — the schemes
                               compute different trajectories, so
                               ``scheme`` (and the mg_* solver
                               fields) sit in the base key, and the
                               lookups ALSO re-check the donor's
                               recorded scheme (defense in depth
                               against a base-key collision; pinned
                               by tests/test_cache.py).
fixed       any                exact: identical semantic key.
fixed       any                prefix: same base key (semantics minus
                               stepping), any generation ``k < steps``
                               — fixed/converge trajectories are the
                               same stepping, a generation at ``k`` is
                               the scratch state at ``k``.
converge    converge, same     exact: identical key; or *converged
            eps + cadence      dominance* — the donor CONVERGED at
                               ``m <= target.steps``: the scratch
                               target converges at the same window
                               with the same grid.
converge    converge, same     prefix: donor exhausted its budget
            eps + cadence      WITHOUT converging — every verdict up
                               to ``steps_done`` was negative, so
                               resuming at a window boundary
                               ``k <= steps_done`` skips only verdicts
                               known negative.
converge    fixed              prefix ONLY with non-convergence
                               evidence: some converge entry (same
                               base/eps/cadence) proves no verdict
                               fires through ``k`` (ran past ``k``
                               unconverged, or converged strictly
                               later). Without evidence the scratch
                               run might have stopped before ``k`` —
                               resuming would skip a real verdict and
                               break the bitwise contract, so the
                               lookup declines.
==========  =================  =======================================

Everything here is jax-free (numpy only, for the finite check): the
daemon admits, serves and evicts without initializing an accelerator
backend, same constraint as ``service/admission.py``.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
from typing import Dict, List, Optional, Tuple

from parallel_heat_tpu.config import (
    OBSERVATION_ONLY_FIELDS,
    SEMANTIC_FIELDS,
    HeatConfig,
)
from parallel_heat_tpu.service.store import Journal, read_journal_file
from parallel_heat_tpu.utils.checkpoint import (
    _fsync_replace,
    generation_paths,
    link_snapshot,
)

CACHE_SCHEMA_VERSION = 1

# The stepping half of the semantic partition: fields that size the
# run, not the per-step trajectory. They stay in the EXACT key (a
# 100-step result is not a 200-step result) but are excluded from the
# BASE key, which names the trajectory family prefix reuse ranges
# over. Every other semantic field must match exactly for any reuse —
# backend/mesh/halo schedule ARE pinned bitwise-identical by tests,
# but the cache deliberately refuses to rely on cross-variant parity:
# one proven contract (resume parity on the SAME spec) is load-bearing
# here, not all of them.
STEPPING_FIELDS = ("steps", "converge", "eps", "check_interval")

# The seed marker the daemon drops next to a prefix-seeded generation
# so the worker can journal its provenance into telemetry
# (``cache_prefix_resume``). Dot-named: invisible to every discovery
# scan (generation_paths matches ``<base>.g<step>`` names only).
SEED_MARKER = ".cache_seed.json"


class CacheKeyError(ValueError):
    """The spec cannot be content-addressed — an unclassified config
    field (the HL101 failure, surfaced at the serving layer) or an
    unknown field the solver would reject anyway."""


def _partition(config_cls=HeatConfig,
               semantic: Optional[Tuple[str, ...]] = None,
               observation: Optional[Tuple[str, ...]] = None):
    """Validate the cache-key partition against the dataclass and
    return ``(semantic_fields_in_order, defaults)``. Raises
    :class:`CacheKeyError` when any field is unclassified or
    double-classified — the exact condition heatlint HL101 fails CI
    on, enforced here independently so a doctored config cannot fork
    cache entries even if lint never ran."""
    semantic = SEMANTIC_FIELDS if semantic is None else semantic
    observation = (OBSERVATION_ONLY_FIELDS if observation is None
                   else observation)
    fields = dataclasses.fields(config_cls)
    names = [f.name for f in fields]
    unclassified = [n for n in names
                    if n not in semantic and n not in observation]
    double = [n for n in names if n in semantic and n in observation]
    if unclassified or double:
        raise CacheKeyError(
            f"cache-key partition incomplete for "
            f"{config_cls.__name__}: unclassified={unclassified} "
            f"double-classified={double} — every config field must "
            f"appear in exactly one of SEMANTIC_FIELDS / "
            f"OBSERVATION_ONLY_FIELDS (heatlint HL101; an unaudited "
            f"field must not be able to fork or alias cache entries)")
    defaults = {f.name: f.default for f in fields}
    return [n for n in names if n in semantic], defaults


def canonical_semantic_config(config: dict, config_cls=HeatConfig,
                              **partition_kw) -> dict:
    """The canonical content of one spec: semantic fields only,
    defaults applied, JSON-normalized (tuples -> lists). Unknown keys
    raise — a spec the solver cannot materialize has no sound key."""
    sem, defaults = _partition(config_cls, **partition_kw)
    known = set(defaults)
    unknown = [k for k in config if k not in known]
    if unknown:
        raise CacheKeyError(
            f"unknown config field(s) {unknown} — not a "
            f"{config_cls.__name__} spec, nothing sound to key on")
    out = {}
    for name in sem:
        v = config.get(name, defaults[name])
        if isinstance(v, tuple):
            v = list(v)
        out[name] = v
    return out


def _digest(doc: dict) -> str:
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:40]


def cache_key(config: dict, config_cls=HeatConfig,
              **partition_kw) -> Tuple[str, dict]:
    """``(exact_key, canonical_semantic_dict)`` for one spec config.
    The key is a content address: byte-identical canonical semantics
    <=> equal keys, and observation-only fields cannot move it."""
    canon = canonical_semantic_config(config, config_cls,
                                      **partition_kw)
    return _digest(canon), canon


def base_key(config: dict, config_cls=HeatConfig,
             **partition_kw) -> str:
    """The trajectory-family key: semantics minus the stepping fields
    (:data:`STEPPING_FIELDS`). Two specs share a base key iff their
    per-step update programs compute the same trajectory — the set
    prefix reuse ranges over."""
    canon = canonical_semantic_config(config, config_cls,
                                      **partition_kw)
    for f in STEPPING_FIELDS:
        canon.pop(f, None)
    return _digest(canon)


# ---------------------------------------------------------------------------
# Index journal + pure fold
# ---------------------------------------------------------------------------

def reduce_cache_journal(events, state=None
                         ) -> Tuple[Dict[str, dict], List[str]]:
    """Pure fold of cache-index events -> ``(entries, anomalies)``.

    Entry lifecycle: ``cache_put`` creates/replaces, ``cache_touch``
    bumps the LRU clock (+ hit counters), ``cache_evict`` removes.
    Same fold law as ``store.reduce_journal``: pass a previous call's
    state to fold only appended events. Unknown events/fields are
    ignored (forward compatibility); a touch/evict of an unknown key
    is an anomaly — the index's own double-terminal analogue."""
    entries: Dict[str, dict] = state[0] if state else {}
    anomalies: List[str] = state[1] if state else []
    for e in events:
        ev = e.get("event")
        key = e.get("key")
        if ev is None or not isinstance(key, str):
            continue
        if ev == "cache_put":
            prior = entries.get(key)
            entries[key] = {
                "key": key,
                "base": e.get("base"),
                # Donor provenance for the cross-scheme decline; None
                # on pre-scheme index lines, which were by
                # construction explicit-scheme runs.
                "scheme": e.get("scheme"),
                "job_id": e.get("job_id"),
                "attempt": e.get("attempt"),
                "steps": e.get("steps"),
                "converge": bool(e.get("converge")),
                "eps": e.get("eps"),
                "check_interval": e.get("check_interval"),
                "steps_done": e.get("steps_done"),
                "converged": e.get("converged"),
                "generations": list(e.get("generations") or []),
                "bytes": int(e.get("bytes") or 0),
                "payload": e.get("payload"),
                "put_t": e.get("t_wall"),
                "last_used_t": e.get("t_wall"),
                "hits": 0,
                "prefix_hits": 0,
            }
            if prior is not None:
                # Re-put of a live key (two twins dispatched before
                # either completed): same content address, same
                # bytes — the entry's USAGE history must survive, or
                # a hot entry would lose its LRU recency and be
                # evicted ahead of genuinely cold ones.
                v = entries[key]
                v["hits"] = prior.get("hits") or 0
                v["prefix_hits"] = prior.get("prefix_hits") or 0
                pt = prior.get("last_used_t")
                if isinstance(pt, (int, float)):
                    v["last_used_t"] = max(pt, v["last_used_t"]
                                           or pt)
        elif ev == "cache_touch":
            v = entries.get(key)
            if v is None:
                anomalies.append(f"cache: touch of unknown entry {key}")
                continue
            t = e.get("t_wall")
            if isinstance(t, (int, float)):
                v["last_used_t"] = t
            if e.get("kind") == "prefix":
                v["prefix_hits"] += 1
            else:
                v["hits"] += 1
        elif ev == "cache_evict":
            if entries.pop(key, None) is None:
                anomalies.append(f"cache: evict of unknown entry {key}")
    return entries, anomalies


# ---------------------------------------------------------------------------
# Lookup (pure functions over the folded entries)
# ---------------------------------------------------------------------------

def _stepping(canon: dict) -> Tuple[int, bool, float, int]:
    return (int(canon.get("steps") or 0), bool(canon.get("converge")),
            float(canon.get("eps") or 0.0),
            int(canon.get("check_interval") or 1))


def _cadence_match(entry: dict, eps: float, ci: int) -> bool:
    return (bool(entry.get("converge"))
            and entry.get("eps") == eps
            and entry.get("check_interval") == ci)


def _scheme_match(entry: dict, canon: dict) -> bool:
    """The cross-scheme decline (see the admissibility table): a donor
    whose recorded time integrator differs from the target's serves
    NOTHING — not exact, not prefix. Structurally the base/exact keys
    already separate schemes (``scheme`` is a non-stepping semantic
    field), so this re-check is defense in depth: a colliding or
    hand-edited index line still cannot cross the scheme boundary.
    Entries from before the scheme field existed (recorded None) were
    explicit-scheme runs by construction."""
    return ((entry.get("scheme") or "explicit")
            == (canon.get("scheme") or "explicit"))


def lookup_exact(entries: Dict[str, dict], config: dict
                 ) -> Optional[Tuple[dict, str]]:
    """``(entry, kind)`` for an O(1) serve, or None. ``kind`` is
    ``"exact"`` (identical semantic key) or ``"converged"`` (converged
    dominance: a converge donor with the same eps/cadence that
    CONVERGED within this target's budget — the scratch run would stop
    at the same window with the same grid)."""
    try:
        key, canon = cache_key(config)
    except CacheKeyError:
        return None
    e = entries.get(key)
    if e is not None and _scheme_match(e, canon) \
            and e.get("steps_done") in (e.get("generations") or []):
        return e, "exact"
    steps, converge, eps, ci = _stepping(canon)
    if not converge:
        return None
    base = base_key(config)
    best = None
    for e in entries.values():
        if e.get("base") != base or not _cadence_match(e, eps, ci) \
                or not _scheme_match(e, canon):
            continue
        m = e.get("steps_done")
        if (e.get("converged") is True and isinstance(m, int)
                and m <= steps and m in (e.get("generations") or [])):
            if best is None or m < best.get("steps_done"):
                best = e
    return (best, "converged") if best is not None else None


def lookup_prefix(entries: Dict[str, dict], config: dict
                  ) -> Optional[Tuple[dict, int]]:
    """``(entry, generation_step)`` naming the newest admissible donor
    generation for a prefix resume, or None. See the module-docstring
    admissibility table — each arm cites the bitwise contract that
    makes it sound."""
    try:
        canon = canonical_semantic_config(config)
        base = base_key(config)
    except CacheKeyError:
        return None
    steps, converge, eps, ci = _stepping(canon)

    def gens(e, bound, align=None):
        return [g for g in e.get("generations") or []
                if isinstance(g, int) and 0 < g < bound
                and (align is None or g % align == 0)]

    # Non-convergence evidence for fixed donors under a converge
    # target: the largest step through which SOME converge entry of
    # this family (same eps/cadence) proves every verdict negative.
    evidence_through = -1
    if converge:
        for e in entries.values():
            if e.get("base") != base or not _cadence_match(e, eps, ci) \
                    or not _scheme_match(e, canon):
                continue
            m = e.get("steps_done")
            if not isinstance(m, int):
                continue
            if e.get("converged") is False:
                evidence_through = max(evidence_through, m)
            elif e.get("converged") is True:
                # Converged at m: no verdict fired strictly before m.
                evidence_through = max(evidence_through, m - 1)

    best: Optional[Tuple[dict, int]] = None
    for e in entries.values():
        if e.get("base") != base or not _scheme_match(e, canon):
            continue
        if not converge:
            # Fixed target: any family member's generations are the
            # scratch trajectory at that step (fixed/converge share
            # the stepping; convergence only decides when to STOP, and
            # a retained generation is by construction from before the
            # donor stopped).
            cand = gens(e, steps)
        elif _cadence_match(e, eps, ci):
            if e.get("converged") is False:
                # Budget-exhausted converge donor: verdicts through
                # steps_done all negative; resume at a window boundary.
                bound = min(steps, int(e.get("steps_done") or 0) + 1)
                cand = gens(e, bound, align=ci)
            else:
                # Converged donors serve via lookup_exact (dominance)
                # or, for a SMALLER target budget, not at all — the
                # scratch run would stop inside the donor's verdict
                # sequence, nothing to resume past.
                cand = []
        elif not e.get("converge"):
            # Fixed donor under a converge target: sound only through
            # the family's proven-unconverged horizon.
            cand = gens(e, min(steps, evidence_through + 1), align=ci)
        else:
            cand = []  # converge donor with different eps/cadence
        for g in cand:
            if best is None or g > best[1]:
                best = (e, g)
    return best


# ---------------------------------------------------------------------------
# Payload capture / seeding (rename-committed hardlinks)
# ---------------------------------------------------------------------------

def payload_stem(payload_dir: str) -> str:
    """The checkpoint stem inside one payload directory — payloads
    reuse the generation naming (``ck.g<step>.npz``) so
    ``generation_paths``/``latest_checkpoint`` read them natively."""
    return os.path.join(payload_dir, "ck")


def _npz_finite(path: str) -> bool:
    """Host-side finite verification of one gathered generation —
    numpy only (jax-free daemon). False on unreadable/foreign files:
    admission to the cache must err toward refusing."""
    import numpy as np

    try:
        with np.load(path) as z:
            return bool(np.isfinite(np.asarray(z["grid"])).all())
    except Exception:  # noqa: BLE001 — any unreadable payload refuses
        return False


def capture_payload(cache_dir: str, key: str, donor_stem: str,
                    steps_done: int) -> Optional[Tuple[str, list, int]]:
    """Rename-commit the donor lineage's gathered generations as the
    payload of ``key``; returns ``(payload_dir, generation_steps,
    bytes)`` or None when the lineage is not cacheable (no committed
    generations, a sharded ``.ckpt`` layout, a final generation that
    is missing or fails the host finite check).

    Only ``.npz`` (gathered) generations are captured: their finite
    verification is one numpy read here, and linking them is O(1).
    Sharded ``.ckpt`` lineages decline — multi-host results resume
    through their own two-phase-committed families, and caching them
    is a follow-on, not a silent half-support.

    The temp directory is dot-named (invisible to any scan) and the
    final ``os.rename`` is the commit: a SIGKILL at any point leaves
    either no payload or a complete one — and the index line that
    makes it LIVE is appended by the caller only after this returns.
    """
    gens = generation_paths(donor_stem)
    npz = [(s, p) for s, p in gens if p.endswith(".npz")]
    if not npz or len(npz) != len(gens):
        return None  # empty or sharded lineage: decline
    if npz[-1][0] != int(steps_done):
        return None  # newest generation is not the committed result
    if not _npz_finite(npz[-1][1]):
        return None  # never admit a non-finite (or torn) result
    dst = os.path.join(cache_dir, key)
    if os.path.isdir(dst):
        # Re-put of the same content address: the existing payload is
        # interchangeable bytes (same key => same trajectory). Reuse
        # it when its newest generation matches; replace otherwise.
        have = generation_paths(payload_stem(dst))
        if have and have[-1][0] == int(steps_done):
            steps = [s for s, _ in have]
            size = sum(os.path.getsize(p) for _, p in have)
            return dst, steps, size
        shutil.rmtree(dst, ignore_errors=True)
    os.makedirs(cache_dir, exist_ok=True)
    tmp = os.path.join(cache_dir, f".tmp-{os.getpid()}-{key}")
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    steps, size = [], 0
    for s, p in npz:
        name = f"ck.g{int(s):012d}.npz"
        link_snapshot(p, os.path.join(tmp, name))
        steps.append(int(s))
        size += os.path.getsize(os.path.join(tmp, name))
    os.rename(tmp, dst)
    return dst, steps, size


def seed_stem(entry: dict, gen_step: int, dst_stem: str,
              marker: Optional[dict] = None) -> Optional[str]:
    """Link one payload generation into a job's own checkpoint stem
    (the prefix seed / exact-hit lineage link); returns the seeded
    path or None when the payload went missing (evicted/garbage —
    the caller just solves from scratch). ``marker`` (rename-committed
    ``.cache_seed.json`` next to the generation) lets the worker
    journal the provenance into its telemetry stream."""
    src = os.path.join(str(entry.get("payload") or ""),
                       f"ck.g{int(gen_step):012d}.npz")
    if not os.path.isfile(src):
        return None
    d = os.path.dirname(os.path.abspath(dst_stem))
    os.makedirs(d, exist_ok=True)
    dst = f"{dst_stem}.g{int(gen_step):012d}.npz"
    try:
        link_snapshot(src, dst)
    except OSError:
        return None
    if marker is not None:
        tmp = os.path.join(d, f".tmp-{os.getpid()}-seed")
        with open(tmp, "w") as f:
            json.dump(marker, f)
        _fsync_replace(tmp, os.path.join(d, SEED_MARKER))
    return dst


def read_seed_marker(stem: str) -> Optional[dict]:
    """The committed seed marker of one checkpoint stem, or None."""
    path = os.path.join(os.path.dirname(os.path.abspath(stem)),
                        SEED_MARKER)
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Eviction (pure policy; the caller applies the verdicts)
# ---------------------------------------------------------------------------

def evict_candidates(entries: Dict[str, dict],
                     max_bytes: Optional[int],
                     max_entries: Optional[int],
                     pinned=()) -> List[str]:
    """Keys to evict, oldest-used first, until both budgets hold.
    Pinned keys (in-flight prefix donors) are never returned — a
    budget that only pinned entries could satisfy stays over-budget
    until the pins release, which the caller re-checks each pass."""
    pinned = set(pinned)
    live = [e for e in entries.values() if e["key"] not in pinned]
    live.sort(key=lambda e: (e.get("last_used_t") or 0.0, e["key"]))
    total = sum(e.get("bytes") or 0 for e in entries.values())
    count = len(entries)
    out = []
    for e in live:
        over_bytes = max_bytes is not None and total > max_bytes
        over_count = max_entries is not None and count > max_entries
        if not over_bytes and not over_count:
            break
        out.append(e["key"])
        total -= e.get("bytes") or 0
        count -= 1
    return out


# ---------------------------------------------------------------------------
# CacheIndex: the daemon's handle (journal writer + incremental fold)
# ---------------------------------------------------------------------------

class CacheIndex:
    """One queue root's cache: the index journal writer plus an
    incremental fold of it (same offset discipline as the daemon's
    job-journal fold — only whole lines are consumed, so a read racing
    an append re-reads the torn tail complete next pass). All writes
    go through this class so the commit ordering (payload before
    index line; evict line before payload delete) has one home."""

    def __init__(self, root: str):
        self.root = str(root)
        self.dir = os.path.join(self.root, "cache")
        os.makedirs(self.dir, exist_ok=True)
        self.index_path = os.path.join(self.dir, "index.jsonl")
        self._journal: Optional[Journal] = None
        self._offset = 0
        self._entries: Dict[str, dict] = {}
        self._anomalies: List[str] = []

    @property
    def journal(self) -> Journal:
        if self._journal is None:
            self._journal = Journal(self.index_path)
        return self._journal

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    @property
    def version(self) -> int:
        """Monotone content version: the byte offset the fold has
        consumed. Changes exactly when the index gains lines — the
        daemon's per-tick miss memo keys on it (a job that missed at
        version V misses at V forever)."""
        return self._offset

    def entries(self) -> Dict[str, dict]:
        """The folded index, O(appended bytes) per call."""
        try:
            with open(self.index_path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
        except OSError:
            return self._entries
        end = data.rfind(b"\n")
        if end >= 0:
            self._offset += end + 1
            events = []
            for line in data[:end + 1].split(b"\n"):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "event" in rec:
                    events.append(rec)
            reduce_cache_journal(events,
                                 state=(self._entries, self._anomalies))
        return self._entries

    # -- writes ----------------------------------------------------------

    def put(self, config: dict, donor_stem: str, *, job_id: str,
            attempt: int, steps_done: int,
            converged: Optional[bool] = None) -> Optional[dict]:
        """Admit one completed, finite-verified lineage; returns the
        entry or None when the lineage declines (see
        :func:`capture_payload`). Payload commit strictly precedes the
        index line — the crash window between them loses the ENTRY
        (re-solved next time), never serves torn bytes."""
        try:
            key, canon = cache_key(config)
            base = base_key(config)
        except CacheKeyError:
            return None
        cap = capture_payload(self.dir, key, donor_stem,
                              int(steps_done))
        if cap is None:
            return None
        payload, gens, size = cap
        rec = self.journal.append(
            "cache_put", key=key, base=base, job_id=job_id,
            attempt=int(attempt), steps=canon.get("steps"),
            converge=bool(canon.get("converge")),
            eps=canon.get("eps"),
            check_interval=canon.get("check_interval"),
            scheme=canon.get("scheme"),
            steps_done=int(steps_done), converged=converged,
            generations=gens, bytes=size, payload=payload)
        self._consume([rec])
        return self._entries.get(key)

    def touch(self, key: str, kind: str = "exact") -> None:
        rec = self.journal.append("cache_touch", key=key, kind=kind)
        self._consume([rec])

    def evict(self, key: str) -> None:
        """Evict-line first, THEN delete the payload: a crash between
        the two leaves an orphan payload directory (swept by
        :meth:`sweep_orphans`), never a live entry naming missing
        bytes."""
        e = self._entries.get(key)
        rec = self.journal.append("cache_evict", key=key,
                                  bytes=(e or {}).get("bytes"))
        self._consume([rec])
        payload = (e or {}).get("payload")
        if payload and os.path.isdir(payload):
            shutil.rmtree(payload, ignore_errors=True)

    def sweep_orphans(self) -> int:
        """Remove payload directories no live entry references —
        the residue of crashes inside the two commit windows above.
        Returns the number removed. Safe to reap dead writers' temps
        too: one daemon per queue root means the only writer is the
        caller, so any temp directory present here is a corpse's."""
        live = {os.path.basename(str(e.get("payload") or ""))
                for e in self.entries().values()}
        n = 0
        try:
            names = os.listdir(self.dir)
        except OSError:
            return 0
        for name in names:
            full = os.path.join(self.dir, name)
            if not os.path.isdir(full) or name in live:
                continue
            shutil.rmtree(full, ignore_errors=True)
            n += 1
        return n

    def _consume(self, recs) -> None:
        """Fold freshly-appended records by hand and advance the
        offset past them (the append landed at the tail; the next
        :meth:`entries` read must not double-fold)."""
        try:
            self._offset = os.path.getsize(self.index_path)
        except OSError:
            pass
        reduce_cache_journal(recs,
                             state=(self._entries, self._anomalies))


# ---------------------------------------------------------------------------
# Durability audit (tools/heatq.py --check)
# ---------------------------------------------------------------------------

def load_cache_index(root: str) -> Tuple[Dict[str, dict], List[str],
                                         int, bool]:
    """Cold read of one root's cache index ->
    ``(entries, anomalies, bad_lines, torn_tail)``."""
    path = os.path.join(str(root), "cache", "index.jsonl")
    events, bad, torn = read_journal_file(path)
    entries, anomalies = reduce_cache_journal(events)
    return entries, anomalies, bad, torn


def audit_cache(root: str, entries: Dict[str, dict],
                job_views: Optional[dict] = None) -> List[str]:
    """Durability anomalies of one cache index (heatq ``--check``):

    - **dangling entry**: a live entry whose payload directory or
      named generation files are missing — the serve path would fail,
      and the commit ordering should have made this impossible;
    - **entry naming an uncommitted result**: the donor's result
      record is missing or not ``completed`` — only committed,
      completed lineages are admissible (a quarantined/rolled-back
      lineage must never enter, and a completed job's terminal state
      is absorbing, so a later quarantine cannot exist either).
    """
    out: List[str] = []
    for key, e in sorted(entries.items()):
        payload = str(e.get("payload") or "")
        if not os.path.isdir(payload):
            out.append(f"cache entry {key}: dangling — payload "
                       f"directory missing ({payload})")
            continue
        for g in e.get("generations") or []:
            p = os.path.join(payload, f"ck.g{int(g):012d}.npz")
            if not os.path.isfile(p):
                out.append(f"cache entry {key}: dangling — named "
                           f"generation {g} missing from payload")
        jid, att = e.get("job_id"), e.get("attempt")
        rec_path = os.path.join(str(root), "results",
                                f"{jid}.a{int(att or 0):04d}.json")
        rec = None
        try:
            with open(rec_path) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            rec = None
        if not isinstance(rec, dict) or rec.get("outcome") != "completed":
            out.append(f"cache entry {key}: names an uncommitted "
                       f"result ({jid} attempt {att}: "
                       f"{'missing record' if rec is None else rec.get('outcome')})")
        elif job_views is not None and jid in job_views \
                and getattr(job_views[jid], "state", None) not in (
                    "completed", None):
            out.append(f"cache entry {key}: donor {jid} lineage is "
                       f"{job_views[jid].state!r} in the journal — "
                       f"not an admissible completed lineage")
    return out
