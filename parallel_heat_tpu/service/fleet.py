"""Fleet federation: many heatds, one durable service.

One shared **fleet root** holds N queue *partitions*, each a complete
single-daemon queue root (its own ``journal.jsonl``, spool, job
records, checkpoints, cache index). Hosts — :class:`FleetHost`
processes, each wrapping one ordinary :class:`Heatd` per partition it
owns — coordinate through exactly two kinds of rename-committed files,
never through the journals:

- **Lease files** (``leases/<partition>.json``): a host may write a
  partition's journal iff it holds the partition's lease. Claims are
  *link-committed* (``os.link`` of a private temp onto the lease path
  — EEXIST means somebody else won); takeovers of a stale lease are
  *rename-committed* (exactly one of the racing hosts succeeds in
  renaming the old lease file away; the loser's rename raises ENOENT).
  The holder re-writes the lease at ``lease_renew_s`` cadence; a lease
  older than its recorded ``timeout_s`` is stale and reclaimable.
  This keeps every partition journal SINGLE-WRITER, so the pure-fold
  discipline of :func:`~parallel_heat_tpu.service.store.reduce_journal`
  — and every durability proof built on it — is untouched by
  federation.
- **Host records** (``hosts/<host>.json``): each host's journaled
  capacity/liveness record (platform, ``max_cells``, slots, held
  leases, adoption/steal counters). The router reads these for
  heterogeneous admission — a CPU host absorbs small grids while big
  meshes go to hosts whose declared capacity fits them.

**Cross-host orphan takeover** (the federated half of "no accepted job
is ever silently lost"): a host whose lease heartbeat goes stale has
its leases reclaimed by a peer, which journals ``host_lost`` plus one
``adopted`` line per in-flight job and then just *steps* the partition
— the single-host reconcile/orphan/requeue machinery re-dispatches
each adopted job, the worker's resume-before-run picks up the newest
committed checkpoint generation, and the completed grid is bitwise an
uninterrupted run's (the PR-2/PR-10/PR-13 resume-parity contracts;
re-certified across hosts by the ``fleet_host_sigkill`` chaos cell).

**Work stealing**: an idle host claims the oldest unleased partition
with backlog (spooled or queued jobs) — journaled as a
``lease_claimed`` line with ``kind="steal"``.

**Cache-aware routing** (:func:`route_submission`): the router folds
every partition's ``cache/index.jsonl`` and scores it with the same
pure admissibility functions the daemon serves from —
:func:`~parallel_heat_tpu.service.cache.lookup_exact` first (an exact
peer hit routes to the donor's partition, where admission serves the
verdict with ZERO dispatches fleet-wide), then
:func:`~parallel_heat_tpu.service.cache.lookup_prefix` (the submission
goes to the host holding the longest admissible checkpoint prefix for
its key), then capacity-filtered least-loaded placement. The decision
rides the spool record (``JobSpec.route``) so the journal's
``accepted`` line carries the routing provenance metrics_report and
slo_gate gate on.

``tools/heatq.py --check`` audits the federated invariants
(:func:`audit_fleet`): stale-lease inventory, cross-host double-claim
/ double-dispatch detection, and adopted-job lineage.
"""

from __future__ import annotations

import json
import os
import signal
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from parallel_heat_tpu.service.cache import (
    load_cache_index,
    lookup_exact,
    lookup_prefix,
)
from parallel_heat_tpu.service.daemon import Heatd, HeatdConfig
from parallel_heat_tpu.service.store import (
    JobStore,
    read_journal_file,
    reduce_journal,
)
from parallel_heat_tpu.supervisor import EXIT_PREEMPTED

FLEET_MARKER = "fleet.json"
FLEET_SCHEMA_VERSION = 1
# Default staleness threshold: several renew cadences, same rationale
# as worker heartbeats — one missed renewal is scheduling noise.
DEFAULT_LEASE_TIMEOUT_S = 10.0


class FleetError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# Fleet root layout
# ---------------------------------------------------------------------------

def fleet_marker_path(root) -> str:
    return os.path.join(str(root), FLEET_MARKER)


def is_fleet_root(root) -> bool:
    """A directory is a federated root iff it carries the rename-
    committed ``fleet.json`` marker (heatq/metrics/slo_gate dispatch
    on this — a plain queue root keeps its single-daemon view)."""
    return os.path.isfile(fleet_marker_path(root))


def fleet_init(root, partitions: int = 2,
               lease_timeout_s: float = DEFAULT_LEASE_TIMEOUT_S,
               clock: Callable[[], float] = time.time) -> dict:
    """Create (or re-open) a fleet root: ``parts/p00..`` queue
    partitions + the ``leases/`` and ``hosts/`` coordination dirs +
    the ``fleet.json`` marker (rename-committed last — a crash mid-init
    leaves directories no reader mistakes for a fleet)."""
    if partitions < 1:
        raise ValueError(f"partitions must be >= 1, got {partitions}")
    if lease_timeout_s <= 0:
        raise ValueError(f"lease_timeout_s must be > 0, got "
                         f"{lease_timeout_s}")
    root = str(root)
    existing = fleet_doc(root) if is_fleet_root(root) else None
    if existing is not None:
        # Idempotent re-init: partition count can only grow (jobs may
        # already live in the existing partitions).
        partitions = max(partitions, int(existing.get("partitions", 0)))
    os.makedirs(os.path.join(root, "leases"), exist_ok=True)
    os.makedirs(os.path.join(root, "hosts"), exist_ok=True)
    for i in range(partitions):
        JobStore(os.path.join(root, "parts", f"p{i:02d}")).close()
    doc = {"schema": FLEET_SCHEMA_VERSION, "partitions": partitions,
           "lease_timeout_s": float(lease_timeout_s),
           "created_t": (existing or {}).get("created_t", clock())}
    _write_json_atomic(fleet_marker_path(root), doc)
    return doc


def fleet_doc(root) -> dict:
    doc = _read_json(fleet_marker_path(root))
    if not isinstance(doc, dict):
        raise FleetError(f"{root}: not a fleet root (no readable "
                         f"{FLEET_MARKER} — run `heatd fleet-init`)")
    return doc


def partition_roots(root) -> List[Tuple[str, str]]:
    """Sorted ``(name, path)`` of every partition under the root —
    discovery by directory scan so a grown fleet needs no marker
    rewrite to be visible."""
    parts_dir = os.path.join(str(root), "parts")
    try:
        names = sorted(n for n in os.listdir(parts_dir)
                       if not n.startswith(".")
                       and os.path.isdir(os.path.join(parts_dir, n)))
    except OSError:
        names = []
    return [(n, os.path.join(parts_dir, n)) for n in names]


def partition_root(root, name: str) -> str:
    return os.path.join(str(root), "parts", name)


def _write_json_atomic(path: str, doc: dict) -> str:
    """Rename-commit (the checkpoint protocol's discipline): dotted
    temp name no discovery scan matches, fsync, atomic replace."""
    d, base = os.path.split(path)
    tmp = os.path.join(d, f".{base}.tmp.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Lease files: link-committed claims, rename-committed takeovers
# ---------------------------------------------------------------------------

def lease_path(root, part: str) -> str:
    return os.path.join(str(root), "leases", f"{part}.json")


def read_lease(root, part: str) -> Optional[dict]:
    return _read_json(lease_path(root, part))


def list_leases(root) -> Dict[str, dict]:
    """``partition -> lease doc`` for every committed lease file
    (dotted temp/steal residue is invisible by construction)."""
    d = os.path.join(str(root), "leases")
    out: Dict[str, dict] = {}
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for n in sorted(names):
        if n.startswith(".") or not n.endswith(".json"):
            continue
        doc = _read_json(os.path.join(d, n))
        if isinstance(doc, dict):
            out[n[:-len(".json")]] = doc
    return out


def lease_stale(doc: dict, now: float) -> bool:
    """Stale = the holder missed its renewals past the lease's own
    recorded timeout (each lease declares its threshold, so auditors
    and thieves judge by the holder's contract, not their own)."""
    t = doc.get("t_wall")
    timeout = doc.get("timeout_s") or DEFAULT_LEASE_TIMEOUT_S
    return not isinstance(t, (int, float)) or now - t > timeout


def _lease_doc(part: str, host: str, epoch: int, timeout_s: float,
               now: float, pid: Optional[int]) -> dict:
    return {"partition": part, "host": host, "epoch": int(epoch),
            "t_wall": now, "timeout_s": float(timeout_s),
            "pid": pid if pid is not None else os.getpid()}


def _link_commit(root, part: str, doc: dict) -> bool:
    """Create-if-absent commit: write a private temp, ``os.link`` it
    onto the lease path. EEXIST = a racer won; any outcome but a clean
    link is a loss. The temp is always unlinked."""
    dst = lease_path(root, part)
    d = os.path.dirname(dst)
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(
        d, f".{part}.claim.{doc['host']}.{os.getpid()}")
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    try:
        os.link(tmp, dst)
        return True
    except OSError:
        return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def claim_lease(root, part: str, host: str, *, epoch: int,
                timeout_s: float, now: Optional[float] = None,
                pid: Optional[int] = None) -> Optional[dict]:
    """Claim an UNLEASED partition. Returns the committed lease doc,
    or None when another host's link landed first (exactly one
    claimant ever wins — the link is the commit point)."""
    now = time.time() if now is None else now
    doc = _lease_doc(part, host, epoch, timeout_s, now, pid)
    return doc if _link_commit(root, part, doc) else None


def steal_lease(root, part: str, observed: dict, host: str, *,
                timeout_s: float, now: Optional[float] = None,
                pid: Optional[int] = None) -> Optional[dict]:
    """Take over a STALE lease. The commit point is renaming the old
    lease file to a thief-private dotted name: of N hosts that judged
    the same lease stale, exactly one rename succeeds (the others get
    ENOENT) — zero double-claims by construction, which is what the
    ``fleet_lease_race`` chaos cell certifies. The winner then
    link-commits its own lease at ``observed["epoch"] + 1``.

    If the stolen bytes show the holder renewed between our staleness
    read and the rename (a near-miss on a live host), the steal is
    rolled back: the file is restored by link (or abandoned to the
    holder's next renewal-failure if a third host claimed meanwhile)
    and None is returned."""
    now = time.time() if now is None else now
    src = lease_path(root, part)
    stale = os.path.join(
        os.path.dirname(src),
        f".{part}.stale.e{int(observed.get('epoch') or 0)}.{host}."
        f"{os.getpid()}")
    try:
        os.rename(src, stale)
    except OSError:
        return None  # another thief won the rename (or holder released)
    try:
        stolen = _read_json(stale)
        if (isinstance(stolen, dict)
                and stolen.get("t_wall") != observed.get("t_wall")
                and not lease_stale(stolen, now)):
            # The holder renewed under our feet: not actually dead.
            # Put the live lease back (best effort — see docstring).
            try:
                os.link(stale, src)
            except OSError:
                pass
            return None
        epoch = int(observed.get("epoch") or 0) + 1
        doc = _lease_doc(part, host, epoch, timeout_s, now, pid)
        if _link_commit(root, part, doc):
            return doc
        return None
    finally:
        try:
            os.unlink(stale)
        except OSError:
            pass


def renew_lease(root, part: str, host: str, epoch: int,
                now: Optional[float] = None) -> Optional[dict]:
    """Heartbeat-renew a held lease. Returns the fresh doc, or None
    when the lease is no longer ours (vanished, different host, or a
    different epoch) — the holder must then STOP writing the
    partition's journal and abandon its daemon immediately; a peer
    owns it now."""
    now = time.time() if now is None else now
    cur = read_lease(root, part)
    if not isinstance(cur, dict) or cur.get("host") != host \
            or int(cur.get("epoch") or -1) != int(epoch):
        return None
    doc = dict(cur)
    doc["t_wall"] = now
    _write_json_atomic(lease_path(root, part), doc)
    return doc


def release_lease(root, part: str, host: str, epoch: int) -> bool:
    """Graceful-drain release: unlink the lease iff still ours."""
    cur = read_lease(root, part)
    if not isinstance(cur, dict) or cur.get("host") != host \
            or int(cur.get("epoch") or -1) != int(epoch):
        return False
    try:
        os.unlink(lease_path(root, part))
    except OSError:
        return False
    return True


def journal_lease_epoch(part_root: str) -> int:
    """Newest lease epoch the partition's journal has ever recorded
    (0 = never claimed). The journal is the durable monotone record —
    a fresh claim after a graceful release (lease file gone) continues
    the epoch chain from here, so the auditor's strictly-increasing
    epoch invariant survives release/re-claim cycles."""
    events, _bad, _torn = read_journal_file(
        os.path.join(part_root, "journal.jsonl"))
    epoch = 0
    for e in events:
        if e.get("event") in ("lease_claimed", "host_lost"):
            try:
                epoch = max(epoch, int(e.get("epoch") or 0))
            except (TypeError, ValueError):
                continue
    return epoch


# ---------------------------------------------------------------------------
# Host capacity records (heterogeneous admission)
# ---------------------------------------------------------------------------

def host_record_path(root, host: str) -> str:
    return os.path.join(str(root), "hosts", f"{host}.json")


def write_host_record(root, doc: dict) -> str:
    d = os.path.join(str(root), "hosts")
    os.makedirs(d, exist_ok=True)
    return _write_json_atomic(
        os.path.join(d, f"{doc['host']}.json"), doc)


def read_host_records(root) -> Dict[str, dict]:
    d = os.path.join(str(root), "hosts")
    out: Dict[str, dict] = {}
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for n in sorted(names):
        if n.startswith(".") or not n.endswith(".json"):
            continue
        doc = _read_json(os.path.join(d, n))
        if isinstance(doc, dict) and doc.get("host"):
            out[doc["host"]] = doc
    return out


def host_record_fresh(doc: dict, now: float) -> bool:
    """A capacity record is believable while younger than its own
    declared ``ttl_s`` (written as several renew cadences) — the same
    self-describing staleness rule lease files use."""
    t = doc.get("t_wall")
    ttl = doc.get("ttl_s") or (4 * DEFAULT_LEASE_TIMEOUT_S)
    return isinstance(t, (int, float)) and now - t <= ttl


def grid_cells(config: dict) -> int:
    """Grid size in cells — the router's capacity currency (matches
    the admission gate's HBM estimate up to the per-cell constant)."""
    try:
        nx = int(config.get("nx") or 0)
        ny = int(config.get("ny") or 0)
        nz = config.get("nz")
        return max(nx, 1) * max(ny, 1) * (int(nz) if nz else 1)
    except (TypeError, ValueError):
        return 0


# ---------------------------------------------------------------------------
# Cache-aware routing
# ---------------------------------------------------------------------------

def _partition_load(part_root: str) -> int:
    """Cheap queue-depth proxy: committed spool entries + the daemon
    status heartbeat's queued/running counts (no journal fold — the
    router must stay O(partitions), not O(history))."""
    load = 0
    try:
        load += sum(1 for n in os.listdir(os.path.join(part_root,
                                                       "spool"))
                    if not n.startswith("."))
    except OSError:
        pass
    status = _read_json(os.path.join(part_root, "heatd.json"))
    if isinstance(status, dict):
        counts = status.get("counts") or {}
        load += int(counts.get("queued") or 0)
        load += int(counts.get("running") or 0)
    return load


def route_submission(fleet_root, config: dict,
                     now: Optional[float] = None) -> dict:
    """Pick the partition one submission should spool into.

    Scoring, in strict priority order (every cache consult is the
    pure admissibility matrix from ``service/cache.py`` over that
    partition's folded ``cache/index.jsonl`` — the router never
    invents its own reuse rule):

    1. ``exact``   — some partition's cache serves this spec outright
       (:func:`lookup_exact`): route there; admission completes it
       with zero dispatches fleet-wide.
    2. ``prefix``  — route to the partition holding the LONGEST
       admissible checkpoint prefix (:func:`lookup_prefix`, max
       generation step wins; ties break to the lower partition name).
    3. ``capacity`` — heterogeneous admission: restrict to partitions
       leased by fresh hosts whose ``max_cells`` fits the grid (when
       that filter actually excludes someone), then least-loaded.
    4. ``load``    — least-loaded partition, ties to the lowest name
       (deterministic: the same fleet state routes the same way).

    Returns ``{"partition", "root", "kind", "host", "donor_key",
    "gen_step"}`` (``host`` = the target partition's current lease
    holder, None when unleased — a spooled submission waits for work
    stealing to pick the partition up)."""
    now = time.time() if now is None else now
    parts = partition_roots(fleet_root)
    if not parts:
        raise FleetError(f"{fleet_root}: no partitions — run "
                         f"`heatd fleet-init`")
    leases = list_leases(fleet_root)

    def holder(name):
        doc = leases.get(name)
        return doc.get("host") if isinstance(doc, dict) \
            and not lease_stale(doc, now) else None

    def decision(name, proot, kind, donor=None, gen=None):
        return {"partition": name, "root": proot, "kind": kind,
                "host": holder(name),
                "donor_key": donor, "gen_step": gen}

    best_prefix = None  # (gen_step, name, proot, donor_key)
    for name, proot in parts:
        entries, _anoms, _bad, _torn = load_cache_index(proot)
        if not entries:
            continue
        hit = lookup_exact(entries, config)
        if hit is not None:
            return decision(name, proot, "exact",
                            donor=hit[0].get("key"))
        pre = lookup_prefix(entries, config)
        if pre is not None:
            gen = pre[1]
            if best_prefix is None or gen > best_prefix[0]:
                best_prefix = (gen, name, proot, pre[0].get("key"))
    if best_prefix is not None:
        gen, name, proot, donor = best_prefix
        return decision(name, proot, "prefix", donor=donor, gen=gen)

    # Capacity filter (heterogeneous admission): only bite when fresh
    # host records exist AND the fit test actually excludes somebody —
    # a homogeneous (or record-less) fleet falls through to pure load.
    hosts = {h: d for h, d in read_host_records(fleet_root).items()
             if host_record_fresh(d, now)}
    cells = grid_cells(config)
    kind = "load"
    candidates = parts
    if hosts:
        fits = {h for h, d in hosts.items()
                if d.get("max_cells") is None
                or cells <= int(d["max_cells"])}
        if fits and fits != set(hosts):
            fitted = [(n, p) for n, p in parts if holder(n) in fits]
            if fitted:
                candidates = fitted
                kind = "capacity"
    name, proot = min(candidates,
                      key=lambda np: (_partition_load(np[1]), np[0]))
    return decision(name, proot, kind)


def find_job(fleet_root, job_id: str) -> Optional[Tuple[str, str]]:
    """Locate a job's partition -> ``(name, root)``: committed job
    record or spool entry first (O(1)), journal fold as the fallback
    (a crash between the ``accepted`` append and the record commit is
    visible only there)."""
    for name, proot in partition_roots(fleet_root):
        store = JobStore(proot, create=False)
        if os.path.isfile(store.job_record_path(job_id)) \
                or os.path.isfile(store.spool_path(job_id)):
            return name, proot
    for name, proot in partition_roots(fleet_root):
        jobs, _ = JobStore(proot, create=False).replay()
        if job_id in jobs:
            return name, proot
    return None


# ---------------------------------------------------------------------------
# Federated audit (tools/heatq.py --check)
# ---------------------------------------------------------------------------

def audit_fleet(fleet_root, now: Optional[float] = None
                ) -> Tuple[dict, List[str]]:
    """Federation-level durability audit -> ``(info, anomalies)``.

    - **stale-lease inventory**: a lease past its own timeout means a
      host died and no peer has reclaimed it yet — jobs there are
      stranded; always an anomaly (a drained host RELEASES, it never
      abandons);
    - **cross-host double-claim**: per partition journal, the
      ``lease_claimed``/``host_lost`` epoch chain must be strictly
      increasing, and the on-disk lease may never be BEHIND the
      journal's newest epoch (two live writers would interleave
      exactly this way);
    - **cross-host double-dispatch**: a ``dispatched`` line for a job
      already running with no intervening failure/requeue/terminal —
      the split-brain signature the lease protocol exists to prevent;
    - **adopted-job lineage**: every ``adopted`` line must follow a
      ``host_lost`` of the same epoch, be appended by that epoch's
      claimant, and name a job that was live at that point.
    """
    now = time.time() if now is None else now
    anomalies: List[str] = []
    leases = list_leases(fleet_root)
    part_names = {n for n, _ in partition_roots(fleet_root)}
    stale = []
    for part, doc in leases.items():
        if part not in part_names:
            anomalies.append(
                f"lease {part!r} names no partition under parts/")
        if lease_stale(doc, now):
            age = now - (doc.get("t_wall") or 0)
            stale.append({"partition": part, "host": doc.get("host"),
                          "age_s": round(age, 3),
                          "timeout_s": doc.get("timeout_s")})
            anomalies.append(
                f"{part}: stale lease held by "
                f"{doc.get('host')!r} (age {age:.1f}s > timeout "
                f"{doc.get('timeout_s')}s) — host lost and not yet "
                f"reclaimed by any peer")

    claims_total = 0
    adopted_total = 0
    for part, proot in partition_roots(fleet_root):
        events, _bad, _torn = read_journal_file(
            os.path.join(proot, "journal.jsonl"))
        last_epoch = 0
        epoch_host: Dict[int, str] = {}
        lost_epochs = set()
        running: Dict[str, Optional[str]] = {}  # job -> dispatch host
        jobs_state: Dict[str, str] = {}
        for e in events:
            ev = e.get("event")
            jid = e.get("job_id")
            if ev in ("lease_claimed", "host_lost"):
                try:
                    epoch = int(e.get("epoch") or 0)
                except (TypeError, ValueError):
                    continue
                if ev == "lease_claimed":
                    claims_total += 1
                    if epoch <= last_epoch and last_epoch:
                        anomalies.append(
                            f"{part}: lease epoch regression — "
                            f"claimed epoch {epoch} after epoch "
                            f"{last_epoch} (cross-host double-claim)")
                    epoch_host[epoch] = e.get("host")
                    last_epoch = max(last_epoch, epoch)
                else:
                    lost_epochs.add(epoch)
                    last_epoch = max(last_epoch, epoch)
                continue
            if jid is None:
                continue
            if ev == "adopted":
                adopted_total += 1
                try:
                    epoch = int(e.get("epoch") or 0)
                except (TypeError, ValueError):
                    epoch = 0
                if epoch not in lost_epochs:
                    anomalies.append(
                        f"{part}: {jid}: adopted at epoch {epoch} "
                        f"with no matching host_lost line (broken "
                        f"adoption lineage)")
                claimant = epoch_host.get(epoch)
                if claimant is not None \
                        and e.get("host") != claimant:
                    anomalies.append(
                        f"{part}: {jid}: adopted by "
                        f"{e.get('host')!r} but epoch {epoch} was "
                        f"claimed by {claimant!r}")
                if jobs_state.get(jid) in (None, "completed",
                                           "quarantined", "cancelled",
                                           "deadline_expired"):
                    anomalies.append(
                        f"{part}: {jid}: adopted while "
                        f"{jobs_state.get(jid) or 'unknown'} — only "
                        f"live jobs are adoptable")
                continue
            if ev == "accepted":
                jobs_state[jid] = "queued"
            elif ev == "dispatched":
                if running.get(jid) is not None:
                    anomalies.append(
                        f"{part}: {jid}: dispatched by host "
                        f"{e.get('host')!r} while already running "
                        f"under host {running[jid]!r} (double "
                        f"dispatch)")
                running[jid] = e.get("host") or "?"
                jobs_state[jid] = "running"
            elif ev in ("worker_failed", "orphaned", "requeued"):
                running[jid] = None
                jobs_state[jid] = ("queued" if ev == "requeued"
                                   else "failed")
            elif ev in ("completed", "quarantined", "cancelled",
                        "deadline_expired", "rejected"):
                running[jid] = None
                jobs_state[jid] = ev
        disk = leases.get(part)
        if isinstance(disk, dict) \
                and int(disk.get("epoch") or 0) < last_epoch:
            anomalies.append(
                f"{part}: on-disk lease epoch "
                f"{disk.get('epoch')} is behind the journal's newest "
                f"epoch {last_epoch} (a stale claimant still holds "
                f"the file — double-claim window)")
    info = {"partitions": sorted(part_names),
            "leases": leases, "stale_leases": stale,
            "hosts": read_host_records(fleet_root),
            "lease_claims": claims_total,
            "jobs_adopted": adopted_total}
    return info, anomalies


# ---------------------------------------------------------------------------
# FleetHost: one process, many leased partitions, each a plain Heatd
# ---------------------------------------------------------------------------

@dataclass
class FleetHostConfig:
    """One federated host's knobs. Everything below ``daemon_opts``
    parameterizes the PER-PARTITION ``HeatdConfig`` (the fleet layer
    adds no scheduler of its own — it only decides which partitions
    this host may step)."""

    fleet_root: str
    host: str
    # Capacity record fields (heterogeneous admission): max_cells is
    # the largest grid this host volunteers for (None = unbounded —
    # the TPU-class host); the router filters on it.
    platform: str = "cpu"
    max_cells: Optional[int] = None
    # Lease protocol: None timeout = the fleet.json default; renewal
    # defaults to a quarter of the timeout (several missable beats).
    lease_timeout_s: Optional[float] = None
    lease_renew_s: Optional[float] = None
    # Most partitions this host will hold at once (None = all of
    # them); work stealing stays inside the same bound.
    max_partitions: Optional[int] = None
    steal: bool = True
    slots: int = 2
    poll_interval_s: float = 0.25
    clock: Callable[[], float] = field(default=time.time)
    sleep_fn: Callable[[float], None] = field(default=time.sleep)
    # Extra HeatdConfig kwargs applied to every partition daemon
    # (tests inject launcher/worker_env/heartbeat knobs here).
    daemon_opts: Optional[dict] = None

    def validate(self) -> "FleetHostConfig":
        if not self.host or "/" in self.host or self.host.startswith("."):
            raise ValueError(f"host must be a plain name, got "
                             f"{self.host!r}")
        if self.max_partitions is not None and self.max_partitions < 1:
            raise ValueError(f"max_partitions must be >= 1, got "
                             f"{self.max_partitions}")
        if self.lease_timeout_s is not None \
                and self.lease_timeout_s <= 0:
            raise ValueError("lease_timeout_s must be > 0")
        if self.lease_renew_s is not None and self.lease_renew_s <= 0:
            raise ValueError("lease_renew_s must be > 0")
        return self


class FleetHost:
    """One federated heatd host: claims partition leases, steps one
    ordinary :class:`Heatd` per held partition, renews its leases,
    reclaims stale peers' leases (adopting their in-flight jobs), and
    publishes its capacity record. Single-threaded like the daemon it
    wraps: every cross-host decision is a rename/link commit, every
    journal write happens under a held lease."""

    def __init__(self, config: FleetHostConfig):
        self.config = config.validate()
        fdoc = fleet_doc(config.fleet_root)
        self.lease_timeout_s = float(
            config.lease_timeout_s
            or fdoc.get("lease_timeout_s")
            or DEFAULT_LEASE_TIMEOUT_S)
        self.lease_renew_s = float(
            config.lease_renew_s or self.lease_timeout_s / 4.0)
        if self.lease_renew_s >= self.lease_timeout_s:
            raise ValueError(
                f"lease_renew_s ({self.lease_renew_s}) must be < "
                f"lease_timeout_s ({self.lease_timeout_s}) — a renew "
                f"cadence past the timeout makes every live host look "
                f"dead")
        self.daemons: Dict[str, Heatd] = {}
        self.leases: Dict[str, dict] = {}
        self._last_renew: Dict[str, float] = {}
        self._last_scan: Optional[float] = None
        self._last_record: Optional[float] = None
        self._draining = False
        self.counters = {"claims": 0, "steals": 0, "takeovers": 0,
                         "hosts_lost": 0, "jobs_adopted": 0,
                         "leases_lost": 0}

    # -- lease lifecycle -------------------------------------------------

    def _daemon_config(self, proot: str) -> HeatdConfig:
        cfg = self.config
        kw = dict(root=proot, slots=cfg.slots,
                  poll_interval_s=cfg.poll_interval_s,
                  clock=cfg.clock, sleep_fn=cfg.sleep_fn,
                  host=cfg.host)
        kw.update(cfg.daemon_opts or {})
        return HeatdConfig(**kw)

    def _attach(self, part: str, proot: str, lease: dict, kind: str,
                observed: Optional[dict] = None) -> Heatd:
        """Construct the partition's daemon under our fresh lease and
        journal the claim — plus, on a takeover, the ``host_lost``
        line and one ``adopted`` line per in-flight job. Ordering: the
        lease commit already happened (we are the single writer by the
        time the first append lands)."""
        d = Heatd(self._daemon_config(proot))
        j = d.store.journal
        epoch = int(lease["epoch"])
        j.append("lease_claimed", partition=part, epoch=epoch,
                 kind=kind)
        if observed is not None:
            self.counters["hosts_lost"] += 1
            j.append("host_lost", partition=part, epoch=epoch,
                     lost_host=observed.get("host"),
                     last_renew_t=observed.get("t_wall"))
            jobs, _ = d._replay()
            for jid in sorted(jobs):
                v = jobs[jid]
                if v.state == "running":
                    self.counters["jobs_adopted"] += 1
                    j.append("adopted", job_id=jid, epoch=epoch,
                             from_host=observed.get("host"),
                             from_worker=v.worker, attempt=v.attempts)
        self.daemons[part] = d
        self.leases[part] = lease
        self._last_renew[part] = float(lease["t_wall"])
        return d

    def _abandon(self, part: str, reason: str) -> None:
        """Lease lost while we were alive (wedged past the timeout; a
        peer legitimately took over): stop IMMEDIATELY — kill our
        workers (the peer's adopted re-dispatches own the stems now;
        the stem lock would fence a straggler anyway, but a split
        brain must not burn the slots) and close the daemon WITHOUT
        journaling: we no longer own that journal."""
        self.counters["leases_lost"] += 1
        d = self.daemons.pop(part, None)
        self.leases.pop(part, None)
        self._last_renew.pop(part, None)
        if d is not None:
            d.abandon()

    def _idle(self) -> bool:
        """Idle = no held partition has live or queued work (the
        work-stealing trigger; counted from the folded views — no
        extra journal reads, the daemons already fold incrementally)."""
        for d in self.daemons.values():
            jobs, _ = d._replay()
            for v in jobs.values():
                if v.state in ("queued", "running", "failed"):
                    return False
        return True

    @staticmethod
    def _has_backlog(proot: str) -> bool:
        if _partition_load(proot) > 0:
            return True
        return False

    def _room(self) -> bool:
        mp = self.config.max_partitions
        return mp is None or len(self.leases) < mp

    def _lease_pass(self, now: float) -> None:
        cfg = self.config
        # 1. Renew what we hold (and detect loss LOUDLY: a renew that
        # comes back None means a peer's takeover committed — abandon
        # before the next journal append, not after).
        for part in list(self.leases):
            if now - self._last_renew.get(part, 0.0) \
                    < self.lease_renew_s:
                continue
            doc = renew_lease(cfg.fleet_root, part, cfg.host,
                              int(self.leases[part]["epoch"]), now=now)
            if doc is None:
                self._abandon(part, "lease lost (peer takeover)")
            else:
                self.leases[part] = doc
                self._last_renew[part] = now
        if self._draining:
            return
        # 2. Scan for claimable partitions at the renew cadence (the
        # scan cold-reads lease files and, on a claim, one journal —
        # too heavy for every poll tick, cheap at heartbeat cadence).
        if self._last_scan is not None \
                and now - self._last_scan < self.lease_renew_s:
            return
        self._last_scan = now
        idle = None  # lazily computed: only when a steal is possible
        for part, proot in partition_roots(cfg.fleet_root):
            if part in self.leases:
                continue
            if not self._room():
                break
            observed = read_lease(cfg.fleet_root, part)
            if observed is None:
                # Unleased: link-commit a claim. "Steal" (work
                # stealing) when we are idle and the partition has
                # backlog another host left behind; plain claim
                # otherwise. Oldest-first: partitions scan sorted.
                epoch = journal_lease_epoch(proot) + 1
                lease = claim_lease(
                    cfg.fleet_root, part, cfg.host, epoch=epoch,
                    timeout_s=self.lease_timeout_s, now=now)
                if lease is None:
                    continue
                kind = "claim"
                if epoch > 1 and self._has_backlog(proot):
                    if idle is None:
                        idle = self._idle()
                    if idle and cfg.steal:
                        kind = "steal"
                        self.counters["steals"] += 1
                self.counters["claims"] += 1
                self._attach(part, proot, lease, kind)
            elif observed.get("host") != cfg.host \
                    and lease_stale(observed, now):
                # Stale peer: rename-committed takeover + adoption.
                lease = steal_lease(
                    cfg.fleet_root, part, observed, cfg.host,
                    timeout_s=self.lease_timeout_s, now=now)
                if lease is None:
                    continue  # a peer won the race — exactly one does
                self.counters["takeovers"] += 1
                self._attach(part, proot, lease, "takeover",
                             observed=observed)
            elif observed.get("host") == cfg.host \
                    and part not in self.daemons \
                    and lease_stale(observed, now):
                # Our own residue from a crashed predecessor process:
                # reclaim through the same rename-committed path (a
                # peer may be racing us for it right now).
                lease = steal_lease(
                    cfg.fleet_root, part, observed, cfg.host,
                    timeout_s=self.lease_timeout_s, now=now)
                if lease is not None:
                    self.counters["takeovers"] += 1
                    self._attach(part, proot, lease, "takeover",
                                 observed=observed)

    # -- capacity record -------------------------------------------------

    def _publish_host(self, now: float, state: Optional[str] = None
                      ) -> None:
        if state is None and self._last_record is not None \
                and now - self._last_record < self.lease_renew_s:
            return
        self._last_record = now
        cfg = self.config
        write_host_record(cfg.fleet_root, {
            "host": cfg.host, "pid": os.getpid(),
            "platform": cfg.platform, "max_cells": cfg.max_cells,
            "slots": cfg.slots, "t_wall": now,
            "ttl_s": 4 * self.lease_renew_s,
            "state": state or ("draining" if self._draining
                               else "serving"),
            "leases": sorted(self.leases),
            "counters": dict(self.counters)})

    # -- driving ---------------------------------------------------------

    def step(self, now: Optional[float] = None) -> dict:
        """One federated pass: renew/claim/reclaim leases, publish the
        capacity record, then one ordinary scheduling pass per held
        partition. Returns a per-partition summary."""
        cfg = self.config
        now = cfg.clock() if now is None else now
        self._lease_pass(now)
        self._publish_host(now)
        summaries = {}
        for part in sorted(self.daemons):
            summaries[part] = self.daemons[part].step(now)
        return {"host": cfg.host, "leases": sorted(self.leases),
                "counters": dict(self.counters),
                "partitions": summaries}

    def serve(self, max_seconds: Optional[float] = None) -> int:
        """Poll loop until SIGTERM/SIGINT (or ``max_seconds``), then
        graceful drain — same lifecycle contract as
        :meth:`Heatd.serve`, returning ``EXIT_PREEMPTED``."""
        cfg = self.config
        stop = {"signum": None}

        def handler(signum, frame):
            stop["signum"] = signum  # flag only — drain at the loop top

        prev = {}
        try:
            for s in (signal.SIGTERM, signal.SIGINT):
                prev[s] = signal.signal(s, handler)
        except ValueError:  # not the main thread (tests)
            prev = {}
        t0 = cfg.clock()
        try:
            while stop["signum"] is None:
                self.step()
                if max_seconds is not None \
                        and cfg.clock() - t0 >= max_seconds:
                    break
                cfg.sleep_fn(cfg.poll_interval_s)
            return self.drain()
        finally:
            for s, h in prev.items():
                signal.signal(s, h)

    def drain(self) -> int:
        """Graceful exit: drain every partition daemon (journals the
        resume states), RELEASE the leases (a released partition is
        immediately claimable — no peer waits out a timeout), publish
        a final drained record."""
        cfg = self.config
        self._draining = True
        for part in sorted(self.daemons):
            self.daemons[part].drain()
        for part in sorted(self.leases):
            release_lease(cfg.fleet_root, part, cfg.host,
                          int(self.leases[part]["epoch"]))
        self.daemons.clear()
        self.leases.clear()
        self._publish_host(cfg.clock(), state="drained")
        return EXIT_PREEMPTED

    def close(self) -> None:
        """Teardown without drain (tests/chaos): release journal
        handles, keep leases on disk — exactly what a crashed host
        leaves behind."""
        for d in self.daemons.values():
            d.close()
        self.daemons.clear()


# ---------------------------------------------------------------------------
# Fleet status (CLI / monitor)
# ---------------------------------------------------------------------------

def fleet_status(fleet_root, now: Optional[float] = None) -> dict:
    """One federated snapshot: partitions with their lease + job
    counts (from each journal's pure fold), host records, stale-lease
    inventory."""
    now = time.time() if now is None else now
    leases = list_leases(fleet_root)
    parts = []
    for name, proot in partition_roots(fleet_root):
        events, _bad, _torn = read_journal_file(
            os.path.join(proot, "journal.jsonl"))
        jobs, anomalies = reduce_journal(events)
        counts: Dict[str, int] = {}
        for v in jobs.values():
            counts[v.state] = counts.get(v.state, 0) + 1
        doc = leases.get(name)
        parts.append({
            "partition": name,
            "host": (doc or {}).get("host"),
            "lease_epoch": (doc or {}).get("epoch"),
            "lease_age_s": (round(now - doc["t_wall"], 3)
                            if doc and isinstance(doc.get("t_wall"),
                                                  (int, float))
                            else None),
            "lease_stale": (lease_stale(doc, now)
                            if doc is not None else None),
            "jobs": len(jobs), "counts": counts,
            "anomalies": len(anomalies)})
    return {"root": str(fleet_root), "partitions": parts,
            "hosts": read_host_records(fleet_root)}
