"""``heatd``: the long-lived solver-as-a-service daemon.

ROADMAP item 2's serving layer, built so that every crash the chaos
matrix can inject — worker SIGKILL mid-job, daemon SIGKILL between
journal append and dispatch, overload bursts — lands in a state the
journal already describes. The daemon holds **no authoritative state
in memory**: each scheduling pass replays ``journal.jsonl`` through
``store.reduce_journal`` and acts on the derived views, so a restarted
daemon resumes exactly where the journal says the world is. The loop
per :meth:`Heatd.step`:

1. **reconcile** worker exits and liveness: a result record maps an
   exited worker to its journal transition; a dead/silent worker
   (SIGKILL, OOM — no record, stale heartbeat) has its job journaled
   ``orphaned`` within one heartbeat timeout, checkpoint lineage
   untouched;
2. **cancel/deadline** enforcement: queued jobs transition directly;
   running jobs are interrupted through the supervisor's flag-only
   signal path (SIGTERM -> checkpoint flush -> preempted record), with
   a SIGKILL escalation after ``kill_grace_s``;
3. **admit** spool submissions through ``service.admission`` — journal
   ``accepted`` (after the job spec is rename-committed) or
   ``rejected`` with a retry-after hint; the handshake is idempotent
   across a daemon crash at any point;
4. **route failures**: fail-fast ``PermanentFailure`` kinds
   (``unstable``/``stalled``/``drift``/``bad_spec``) quarantine
   immediately;
   everything else is re-admitted under bounded exponential backoff
   until ``quarantine_after`` distinct workers have failed the job;
5. **dispatch** due queued jobs to worker subprocesses (one process
   per attempt — ``service/worker.py`` resumes from the newest
   committed checkpoint generation, so a re-dispatched job continues
   bit-exactly);
6. publish the ``heatd.json`` status heartbeat for probes
   (``tools/monitor.py --daemon``, ``heatd status``).

SIGTERM/SIGINT triggers the graceful drain: stop admitting, interrupt
in-flight workers, wait for their checkpoint flushes, journal each
job's resume state (``requeued``), and exit ``EXIT_PREEMPTED`` — the
restart re-dispatches from the journal.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from parallel_heat_tpu.service.admission import admission_verdict
from parallel_heat_tpu.service.store import (
    FAILFAST_KINDS,
    JobStore,
    JobView,
    reduce_journal,
)
from parallel_heat_tpu.supervisor import EXIT_PREEMPTED
from parallel_heat_tpu.utils.tracing import (
    ENV_PARENT_SPAN_ID,
    ENV_SPAN_ID,
    ENV_TRACE_ID,
    TraceContext,
    dispatch_span_id,
    submit_span_id,
)


@dataclass
class HeatdConfig:
    """Daemon knobs. Time sources and the worker launcher are
    injectable (tests drive the scheduler on a fake clock; the chaos
    harness swaps launchers) — same pattern as
    ``SupervisorPolicy.sleep_fn``."""

    root: str
    # Concurrent worker processes (one job each).
    slots: int = 2
    poll_interval_s: float = 0.25
    # Cadence workers rewrite their liveness heartbeat at, and the
    # staleness threshold past which a silent worker's job is declared
    # orphaned. The timeout must cover several beats: one missed write
    # is scheduling noise, not death.
    worker_heartbeat_s: float = 0.5
    heartbeat_timeout_s: float = 3.0
    # Admission gates (service.admission).
    max_queue_depth: int = 16
    hbm_budget_bytes: Optional[int] = None
    retry_after_s: float = 2.0
    # Poison-job quarantine: after failures on this many DISTINCT
    # workers (fail-fast PermanentFailure kinds quarantine immediately).
    quarantine_after: int = 3
    # Bounded exponential re-admission backoff after a non-fail-fast
    # failure: min(max, base * 2**(failures-1)).
    requeue_backoff_base_s: float = 0.5
    requeue_backoff_max_s: float = 30.0
    # Escalation: SIGTERM -> this grace -> SIGKILL (cancel/deadline/
    # drain paths).
    kill_grace_s: float = 5.0
    drain_grace_s: float = 60.0
    # Ensemble packing (SEMANTICS.md "Ensemble"): coalesce compatible
    # due FRESH jobs (identical semantic config + supervisor knobs, no
    # deadline, no fault plan, attempt 0, never requeued) into ONE
    # packed worker running them as a batched ensemble program, up to
    # pack_max members per dispatch. The pack consumes one slot. Each
    # member's HBM was already counted by the admission gate at
    # acceptance, so a pack can never exceed what admission allowed.
    # The worker itself re-verifies runtime packability (the bitwise
    # member-parity contract needs the resolved execution path, which
    # requires the accelerator runtime the daemon deliberately never
    # initializes) and demotes the whole pack to solo requeues when it
    # does not hold — packing is a fast path, never a semantic change.
    pack_jobs: bool = False
    pack_max: int = 16
    # Coalescing dwell: a packable job with no companion yet is held
    # back from solo dispatch until it has been queued this long, so a
    # burst of compatible submissions lands in one packed dispatch
    # instead of the first arrival stealing a slot solo. 0 = dispatch
    # greedily (packing still coalesces whatever is queued together).
    pack_wait_s: float = 0.0
    # Extra environment for worker subprocesses (the chaos matrix pins
    # JAX_PLATFORMS=cpu here); inherits os.environ otherwise.
    worker_env: Optional[dict] = None
    clock: Callable[[], float] = field(default=time.time)
    sleep_fn: Callable[[float], None] = field(default=time.sleep)
    # Injectable worker launcher (tests run jobs inline): called as
    # launcher(job_id=, worker_id=, attempt=, deadline_t=) and must
    # return a Popen-shaped handle (poll/terminate/kill/pid). None =
    # spawn `python -m parallel_heat_tpu.service.worker`.
    launcher: Optional[Callable] = None
    # CHAOS HARNESS ONLY: SIGKILL this daemon immediately after
    # journaling the Nth `accepted` event — the exact
    # between-append-and-dispatch crash window the durability contract
    # is certified against (tools/chaos_matrix.py `svc_daemon_restart`).
    chaos_kill_after_accept: Optional[int] = None

    def validate(self) -> "HeatdConfig":
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{self.max_queue_depth}")
        if self.quarantine_after < 1:
            raise ValueError(f"quarantine_after must be >= 1, got "
                             f"{self.quarantine_after}")
        if self.heartbeat_timeout_s < self.worker_heartbeat_s:
            raise ValueError(
                f"heartbeat_timeout_s ({self.heartbeat_timeout_s}) must "
                f"be >= worker_heartbeat_s ({self.worker_heartbeat_s}) "
                f"— a timeout shorter than the write cadence declares "
                f"every live worker dead")
        if self.pack_max < 2:
            raise ValueError(f"pack_max must be >= 2, got "
                             f"{self.pack_max}")
        return self


class _StopFlag:
    __slots__ = ("signum",)

    def __init__(self):
        self.signum: Optional[int] = None


class Heatd:
    """One daemon instance bound to one queue root. Single-threaded by
    design: every mutation of queue state is a journal append from
    :meth:`step`, so there is exactly one writer and no lock to get
    wrong. Construct, then either call :meth:`serve` (the CLI path:
    poll loop + signal-driven drain) or drive :meth:`step` directly
    (tests and the chaos matrix)."""

    def __init__(self, config: HeatdConfig):
        self.config = config.validate()
        self.store = JobStore(config.root)
        self._procs: Dict[str, object] = {}  # job_id -> worker handle
        self._term_sent: Dict[str, float] = {}  # job_id -> SIGTERM t
        # Adopted jobs (no Popen handle) interrupted by heartbeat pid:
        # job_id -> pid, for the SIGKILL escalation.
        self._term_pid: Dict[str, int] = {}
        self._accepts = 0
        self._draining = False
        # job_id -> spec-derived pack key (see _spec_pack_key).
        self._pack_key_cache: Dict[str, object] = {}
        # Incremental journal fold: byte offset consumed so far + the
        # folded state. Equivalent to store.replay() by the reducer's
        # fold law, but each pass parses only the appended events — a
        # long-lived daemon must not re-read its whole history 5x per
        # poll tick.
        self._journal_offset = 0
        self._jobs: Dict[str, JobView] = {}
        self._anomalies: list = []
        self.store.journal.append("daemon_start", pid=os.getpid(),
                                  slots=self.config.slots)

    def _replay(self):
        """Fold journal bytes appended since the last call into the
        cached views; returns ``(jobs, anomalies)`` — the same answer
        ``store.replay()`` gives, O(new events) per pass. Only whole
        lines are consumed: a torn tail (this read racing an append)
        stays unconsumed and is re-read complete next pass."""
        try:
            with open(self.store.journal_path, "rb") as f:
                f.seek(self._journal_offset)
                data = f.read()
        except OSError:
            return self._jobs, self._anomalies
        end = data.rfind(b"\n")
        if end >= 0:
            self._journal_offset += end + 1
            events = []
            for line in data[:end + 1].split(b"\n"):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "event" in rec:
                    events.append(rec)
            reduce_journal(events, state=(self._jobs, self._anomalies))
        return self._jobs, self._anomalies

    # -- scheduling pass -------------------------------------------------

    def step(self, now: Optional[float] = None) -> dict:
        """One scheduling pass; returns a state-count summary (tests
        and the status heartbeat read it)."""
        cfg = self.config
        now = cfg.clock() if now is None else now
        self._reconcile(now)
        self._cancels_and_deadlines(now)
        self._admit(now)
        self._route_failed(now)
        if not self._draining:
            self._dispatch(now)
        return self._publish_status(now)

    # -- phase 1: worker exits / liveness --------------------------------

    def _reconcile(self, now: float) -> None:
        jobs, _ = self._replay()
        for jid, v in jobs.items():
            if v.state != "running":
                continue
            handle = self._procs.get(jid)
            if handle is not None:
                rc = handle.poll()
                if rc is None:
                    continue  # still running
                self._procs.pop(jid, None)
                self._term_sent.pop(jid, None)
                # Read the outcome record only AFTER the exit is
                # observed: a live worker commits its record before
                # exiting, so post-exit is the one moment the read
                # cannot race the rename (and inline test launchers
                # produce the record during poll() itself).
                rec = self.store.read_result(jid, v.attempts)
                self._classify_exit(v, rc, rec, now)
                continue
            rec = self.store.read_result(jid, v.attempts)
            if rec is not None:
                # Adopted job (daemon restarted after dispatch): the
                # worker finished and its rename-committed record is
                # the outcome — journal it exactly once.
                self._term_sent.pop(jid, None)
                self._term_pid.pop(jid, None)
                self._classify_exit(v, None, rec, now)
            else:
                # Adopted job, no outcome record: judge liveness by the
                # worker's heartbeat. A worker that has NEVER beaten
                # gets one heartbeat timeout of grace from its
                # dispatch stamp — a freshly-spawned worker is still
                # importing its runtime before the first beat lands,
                # and orphaning it would race a live process (a second
                # worker against the stem lock). After the grace, a
                # missing/stale beat or a dead pid is a corpse; its
                # job is orphaned — the checkpoint lineage under
                # ck/<job>/ is untouched, so the re-dispatched attempt
                # resumes bit-exactly.
                hb = self.store.read_worker_hb(v.worker or "")
                if hb is None and v.last_dispatch_t is not None \
                        and now - v.last_dispatch_t \
                        <= self.config.heartbeat_timeout_s:
                    continue
                if not self._worker_alive(hb, now):
                    self.store.journal.append(
                        "orphaned", job_id=jid, worker=v.worker,
                        attempt=v.attempts,
                        reason=("worker heartbeat stale/dead "
                                "(no exit record)"))

    def _worker_alive(self, hb: Optional[dict], now: float) -> bool:
        if hb is None:
            return False
        pid = hb.get("pid")
        if isinstance(pid, int) and pid > 0:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return False
            except OSError:
                pass  # EPERM: exists
        t = hb.get("t_wall")
        return (isinstance(t, (int, float))
                and now - t <= self.config.heartbeat_timeout_s)

    def _classify_exit(self, v: JobView, rc, rec, now: float) -> None:
        j = self.store.journal
        jid = v.job_id
        outcome = (rec or {}).get("outcome")
        if outcome == "completed":
            j.append("completed", job_id=jid, worker=v.worker,
                     attempt=v.attempts,
                     steps_done=rec.get("steps_done"),
                     wall_s=rec.get("wall_s"))
        elif outcome == "permanent_failure":
            j.append("worker_failed", job_id=jid, worker=v.worker,
                     attempt=v.attempts, exit_code=rc,
                     kind=rec.get("kind") or "unknown",
                     diagnosis=rec.get("diagnosis"))
        elif outcome == "preempted":
            reason = rec.get("reason")
            if v.cancel_requested:
                j.append("cancelled", job_id=jid, worker=v.worker,
                         attempt=v.attempts,
                         steps_done=rec.get("steps_done"))
                self.store.clear_cancel(jid)
            elif reason == "deadline" or (v.deadline_t is not None
                                          and now >= v.deadline_t):
                j.append("deadline_expired", job_id=jid,
                         worker=v.worker, attempt=v.attempts,
                         steps_done=rec.get("steps_done"))
            else:
                # Drain / external preemption: the flushed checkpoint
                # IS the resume state — journal it so a restart
                # re-dispatches from exactly here.
                j.append("requeued", job_id=jid, reason="preempted",
                         not_before=now, attempt=v.attempts,
                         steps_done=rec.get("steps_done"))
        else:
            # No record (SIGKILL/OOM before the rename landed) or an
            # unreadable one: a true orphan.
            j.append("orphaned", job_id=jid, worker=v.worker,
                     attempt=v.attempts,
                     reason=f"worker exited rc={rc} without an outcome "
                            f"record")

    # -- phase 2: cancellation + deadlines -------------------------------

    def _cancels_and_deadlines(self, now: float) -> None:
        cfg = self.config
        jobs, _ = self._replay()
        j = self.store.journal
        for jid in self.store.cancel_requests():
            v = jobs.get(jid)
            if v is None or v.terminal or v.state == "rejected":
                self.store.clear_cancel(jid)
                continue
            if not v.cancel_requested:
                j.append("cancel_requested", job_id=jid)
                v.cancel_requested = True
            if v.state in ("queued", "failed"):
                j.append("cancelled", job_id=jid, attempt=v.attempts)
                self.store.clear_cancel(jid)
            elif v.state == "running":
                self._interrupt_worker(jid, now, worker=v.worker)
        for jid, v in jobs.items():
            if v.terminal or v.deadline_t is None or now < v.deadline_t:
                continue
            if v.state in ("queued", "failed"):
                j.append("deadline_expired", job_id=jid,
                         attempt=v.attempts,
                         reason=f"deadline passed while {v.state}")
            elif v.state == "running":
                # The worker's own interrupt hook normally beats this;
                # the daemon-side SIGTERM (then SIGKILL after the
                # grace) is the backstop for a wedged worker.
                self._interrupt_worker(jid, now, worker=v.worker)
        # Escalation: a worker that ignored SIGTERM past the grace gets
        # the uncatchable one; reconcile then orphans+requeues its job.
        for jid, t0 in list(self._term_sent.items()):
            v = jobs.get(jid)
            if v is None or v.state != "running":
                self._term_sent.pop(jid, None)
                self._term_pid.pop(jid, None)
                continue
            if now - t0 <= cfg.kill_grace_s:
                continue
            handle = self._procs.get(jid)
            if handle is not None:
                if handle.poll() is None:
                    handle.kill()
            elif jid in self._term_pid:
                try:
                    os.kill(self._term_pid[jid], signal.SIGKILL)
                except OSError:
                    pass

    def _interrupt_worker(self, jid: str, now: float,
                          worker: Optional[str] = None) -> None:
        if jid in self._term_sent:
            return
        handle = self._procs.get(jid)
        if handle is not None:
            try:
                handle.terminate()
            except OSError:
                pass
            self._term_sent[jid] = now
            return
        # Adopted job (daemon restarted after dispatch): no handle,
        # but the worker's heartbeat names its pid — cancellation and
        # deadlines must reach it all the same.
        hb = self.store.read_worker_hb(worker or "")
        pid = (hb or {}).get("pid")
        if isinstance(pid, int) and pid > 0:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                return
            self._term_sent[jid] = now
            self._term_pid[jid] = pid

    # -- phase 3: admission ----------------------------------------------

    def _admit(self, now: float) -> None:
        cfg = self.config
        j = self.store.journal
        jobs, _ = self._replay()
        for jid in self.store.iter_spool():
            if jid in jobs:
                # Crash between the journal append and the spool unlink
                # on a previous pass: finish the handshake idempotently
                # — never a second accepted/rejected line.
                self.store.drop_spool(jid)
                continue
            spec = self.store.read_spool(jid)
            if spec is None:
                continue  # torn/foreign spool entry: leave for inspection
            active = [v for v in jobs.values()
                      if not v.terminal and v.state != "rejected"]
            ok, reason, retry_after, est = admission_verdict(
                spec.config, len(active),
                sum(v.hbm_bytes for v in active),
                cfg.max_queue_depth, cfg.hbm_budget_bytes,
                cfg.retry_after_s, cfg.slots, draining=self._draining)
            if not ok:
                rec = j.append("rejected", job_id=jid, reason=reason,
                               retry_after_s=retry_after)
                # Fold by hand like the accepted branch below: a later
                # acceptance in this same pass bumps the offset past
                # these bytes, and an unfolded rejection would both
                # undercount forever and let a re-used id through the
                # `jid in jobs` dedupe.
                self._journal_offset = os.path.getsize(
                    self.store.journal_path)
                reduce_journal([rec],
                               state=(self._jobs, self._anomalies))
                self.store.drop_spool(jid)
                continue
            # Durable spec FIRST, then the accepted line: a crash
            # between the two replays the handshake from the spool copy
            # (record rewrite is idempotent), so `accepted` in the
            # journal always implies a loadable spec on disk.
            self.store.commit_job_record(spec)
            rec = j.append("accepted", job_id=jid,
                           deadline_s=spec.deadline_s, hbm_bytes=est,
                           submitted_t=spec.submitted_t,
                           trace_id=(spec.trace or {}).get("trace_id"))
            # Fold the acceptance into the cached view by hand so the
            # NEXT spool entry's gate sees this job as active without
            # re-reading the journal (the incremental fold will skip
            # these bytes — they are consumed here).
            self._journal_offset = os.path.getsize(
                self.store.journal_path)
            reduce_journal([rec], state=(self._jobs, self._anomalies))
            self._accepts += 1
            if cfg.chaos_kill_after_accept is not None \
                    and self._accepts >= cfg.chaos_kill_after_accept:
                # Chaos window: die BETWEEN the journal append and the
                # dispatch (and even before the spool unlink) — restart
                # must recover the job from the journal alone.
                os.kill(os.getpid(), signal.SIGKILL)
            self.store.drop_spool(jid)

    # -- phase 4: failure routing ----------------------------------------

    def _route_failed(self, now: float) -> None:
        cfg = self.config
        jobs, _ = self._replay()
        j = self.store.journal
        for jid, v in jobs.items():
            if v.state != "failed":
                continue
            last_kind = v.failures[-1][1] if v.failures else "unknown"
            if last_kind in FAILFAST_KINDS:
                # Deterministic verdicts: replaying bad physics on a
                # different worker replays the same physics.
                j.append("quarantined", job_id=jid, kind=last_kind,
                         diagnosis=v.diagnosis,
                         distinct_workers=v.distinct_failed_workers,
                         reason=f"fail-fast permanent failure "
                                f"(kind={last_kind})")
            elif v.distinct_failed_workers >= cfg.quarantine_after:
                j.append("quarantined", job_id=jid, kind=last_kind,
                         diagnosis=v.diagnosis,
                         distinct_workers=v.distinct_failed_workers,
                         reason=f"failed on "
                                f"{v.distinct_failed_workers} distinct "
                                f"workers (poison-job threshold "
                                f"{cfg.quarantine_after})")
            else:
                n = len(v.failures)
                delay = min(cfg.requeue_backoff_max_s,
                            cfg.requeue_backoff_base_s * 2 ** (n - 1))
                j.append("requeued", job_id=jid, reason=last_kind,
                         backoff_s=delay, not_before=now + delay,
                         attempt=v.attempts)

    # -- phase 5: dispatch -----------------------------------------------

    def _spec_pack_key(self, job_id: str):
        """The SPEC-derived half of the pack key (or None for a spec
        that can never pack), cached per job id — committed specs are
        immutable, and _dispatch consults the key for every queued job
        on every poll tick, so re-reading the record each time would
        turn a dwelling burst into O(jobs) disk reads per tick."""
        if job_id in self._pack_key_cache:
            return self._pack_key_cache[job_id]
        try:
            spec = self.store.load_spec(job_id)
        except (OSError, ValueError):
            return None  # not cached: the record may still be landing
        if spec.faults is not None or spec.deadline_s is not None:
            key = None
        else:
            # Every knob worker.execute_pack builds the SHARED
            # SupervisorPolicy from must be in the key — a member
            # running under another job's settings would be a silent
            # semantics change.
            key = (json.dumps(spec.config, sort_keys=True),
                   spec.checkpoint_every, spec.guard_interval,
                   spec.max_retries, spec.backoff_base_s)
        self._pack_key_cache[job_id] = key
        if len(self._pack_key_cache) > 4096:  # bound a long daemon's map
            self._pack_key_cache.pop(next(iter(self._pack_key_cache)))
        return key

    def _pack_key(self, v: JobView):
        """Compatibility key for ensemble packing, or None when this
        job must run solo. FRESH jobs only (attempt 0, never requeued):
        a member with history has checkpoint lineage or per-attempt
        state the batched fresh-start program would ignore. The key is
        the full semantic config (byte-equal JSON) plus the supervisor
        knobs the packed run shares; deadlines and fault plans are
        per-job machinery the pack deliberately refuses."""
        if v.attempts > 0 or v.requeues > 0 or v.cancel_requested \
                or v.deadline_t is not None:
            return None
        return self._spec_pack_key(v.job_id)

    def _dispatch(self, now: float) -> None:
        cfg = self.config
        jobs, _ = self._replay()
        # Slot accounting counts WORKERS, not jobs: a packed dispatch
        # runs many jobs in one process and consumes one slot.
        running = len({v.worker for v in jobs.values()
                       if v.state == "running" and v.worker})
        due = sorted((v for v in jobs.values()
                      if v.state == "queued" and v.not_before <= now),
                     key=lambda v: (v.accepted_t or 0.0, v.job_id))
        j = self.store.journal
        packed: set = set()
        if cfg.pack_jobs and len(due) > 1:
            groups: Dict[object, list] = {}
            for v in due:
                key = self._pack_key(v)
                if key is not None:
                    groups.setdefault(key, []).append(v)
            for key in sorted(groups, key=str):
                members = groups[key]
                while len(members) >= 2 and running < cfg.slots:
                    batch = members[:cfg.pack_max]
                    members = members[len(batch):]
                    if len(batch) < 2:
                        break
                    leader = batch[0]
                    wid = f"w-{leader.job_id}-a001-p{len(batch):03d}"
                    # Journal every member BEFORE spawn (the solo
                    # ordering rule): a crash in between leaves
                    # dispatched jobs with no live worker — reconcile
                    # orphans and requeues them, and requeued members
                    # are no longer fresh, so the retry runs solo.
                    for v in batch:
                        j.append("dispatched", job_id=v.job_id,
                                 worker=wid, attempt=v.attempts + 1,
                                 pack=leader.job_id,
                                 pack_size=len(batch),
                                 trace_id=v.trace_id)
                    try:
                        handle = self._launch_pack(batch, wid)
                    except OSError as e:
                        for v in batch:
                            j.append("orphaned", job_id=v.job_id,
                                     worker=wid, attempt=v.attempts + 1,
                                     reason=f"worker spawn failed: {e}")
                        continue
                    for v in batch:
                        self._procs[v.job_id] = handle
                        packed.add(v.job_id)
                    running += 1
        for v in due:
            if v.job_id in packed:
                continue
            if cfg.pack_jobs and cfg.pack_wait_s > 0 \
                    and v.accepted_t is not None \
                    and now - v.accepted_t < cfg.pack_wait_s \
                    and self._pack_key(v) is not None:
                # Coalescing dwell: hold a lone packable job briefly —
                # a compatible companion may be right behind it.
                continue
            if running >= cfg.slots:
                break
            attempt = v.attempts + 1
            # Deterministic worker id (job + attempt): replayable after
            # a daemon restart, and distinct per attempt so the
            # poison-job classifier's distinct-worker count is exactly
            # the distinct-attempt count.
            wid = f"w-{v.job_id}-a{attempt:03d}"
            # Journal BEFORE spawn: a crash in between leaves a
            # `dispatched` job with no live worker — the reconcile
            # pass orphans and requeues it. The opposite order could
            # run a worker the journal knows nothing about (a double
            # execution after restart).
            j.append("dispatched", job_id=v.job_id, worker=wid,
                     attempt=attempt, trace_id=v.trace_id)
            try:
                handle = self._launch(v, wid, attempt)
            except OSError as e:
                j.append("orphaned", job_id=v.job_id, worker=wid,
                         attempt=attempt,
                         reason=f"worker spawn failed: {e}")
                continue
            self._procs[v.job_id] = handle
            running += 1

    def _spawn_worker(self, job_args, worker_id: str,
                      trace: Optional[TraceContext] = None):
        """Shared subprocess plumbing for solo AND packed dispatches
        (one site to evolve env/log handling): spawn
        ``python -m parallel_heat_tpu.service.worker`` with
        ``job_args`` + the common flags, stdout/stderr to the worker
        log. ``trace`` (the dispatch span context) rides the
        environment — the worker's telemetry sink inherits it, so the
        run's envelope joins the submit's trace without a flag."""
        cfg = self.config
        argv = [sys.executable, "-m", "parallel_heat_tpu.service.worker",
                "--root", self.store.root, *job_args,
                "--worker", worker_id,
                "--hb-interval", str(cfg.worker_heartbeat_s)]
        env = dict(os.environ)
        # Always set or CLEAR the trace variables: the daemon's own
        # environment may carry foreign HEATTRACE_* values (started by
        # a traced harness), and an untraced job's worker inheriting
        # them would stamp its whole stream into an unrelated trace.
        for k in (ENV_TRACE_ID, ENV_SPAN_ID, ENV_PARENT_SPAN_ID):
            env.pop(k, None)
        if trace is not None:
            env.update(trace.to_env())
        # The worker must import this package regardless of the
        # daemon's cwd (the CLI may be launched from anywhere).
        import parallel_heat_tpu

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(parallel_heat_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        env.update(cfg.worker_env or {})
        log = open(self.store.worker_log_path(worker_id), "ab")
        try:
            return subprocess.Popen(argv, stdout=log,
                                    stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()  # Popen holds its own duplicate

    def _trace_for(self, v: JobView, attempt: int
                   ) -> Optional[TraceContext]:
        """The dispatch span context this attempt inherits: the job's
        journaled trace id with the deterministic dispatch span as the
        current hop (parent = the client's submit span). None for
        untraced (pre-trace) jobs."""
        if v.trace_id is None:
            return None
        return TraceContext(v.trace_id,
                            dispatch_span_id(v.job_id, attempt),
                            submit_span_id(v.job_id))

    def _launch(self, v: JobView, worker_id: str, attempt: int):
        cfg = self.config
        if cfg.launcher is not None:
            return cfg.launcher(job_id=v.job_id, worker_id=worker_id,
                                attempt=attempt, deadline_t=v.deadline_t)
        job_args = ["--job", v.job_id, "--attempt", str(attempt)]
        if v.deadline_t is not None:
            job_args += ["--deadline-t", repr(v.deadline_t)]
        return self._spawn_worker(job_args, worker_id,
                                  trace=self._trace_for(v, attempt))

    def _launch_pack(self, batch, worker_id: str):
        """Spawn ONE worker process running ``batch`` as a packed
        ensemble dispatch (``service/worker.py --jobs``). Injectable
        like the solo launcher: a configured ``launcher`` receives the
        extra ``job_ids`` keyword (inline test harnesses run
        ``worker.execute_pack`` directly)."""
        cfg = self.config
        job_ids = [v.job_id for v in batch]
        if cfg.launcher is not None:
            return cfg.launcher(job_id=job_ids[0], worker_id=worker_id,
                                attempt=1, deadline_t=None,
                                job_ids=job_ids)
        # One env can carry one context: the pack's shared stream
        # traces under the LEADER's trace (per-member journal lines
        # keep each member's own trace_id; heattrace renders member
        # lanes from the stream's `member` fields).
        return self._spawn_worker(["--jobs", ",".join(job_ids)],
                                  worker_id,
                                  trace=self._trace_for(batch[0], 1))

    # -- phase 6: status heartbeat ---------------------------------------

    def _publish_status(self, now: float) -> dict:
        jobs, anomalies = self._replay()
        counts: Dict[str, int] = {}
        for v in jobs.values():
            counts[v.state] = counts.get(v.state, 0) + 1
        doc = {"pid": os.getpid(), "t_wall": now,
               "state": "draining" if self._draining else "serving",
               "slots": self.config.slots,
               # Distinct processes: a packed dispatch maps several
               # jobs onto one worker handle.
               "running_workers": len({id(h)
                                       for h in self._procs.values()}),
               "poll_interval_s": self.config.poll_interval_s,
               "counts": counts, "anomalies": len(anomalies)}
        self.store.write_daemon_status(doc)
        return doc

    # -- lifecycle -------------------------------------------------------

    def serve(self, max_seconds: Optional[float] = None) -> int:
        """Poll loop until SIGTERM/SIGINT (or ``max_seconds``, for
        harnesses), then graceful drain. Returns the process exit code
        (``EXIT_PREEMPTED`` after a drain — restart loops treat the
        daemon like any preempted supervised run: start it again and
        it resumes from the journal)."""
        cfg = self.config
        stop = _StopFlag()

        def handler(signum, frame):
            stop.signum = signum  # flag only — drain at the loop top

        prev = {}
        try:
            for s in (signal.SIGTERM, signal.SIGINT):
                prev[s] = signal.signal(s, handler)
        except ValueError:  # not the main thread (tests)
            prev = {}
        t0 = cfg.clock()
        try:
            while stop.signum is None:
                self.step()
                if max_seconds is not None \
                        and cfg.clock() - t0 >= max_seconds:
                    break
                cfg.sleep_fn(cfg.poll_interval_s)
            return self.drain(
                reason=(signal.Signals(stop.signum).name
                        if stop.signum is not None else "max_seconds"))
        finally:
            for s, h in prev.items():
                signal.signal(s, h)

    def drain(self, reason: str = "drain") -> int:
        """Graceful shutdown: stop admitting (pending spool entries are
        rejected with a retry-after), interrupt in-flight workers
        through the supervisor's flag-only signal path, wait for their
        checkpoint flushes, journal every in-flight job's resume state,
        and exit ``EXIT_PREEMPTED``. Queued jobs stay queued — they are
        already durable; the restarted daemon dispatches them."""
        cfg = self.config
        self._draining = True
        self.store.journal.append("daemon_drain", reason=reason)
        now = cfg.clock()
        self._admit(now)  # draining=True -> loud rejections
        for jid in list(self._procs):
            self._interrupt_worker(jid, now)  # handles exist here
        deadline = now + cfg.drain_grace_s
        while self._procs and cfg.clock() < deadline:
            self.step()
            if self._procs:
                cfg.sleep_fn(cfg.poll_interval_s)
        for handle in self._procs.values():  # wedged past the grace
            try:
                handle.kill()
            except OSError:
                pass
        self.step()  # final reconcile: orphan anything SIGKILLed above
        self.store.journal.append("daemon_exit", outcome="drained")
        self._publish_status(cfg.clock())
        self.store.close()
        return EXIT_PREEMPTED
