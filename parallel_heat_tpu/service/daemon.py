"""``heatd``: the long-lived solver-as-a-service daemon.

ROADMAP item 2's serving layer, built so that every crash the chaos
matrix can inject — worker SIGKILL mid-job, daemon SIGKILL between
journal append and dispatch, overload bursts — lands in a state the
journal already describes. The daemon holds **no authoritative state
in memory**: each scheduling pass replays ``journal.jsonl`` through
``store.reduce_journal`` and acts on the derived views, so a restarted
daemon resumes exactly where the journal says the world is. The loop
per :meth:`Heatd.step`:

1. **reconcile** worker exits and liveness: a result record maps an
   exited worker to its journal transition; a dead/silent worker
   (SIGKILL, OOM — no record, stale heartbeat) has its job journaled
   ``orphaned`` within one heartbeat timeout, checkpoint lineage
   untouched;
2. **cancel/deadline** enforcement: queued jobs transition directly;
   running jobs are interrupted through the supervisor's flag-only
   signal path (SIGTERM -> checkpoint flush -> preempted record), with
   a SIGKILL escalation after ``kill_grace_s``;
3. **admit** spool submissions through ``service.admission`` — journal
   ``accepted`` (after the job spec is rename-committed) or
   ``rejected`` with a retry-after hint; the handshake is idempotent
   across a daemon crash at any point;
4. **route failures**: fail-fast ``PermanentFailure`` kinds
   (``unstable``/``stalled``/``drift``/``bad_spec``) quarantine
   immediately;
   everything else is re-admitted under bounded exponential backoff
   until ``quarantine_after`` distinct workers have failed the job;
5. **dispatch** due queued jobs to worker subprocesses (one process
   per attempt — ``service/worker.py`` resumes from the newest
   committed checkpoint generation, so a re-dispatched job continues
   bit-exactly);
6. publish the ``heatd.json`` status heartbeat for probes
   (``tools/monitor.py --daemon``, ``heatd status``).

SIGTERM/SIGINT triggers the graceful drain: stop admitting, interrupt
in-flight workers, wait for their checkpoint flushes, journal each
job's resume state (``requeued``), and exit ``EXIT_PREEMPTED`` — the
restart re-dispatches from the journal.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from parallel_heat_tpu.service.admission import admission_verdict
from parallel_heat_tpu.service.cache import (
    CacheIndex,
    evict_candidates,
    lookup_exact,
    lookup_prefix,
    seed_stem,
)
from parallel_heat_tpu.service.store import (
    FAILFAST_KINDS,
    JobStore,
    JobView,
    reduce_journal,
)
from parallel_heat_tpu.supervisor import EXIT_PREEMPTED
from parallel_heat_tpu.utils.tracing import (
    ENV_PARENT_SPAN_ID,
    ENV_SPAN_ID,
    ENV_TRACE_ID,
    TraceContext,
    dispatch_span_id,
    submit_span_id,
)


@dataclass
class HeatdConfig:
    """Daemon knobs. Time sources and the worker launcher are
    injectable (tests drive the scheduler on a fake clock; the chaos
    harness swaps launchers) — same pattern as
    ``SupervisorPolicy.sleep_fn``."""

    root: str
    # Fleet host identity (service/fleet.py): set by FleetHost on its
    # per-partition daemons so EVERY journal line this daemon appends
    # carries a `host` field — the attribution the federated audit
    # (cross-host double-dispatch) and per-host metrics rows fold on.
    # None = a plain single-host daemon (lines stay host-less).
    host: Optional[str] = None
    # Concurrent worker processes (one job each).
    slots: int = 2
    poll_interval_s: float = 0.25
    # Cadence workers rewrite their liveness heartbeat at, and the
    # staleness threshold past which a silent worker's job is declared
    # orphaned. The timeout must cover several beats: one missed write
    # is scheduling noise, not death.
    worker_heartbeat_s: float = 0.5
    heartbeat_timeout_s: float = 3.0
    # Admission gates (service.admission).
    max_queue_depth: int = 16
    hbm_budget_bytes: Optional[int] = None
    retry_after_s: float = 2.0
    # Poison-job quarantine: after failures on this many DISTINCT
    # workers (fail-fast PermanentFailure kinds quarantine immediately).
    quarantine_after: int = 3
    # Bounded exponential re-admission backoff after a non-fail-fast
    # failure: min(max, base * 2**(failures-1)).
    requeue_backoff_base_s: float = 0.5
    requeue_backoff_max_s: float = 30.0
    # Escalation: SIGTERM -> this grace -> SIGKILL (cancel/deadline/
    # drain paths).
    kill_grace_s: float = 5.0
    drain_grace_s: float = 60.0
    # Ensemble packing (SEMANTICS.md "Ensemble"): coalesce compatible
    # due FRESH jobs (identical semantic config + supervisor knobs, no
    # deadline, no fault plan, attempt 0, never requeued) into ONE
    # packed worker running them as a batched ensemble program, up to
    # pack_max members per dispatch. The pack consumes one slot. Each
    # member's HBM was already counted by the admission gate at
    # acceptance, so a pack can never exceed what admission allowed.
    # The worker itself re-verifies runtime packability (the bitwise
    # member-parity contract needs the resolved execution path, which
    # requires the accelerator runtime the daemon deliberately never
    # initializes) and demotes the whole pack to solo requeues when it
    # does not hold — packing is a fast path, never a semantic change.
    pack_jobs: bool = False
    pack_max: int = 16
    # Coalescing dwell: a packable job with no companion yet is held
    # back from solo dispatch until it has been queued this long, so a
    # burst of compatible submissions lands in one packed dispatch
    # instead of the first arrival stealing a slot solo. 0 = dispatch
    # greedily (packing still coalesces whatever is queued together).
    pack_wait_s: float = 0.0
    # Content-addressed result cache (SEMANTICS.md "Cache soundness").
    # On by default: an EXACT hit — a completed, finite-verified
    # lineage with the identical semantic-spec + stepping key — serves
    # the verdict in O(1) with zero worker spawns and zero HBM priced;
    # a PREFIX hit seeds the new job's checkpoint stem with the
    # newest admissible donor generation so the worker resumes instead
    # of solving from step 0 (bitwise a from-scratch run, by the
    # resume-parity contract). Specs carrying fault plans never hit
    # and never populate the cache.
    cache_results: bool = True
    # LRU eviction budgets (None = unbounded); in-flight prefix donors
    # are pinned past both.
    cache_max_bytes: Optional[int] = None
    cache_max_entries: Optional[int] = None
    # Extra environment for worker subprocesses (the chaos matrix pins
    # JAX_PLATFORMS=cpu here); inherits os.environ otherwise.
    worker_env: Optional[dict] = None
    clock: Callable[[], float] = field(default=time.time)
    sleep_fn: Callable[[float], None] = field(default=time.sleep)
    # Injectable worker launcher (tests run jobs inline): called as
    # launcher(job_id=, worker_id=, attempt=, deadline_t=) and must
    # return a Popen-shaped handle (poll/terminate/kill/pid). None =
    # spawn `python -m parallel_heat_tpu.service.worker`.
    launcher: Optional[Callable] = None
    # CHAOS HARNESS ONLY: SIGKILL this daemon immediately after
    # journaling the Nth `accepted` event — the exact
    # between-append-and-dispatch crash window the durability contract
    # is certified against (tools/chaos_matrix.py `svc_daemon_restart`).
    chaos_kill_after_accept: Optional[int] = None
    # CHAOS HARNESS ONLY: SIGKILL this daemon on the Nth completed
    # job's cache admission, AFTER the result + `completed` journal
    # line commit but BEFORE the cache-index append — the window the
    # cache durability contract is certified against
    # (`svc_cache_crash`: entry lost, job NOT lost, next identical
    # submit re-solves; torn bytes are never servable).
    chaos_kill_before_cache_put: Optional[int] = None

    def validate(self) -> "HeatdConfig":
        if self.slots < 1:
            raise ValueError(f"slots must be >= 1, got {self.slots}")
        if self.max_queue_depth < 1:
            raise ValueError(f"max_queue_depth must be >= 1, got "
                             f"{self.max_queue_depth}")
        if self.quarantine_after < 1:
            raise ValueError(f"quarantine_after must be >= 1, got "
                             f"{self.quarantine_after}")
        if self.heartbeat_timeout_s < self.worker_heartbeat_s:
            raise ValueError(
                f"heartbeat_timeout_s ({self.heartbeat_timeout_s}) must "
                f"be >= worker_heartbeat_s ({self.worker_heartbeat_s}) "
                f"— a timeout shorter than the write cadence declares "
                f"every live worker dead")
        if self.pack_max < 2:
            raise ValueError(f"pack_max must be >= 2, got "
                             f"{self.pack_max}")
        if self.cache_max_bytes is not None and self.cache_max_bytes < 0:
            raise ValueError(f"cache_max_bytes must be >= 0, got "
                             f"{self.cache_max_bytes}")
        if self.cache_max_entries is not None \
                and self.cache_max_entries < 0:
            raise ValueError(f"cache_max_entries must be >= 0, got "
                             f"{self.cache_max_entries}")
        return self


class _StopFlag:
    __slots__ = ("signum",)

    def __init__(self):
        self.signum: Optional[int] = None


class Heatd:
    """One daemon instance bound to one queue root. Single-threaded by
    design: every mutation of queue state is a journal append from
    :meth:`step`, so there is exactly one writer and no lock to get
    wrong. Construct, then either call :meth:`serve` (the CLI path:
    poll loop + signal-driven drain) or drive :meth:`step` directly
    (tests and the chaos matrix)."""

    def __init__(self, config: HeatdConfig):
        self.config = config.validate()
        self.store = JobStore(config.root)
        self._procs: Dict[str, object] = {}  # job_id -> worker handle
        self._term_sent: Dict[str, float] = {}  # job_id -> SIGTERM t
        # Adopted jobs (no Popen handle) interrupted by heartbeat pid:
        # job_id -> pid, for the SIGKILL escalation.
        self._term_pid: Dict[str, int] = {}
        self._accepts = 0
        self._draining = False
        # job_id -> spec-derived pack key (see _spec_pack_key).
        self._pack_key_cache: Dict[str, object] = {}
        # Content-addressed result cache (None = disabled). Pins map
        # prefix-resumed job -> donor cache key: the donor is exempt
        # from eviction while the job is non-terminal.
        self.cache: Optional[CacheIndex] = (
            CacheIndex(config.root) if config.cache_results else None)
        self._cache_pins: Dict[str, str] = {}
        self._cache_puts = 0
        # job_id -> committed spec config dict (None = cache-exempt),
        # same memoization rationale as _pack_key_cache: committed
        # specs are immutable and the dispatch-time cache sweep
        # consults every queued job on every poll tick.
        self._cache_spec_cache: Dict[str, Optional[dict]] = {}
        # job_id -> cache-index version at which its exact lookup
        # last MISSED: while the index hasn't grown, re-hashing the
        # key and re-scanning the entries every tick is wasted work —
        # a miss is a miss until a new entry lands.
        self._cache_miss_memo: Dict[str, int] = {}
        if self.cache is not None:
            # Crash residue from the two commit windows (payload
            # committed but never indexed; evicted but never deleted)
            # is unreferenced garbage — reap it at boot.
            self.cache.sweep_orphans()
        # Incremental journal fold: byte offset consumed so far + the
        # folded state. Equivalent to store.replay() by the reducer's
        # fold law, but each pass parses only the appended events — a
        # long-lived daemon must not re-read its whole history 5x per
        # poll tick.
        self._journal_offset = 0
        self._jobs: Dict[str, JobView] = {}
        self._anomalies: list = []
        if self.config.host is not None:
            # Federated identity: stamp the host on every append (the
            # journal envelope, not per call site).
            self.store.journal.extra = {"host": self.config.host}
        self.store.journal.append("daemon_start", pid=os.getpid(),
                                  slots=self.config.slots)

    def _replay(self):
        """Fold journal bytes appended since the last call into the
        cached views; returns ``(jobs, anomalies)`` — the same answer
        ``store.replay()`` gives, O(new events) per pass. Only whole
        lines are consumed: a torn tail (this read racing an append)
        stays unconsumed and is re-read complete next pass."""
        try:
            with open(self.store.journal_path, "rb") as f:
                f.seek(self._journal_offset)
                data = f.read()
        except OSError:
            return self._jobs, self._anomalies
        end = data.rfind(b"\n")
        if end >= 0:
            self._journal_offset += end + 1
            events = []
            for line in data[:end + 1].split(b"\n"):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "event" in rec:
                    events.append(rec)
            reduce_journal(events, state=(self._jobs, self._anomalies))
        return self._jobs, self._anomalies

    # -- scheduling pass -------------------------------------------------

    def step(self, now: Optional[float] = None) -> dict:
        """One scheduling pass; returns a state-count summary (tests
        and the status heartbeat read it)."""
        cfg = self.config
        now = cfg.clock() if now is None else now
        self._reconcile(now)
        self._cancels_and_deadlines(now)
        self._admit(now)
        self._route_failed(now)
        if not self._draining:
            self._dispatch(now)
        if self._cache_pins:
            # Release terminal jobs' donor pins every pass — under
            # unbounded budgets the evict pass (the other prune site)
            # early-returns, and a long daemon must not grow a pin
            # per prefix-resumed job forever.
            jobs, _ = self._replay()
            self._cache_pins = {jid: key for jid, key
                                in self._cache_pins.items()
                                if jid in jobs
                                and not jobs[jid].terminal}
        return self._publish_status(now)

    # -- phase 1: worker exits / liveness --------------------------------

    def _reconcile(self, now: float) -> None:
        jobs, _ = self._replay()
        for jid, v in jobs.items():
            if v.state != "running":
                continue
            handle = self._procs.get(jid)
            if handle is not None:
                rc = handle.poll()
                if rc is None:
                    continue  # still running
                self._procs.pop(jid, None)
                self._term_sent.pop(jid, None)
                # Read the outcome record only AFTER the exit is
                # observed: a live worker commits its record before
                # exiting, so post-exit is the one moment the read
                # cannot race the rename (and inline test launchers
                # produce the record during poll() itself).
                rec = self.store.read_result(jid, v.attempts)
                self._classify_exit(v, rc, rec, now)
                continue
            rec = self.store.read_result(jid, v.attempts)
            if rec is not None:
                # Adopted job (daemon restarted after dispatch): the
                # worker finished and its rename-committed record is
                # the outcome — journal it exactly once.
                self._term_sent.pop(jid, None)
                self._term_pid.pop(jid, None)
                self._classify_exit(v, None, rec, now)
            else:
                # Adopted job, no outcome record: judge liveness by the
                # worker's heartbeat. A worker that has NEVER beaten
                # gets one heartbeat timeout of grace from its
                # dispatch stamp — a freshly-spawned worker is still
                # importing its runtime before the first beat lands,
                # and orphaning it would race a live process (a second
                # worker against the stem lock). After the grace, a
                # missing/stale beat or a dead pid is a corpse; its
                # job is orphaned — the checkpoint lineage under
                # ck/<job>/ is untouched, so the re-dispatched attempt
                # resumes bit-exactly.
                hb = self.store.read_worker_hb(v.worker or "")
                if hb is None and v.last_dispatch_t is not None \
                        and now - v.last_dispatch_t \
                        <= self.config.heartbeat_timeout_s:
                    continue
                if not self._worker_alive(hb, now):
                    self.store.journal.append(
                        "orphaned", job_id=jid, worker=v.worker,
                        attempt=v.attempts,
                        reason=("worker heartbeat stale/dead "
                                "(no exit record)"))

    def _worker_alive(self, hb: Optional[dict], now: float) -> bool:
        if hb is None:
            return False
        pid = hb.get("pid")
        if isinstance(pid, int) and pid > 0:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                return False
            except OSError:
                pass  # EPERM: exists
        t = hb.get("t_wall")
        return (isinstance(t, (int, float))
                and now - t <= self.config.heartbeat_timeout_s)

    def _classify_exit(self, v: JobView, rc, rec, now: float) -> None:
        j = self.store.journal
        jid = v.job_id
        outcome = (rec or {}).get("outcome")
        if outcome == "completed":
            j.append("completed", job_id=jid, worker=v.worker,
                     attempt=v.attempts,
                     steps_done=rec.get("steps_done"),
                     wall_s=rec.get("wall_s"))
            # Cache admission strictly AFTER the result + journal
            # commit: a crash here loses the cache ENTRY (the next
            # identical submit re-solves), never the job and never a
            # half-committed payload a reader could serve.
            self._cache_put(v, rec)
        elif outcome == "permanent_failure":
            j.append("worker_failed", job_id=jid, worker=v.worker,
                     attempt=v.attempts, exit_code=rc,
                     kind=rec.get("kind") or "unknown",
                     diagnosis=rec.get("diagnosis"))
        elif outcome == "preempted":
            reason = rec.get("reason")
            if v.cancel_requested:
                j.append("cancelled", job_id=jid, worker=v.worker,
                         attempt=v.attempts,
                         steps_done=rec.get("steps_done"))
                self.store.clear_cancel(jid)
            elif reason == "deadline" or (v.deadline_t is not None
                                          and now >= v.deadline_t):
                j.append("deadline_expired", job_id=jid,
                         worker=v.worker, attempt=v.attempts,
                         steps_done=rec.get("steps_done"))
            else:
                # Drain / external preemption: the flushed checkpoint
                # IS the resume state — journal it so a restart
                # re-dispatches from exactly here.
                j.append("requeued", job_id=jid, reason="preempted",
                         not_before=now, attempt=v.attempts,
                         steps_done=rec.get("steps_done"))
        else:
            # No record (SIGKILL/OOM before the rename landed) or an
            # unreadable one: a true orphan.
            j.append("orphaned", job_id=jid, worker=v.worker,
                     attempt=v.attempts,
                     reason=f"worker exited rc={rc} without an outcome "
                            f"record")

    # -- phase 2: cancellation + deadlines -------------------------------

    def _cancels_and_deadlines(self, now: float) -> None:
        cfg = self.config
        jobs, _ = self._replay()
        j = self.store.journal
        for jid in self.store.cancel_requests():
            v = jobs.get(jid)
            if v is None or v.terminal or v.state == "rejected":
                self.store.clear_cancel(jid)
                continue
            if not v.cancel_requested:
                j.append("cancel_requested", job_id=jid)
                v.cancel_requested = True
            if v.state in ("queued", "failed"):
                j.append("cancelled", job_id=jid, attempt=v.attempts)
                self.store.clear_cancel(jid)
            elif v.state == "running":
                self._interrupt_worker(jid, now, worker=v.worker)
        for jid, v in jobs.items():
            if v.terminal or v.deadline_t is None or now < v.deadline_t:
                continue
            if v.state in ("queued", "failed"):
                j.append("deadline_expired", job_id=jid,
                         attempt=v.attempts,
                         reason=f"deadline passed while {v.state}")
            elif v.state == "running":
                # The worker's own interrupt hook normally beats this;
                # the daemon-side SIGTERM (then SIGKILL after the
                # grace) is the backstop for a wedged worker.
                self._interrupt_worker(jid, now, worker=v.worker)
        # Escalation: a worker that ignored SIGTERM past the grace gets
        # the uncatchable one; reconcile then orphans+requeues its job.
        for jid, t0 in list(self._term_sent.items()):
            v = jobs.get(jid)
            if v is None or v.state != "running":
                self._term_sent.pop(jid, None)
                self._term_pid.pop(jid, None)
                continue
            if now - t0 <= cfg.kill_grace_s:
                continue
            handle = self._procs.get(jid)
            if handle is not None:
                if handle.poll() is None:
                    handle.kill()
            elif jid in self._term_pid:
                try:
                    os.kill(self._term_pid[jid], signal.SIGKILL)
                except OSError:
                    pass

    def _interrupt_worker(self, jid: str, now: float,
                          worker: Optional[str] = None) -> None:
        if jid in self._term_sent:
            return
        handle = self._procs.get(jid)
        if handle is not None:
            try:
                handle.terminate()
            except OSError:
                pass
            self._term_sent[jid] = now
            return
        # Adopted job (daemon restarted after dispatch): no handle,
        # but the worker's heartbeat names its pid — cancellation and
        # deadlines must reach it all the same.
        hb = self.store.read_worker_hb(worker or "")
        pid = (hb or {}).get("pid")
        if isinstance(pid, int) and pid > 0:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                return
            self._term_sent[jid] = now
            self._term_pid[jid] = pid

    # -- phase 3: admission ----------------------------------------------

    def _admit(self, now: float) -> None:
        cfg = self.config
        j = self.store.journal
        jobs, _ = self._replay()
        for jid in self.store.iter_spool():
            if jid in jobs:
                # Crash between the journal append and the spool unlink
                # on a previous pass: finish the handshake idempotently
                # — never a second accepted/rejected line.
                self.store.drop_spool(jid)
                continue
            spec = self.store.read_spool(jid)
            if spec is None:
                continue  # torn/foreign spool entry: leave for inspection
            if self.cache is not None and not self._draining \
                    and not self._cache_exempt(spec):
                hit = lookup_exact(self.cache.entries(), spec.config)
                if hit is not None:
                    # Exact hit at the door: accept with ZERO HBM
                    # priced (no worker will run) and serve the
                    # verdict in O(1). Spec commit still precedes the
                    # accepted line (the idempotent-handshake order);
                    # queue-depth/HBM gates deliberately do not apply
                    # — an instant completion consumes neither.
                    self.store.commit_job_record(spec)
                    recs = [j.append(
                        "accepted", job_id=jid,
                        deadline_s=spec.deadline_s, hbm_bytes=0,
                        submitted_t=spec.submitted_t,
                        trace_id=(spec.trace or {}).get("trace_id"),
                        **({"route": spec.route} if spec.route
                           else {}))]
                    self._fold(recs)
                    self._cache_serve(
                        jid, hit,
                        trace_id=(spec.trace or {}).get("trace_id"))
                    # A vanished payload leaves the job accepted and
                    # queued — dispatch runs it like any other.
                    self.store.drop_spool(jid)
                    continue
            active = [v for v in jobs.values()
                      if not v.terminal and v.state != "rejected"]
            ok, reason, retry_after, est = admission_verdict(
                spec.config, len(active),
                sum(v.hbm_bytes for v in active),
                cfg.max_queue_depth, cfg.hbm_budget_bytes,
                cfg.retry_after_s, cfg.slots, draining=self._draining)
            if not ok:
                rec = j.append("rejected", job_id=jid, reason=reason,
                               retry_after_s=retry_after)
                # Fold by hand like the accepted branch below: a later
                # acceptance in this same pass bumps the offset past
                # these bytes, and an unfolded rejection would both
                # undercount forever and let a re-used id through the
                # `jid in jobs` dedupe.
                self._fold([rec])
                self.store.drop_spool(jid)
                continue
            # Durable spec FIRST, then the accepted line: a crash
            # between the two replays the handshake from the spool copy
            # (record rewrite is idempotent), so `accepted` in the
            # journal always implies a loadable spec on disk.
            self.store.commit_job_record(spec)
            rec = j.append("accepted", job_id=jid,
                           deadline_s=spec.deadline_s, hbm_bytes=est,
                           submitted_t=spec.submitted_t,
                           trace_id=(spec.trace or {}).get("trace_id"),
                           **({"route": spec.route} if spec.route
                              else {}))
            # Fold the acceptance into the cached view by hand so the
            # NEXT spool entry's gate sees this job as active without
            # re-reading the journal (the incremental fold will skip
            # these bytes — they are consumed here).
            self._fold([rec])
            self._accepts += 1
            if cfg.chaos_kill_after_accept is not None \
                    and self._accepts >= cfg.chaos_kill_after_accept:
                # Chaos window: die BETWEEN the journal append and the
                # dispatch (and even before the spool unlink) — restart
                # must recover the job from the journal alone.
                os.kill(os.getpid(), signal.SIGKILL)
            self.store.drop_spool(jid)

    # -- phase 4: failure routing ----------------------------------------

    def _route_failed(self, now: float) -> None:
        cfg = self.config
        jobs, _ = self._replay()
        j = self.store.journal
        for jid, v in jobs.items():
            if v.state != "failed":
                continue
            last_kind = v.failures[-1][1] if v.failures else "unknown"
            if last_kind in FAILFAST_KINDS:
                # Deterministic verdicts: replaying bad physics on a
                # different worker replays the same physics.
                j.append("quarantined", job_id=jid, kind=last_kind,
                         diagnosis=v.diagnosis,
                         distinct_workers=v.distinct_failed_workers,
                         reason=f"fail-fast permanent failure "
                                f"(kind={last_kind})")
            elif v.distinct_failed_workers >= cfg.quarantine_after:
                j.append("quarantined", job_id=jid, kind=last_kind,
                         diagnosis=v.diagnosis,
                         distinct_workers=v.distinct_failed_workers,
                         reason=f"failed on "
                                f"{v.distinct_failed_workers} distinct "
                                f"workers (poison-job threshold "
                                f"{cfg.quarantine_after})")
            else:
                n = len(v.failures)
                delay = min(cfg.requeue_backoff_max_s,
                            cfg.requeue_backoff_base_s * 2 ** (n - 1))
                j.append("requeued", job_id=jid, reason=last_kind,
                         backoff_s=delay, not_before=now + delay,
                         attempt=v.attempts)

    # -- content-addressed result cache (SEMANTICS.md "Cache
    # soundness"): exact hits serve in O(1), prefix hits seed the
    # job's checkpoint stem so the worker resumes instead of solving
    # from step 0. ---------------------------------------------------------

    def _fold(self, recs) -> None:
        """Fold freshly-appended journal records into the cached views
        and advance the incremental-fold offset past them (the appends
        landed at the tail; the next _replay must not double-fold)."""
        self._journal_offset = os.path.getsize(self.store.journal_path)
        reduce_journal(recs, state=(self._jobs, self._anomalies))

    @staticmethod
    def _cache_exempt(spec) -> bool:
        """Specs the cache never serves and never admits: fault plans
        are per-run chaos machinery, not content."""
        return spec is None or spec.faults is not None

    def _cache_put(self, v: JobView, rec: dict) -> None:
        """Admit a completed job's lineage. Declines quietly for
        fault-injected specs, cache-served completions (their lineage
        IS the entry's payload), sharded layouts, or lineages whose
        newest generation is not the committed finite result."""
        if self.cache is None or rec.get("cache") is not None:
            return
        try:
            spec = self.store.load_spec(v.job_id)
        except (OSError, ValueError):
            return
        if self._cache_exempt(spec) or rec.get("steps_done") is None:
            return
        self._cache_puts += 1
        cfg = self.config
        if cfg.chaos_kill_before_cache_put is not None \
                and self._cache_puts >= cfg.chaos_kill_before_cache_put:
            # Chaos window: the job's `completed` line is durable, the
            # cache index knows nothing — restart must re-solve the
            # next identical submit, never serve torn bytes.
            os.kill(os.getpid(), signal.SIGKILL)
        entry = self.cache.put(
            spec.config, self.store.checkpoint_stem(v.job_id),
            job_id=v.job_id, attempt=v.attempts,
            steps_done=int(rec["steps_done"]),
            converged=rec.get("converged"))
        if entry is not None:
            self._cache_evict_pass()

    def _cache_evict_pass(self) -> None:
        """LRU eviction to the configured budgets; donors of in-flight
        prefix resumes are pinned (their payload generation is already
        hardlinked into the job's stem, but the pin keeps the entry —
        and its LRU/provenance state — stable until the job lands)."""
        cfg = self.config
        if cfg.cache_max_bytes is None and cfg.cache_max_entries is None:
            return
        jobs, _ = self._replay()
        self._cache_pins = {jid: key for jid, key
                            in self._cache_pins.items()
                            if jid in jobs and not jobs[jid].terminal}
        for key in evict_candidates(self.cache.entries(),
                                    cfg.cache_max_bytes,
                                    cfg.cache_max_entries,
                                    pinned=self._cache_pins.values()):
            self.cache.evict(key)

    def _cache_serve(self, jid: str, hit, trace_id=None) -> bool:
        """Complete ``jid`` in O(1) from an exact/converged-dominance
        hit: link the payload's final generation into the job's own
        checkpoint lineage (the served job is indistinguishable on
        disk from one that ran), rename-commit an attempt-0 result
        record carrying the provenance, and journal ``cache_hit`` +
        ``completed``. Returns False when the payload went missing —
        the caller falls through to a real solve."""
        entry, kind = hit
        steps_done = int(entry.get("steps_done") or 0)
        linked = seed_stem(entry, steps_done,
                           self.store.checkpoint_stem(jid))
        if linked is None:
            return False
        prov = {"hit": kind, "key": entry["key"],
                "donor": entry.get("job_id"),
                "generation_step": steps_done}
        self.store.write_result(jid, 0, {
            "outcome": "completed", "worker": None, "attempt": 0,
            "job_id": jid, "steps_done": steps_done, "wall_s": 0.0,
            "cache": prov, "last_checkpoint": linked,
            "converged": entry.get("converged")})
        j = self.store.journal
        recs = [
            j.append("cache_hit", job_id=jid, key=entry["key"],
                     kind=kind, donor=entry.get("job_id"),
                     generation_step=steps_done,
                     steps_saved=steps_done,
                     bytes_saved=entry.get("bytes"),
                     trace_id=trace_id),
            j.append("completed", job_id=jid, worker=None, attempt=0,
                     steps_done=steps_done, cache=prov),
        ]
        self.cache.touch(entry["key"], kind="exact")
        self._fold(recs)
        return True

    def _cacheable_config(self, job_id: str) -> Optional[dict]:
        """The committed spec's config dict, or None for a job the
        cache must ignore (fault plan, unloadable record) — memoized
        per job id: committed specs are immutable and the dispatch
        sweep asks on every poll tick."""
        if job_id in self._cache_spec_cache:
            return self._cache_spec_cache[job_id]
        try:
            spec = self.store.load_spec(job_id)
        except (OSError, ValueError):
            return None  # not cached: the record may still be landing
        cfg = None if self._cache_exempt(spec) else spec.config
        self._cache_spec_cache[job_id] = cfg
        if len(self._cache_spec_cache) > 4096:
            self._cache_spec_cache.pop(
                next(iter(self._cache_spec_cache)))
        return cfg

    def _cache_serve_queued(self, due, now: float):
        """Dispatch-time exact-hit sweep over due queued jobs (covers
        specs admitted BEFORE their twin completed — the burst case
        packing coalesces and admission-time lookup cannot see);
        returns the due list minus the served jobs."""
        if self.cache is None:
            return due
        entries = self.cache.entries()
        if not entries:
            return due
        version = self.cache.version
        out = []
        for v in due:
            if v.attempts > 0 or v.requeues > 0 or v.cancel_requested:
                out.append(v)
                continue
            if self._cache_miss_memo.get(v.job_id) == version:
                out.append(v)  # nothing new to hit since last tick
                continue
            config = self._cacheable_config(v.job_id)
            hit = (lookup_exact(entries, config)
                   if config is not None else None)
            if hit is None or not self._cache_serve(
                    v.job_id, hit, trace_id=v.trace_id):
                self._cache_miss_memo[v.job_id] = version
                if len(self._cache_miss_memo) > 4096:
                    self._cache_miss_memo.pop(
                        next(iter(self._cache_miss_memo)))
                out.append(v)
        return out

    def _maybe_prefix_seed(self, v: JobView, now: float) -> None:
        """Before a FRESH job's first solo dispatch: seed its
        checkpoint stem from the newest admissible donor generation
        and journal ``cache_prefix`` — the worker's ordinary
        resume-before-run then continues from there, bitwise a
        from-scratch solve (the resume-parity contract). A missing
        payload (raced eviction) just means no seed: the job solves
        from step 0, correct either way."""
        if self.cache is None or v.attempts > 0 or v.requeues > 0:
            return
        config = self._cacheable_config(v.job_id)
        if config is None:
            return
        found = lookup_prefix(self.cache.entries(), config)
        if found is None:
            return
        entry, gen_step = found
        marker = {"key": entry["key"], "donor": entry.get("job_id"),
                  "generation_step": int(gen_step),
                  "job_id": v.job_id}
        if seed_stem(entry, gen_step,
                     self.store.checkpoint_stem(v.job_id),
                     marker=marker) is None:
            return
        rec = self.store.journal.append(
            "cache_prefix", job_id=v.job_id, key=entry["key"],
            donor=entry.get("job_id"), generation_step=int(gen_step),
            steps_saved=int(gen_step), trace_id=v.trace_id)
        self.cache.touch(entry["key"], kind="prefix")
        self._cache_pins[v.job_id] = entry["key"]
        self._fold([rec])

    # -- phase 5: dispatch -----------------------------------------------

    def _spec_pack_key(self, job_id: str):
        """The SPEC-derived half of the pack key (or None for a spec
        that can never pack), cached per job id — committed specs are
        immutable, and _dispatch consults the key for every queued job
        on every poll tick, so re-reading the record each time would
        turn a dwelling burst into O(jobs) disk reads per tick."""
        if job_id in self._pack_key_cache:
            return self._pack_key_cache[job_id]
        try:
            spec = self.store.load_spec(job_id)
        except (OSError, ValueError):
            return None  # not cached: the record may still be landing
        if spec.faults is not None or spec.deadline_s is not None:
            key = None
        else:
            # Every knob worker.execute_pack builds the SHARED
            # SupervisorPolicy from must be in the key — a member
            # running under another job's settings would be a silent
            # semantics change.
            key = (json.dumps(spec.config, sort_keys=True),
                   spec.checkpoint_every, spec.guard_interval,
                   spec.max_retries, spec.backoff_base_s)
        self._pack_key_cache[job_id] = key
        if len(self._pack_key_cache) > 4096:  # bound a long daemon's map
            self._pack_key_cache.pop(next(iter(self._pack_key_cache)))
        return key

    def _pack_key(self, v: JobView):
        """Compatibility key for ensemble packing, or None when this
        job must run solo. FRESH jobs only (attempt 0, never requeued):
        a member with history has checkpoint lineage or per-attempt
        state the batched fresh-start program would ignore. The key is
        the full semantic config (byte-equal JSON) plus the supervisor
        knobs the packed run shares; deadlines and fault plans are
        per-job machinery the pack deliberately refuses."""
        if v.attempts > 0 or v.requeues > 0 or v.cancel_requested \
                or v.deadline_t is not None:
            return None
        return self._spec_pack_key(v.job_id)

    def _dispatch(self, now: float) -> None:
        cfg = self.config
        jobs, _ = self._replay()
        # Slot accounting counts WORKERS, not jobs: a packed dispatch
        # runs many jobs in one process and consumes one slot.
        running = len({v.worker for v in jobs.values()
                       if v.state == "running" and v.worker})
        due = sorted((v for v in jobs.values()
                      if v.state == "queued" and v.not_before <= now),
                     key=lambda v: (v.accepted_t or 0.0, v.job_id))
        # Exact-hit sweep first: a queued twin of a job that completed
        # since admission serves in O(1) instead of taking a slot.
        due = self._cache_serve_queued(due, now)
        j = self.store.journal
        packed: set = set()
        if cfg.pack_jobs and len(due) > 1:
            groups: Dict[object, list] = {}
            for v in due:
                key = self._pack_key(v)
                if key is not None:
                    groups.setdefault(key, []).append(v)
            for key in sorted(groups, key=str):
                members = groups[key]
                while len(members) >= 2 and running < cfg.slots:
                    batch = members[:cfg.pack_max]
                    members = members[len(batch):]
                    if len(batch) < 2:
                        break
                    leader = batch[0]
                    wid = f"w-{leader.job_id}-a001-p{len(batch):03d}"
                    # Journal every member BEFORE spawn (the solo
                    # ordering rule): a crash in between leaves
                    # dispatched jobs with no live worker — reconcile
                    # orphans and requeues them, and requeued members
                    # are no longer fresh, so the retry runs solo.
                    for v in batch:
                        j.append("dispatched", job_id=v.job_id,
                                 worker=wid, attempt=v.attempts + 1,
                                 pack=leader.job_id,
                                 pack_size=len(batch),
                                 trace_id=v.trace_id)
                    try:
                        handle = self._launch_pack(batch, wid)
                    except OSError as e:
                        for v in batch:
                            j.append("orphaned", job_id=v.job_id,
                                     worker=wid, attempt=v.attempts + 1,
                                     reason=f"worker spawn failed: {e}")
                        continue
                    for v in batch:
                        self._procs[v.job_id] = handle
                        packed.add(v.job_id)
                    running += 1
        for v in due:
            if v.job_id in packed:
                continue
            if cfg.pack_jobs and cfg.pack_wait_s > 0 \
                    and v.accepted_t is not None \
                    and now - v.accepted_t < cfg.pack_wait_s \
                    and self._pack_key(v) is not None:
                # Coalescing dwell: hold a lone packable job briefly —
                # a compatible companion may be right behind it.
                continue
            if running >= cfg.slots:
                break
            attempt = v.attempts + 1
            # Deterministic worker id (job + attempt): replayable after
            # a daemon restart, and distinct per attempt so the
            # poison-job classifier's distinct-worker count is exactly
            # the distinct-attempt count.
            wid = f"w-{v.job_id}-a{attempt:03d}"
            # Prefix seed BEFORE the dispatch line: the seeded
            # generation + `cache_prefix` line are durable by the time
            # the journal says a worker may be running, so a crash
            # anywhere re-dispatches with the same resume point.
            self._maybe_prefix_seed(v, now)
            # Journal BEFORE spawn: a crash in between leaves a
            # `dispatched` job with no live worker — the reconcile
            # pass orphans and requeues it. The opposite order could
            # run a worker the journal knows nothing about (a double
            # execution after restart).
            j.append("dispatched", job_id=v.job_id, worker=wid,
                     attempt=attempt, trace_id=v.trace_id)
            try:
                handle = self._launch(v, wid, attempt)
            except OSError as e:
                j.append("orphaned", job_id=v.job_id, worker=wid,
                         attempt=attempt,
                         reason=f"worker spawn failed: {e}")
                continue
            self._procs[v.job_id] = handle
            running += 1

    def _spawn_worker(self, job_args, worker_id: str,
                      trace: Optional[TraceContext] = None):
        """Shared subprocess plumbing for solo AND packed dispatches
        (one site to evolve env/log handling): spawn
        ``python -m parallel_heat_tpu.service.worker`` with
        ``job_args`` + the common flags, stdout/stderr to the worker
        log. ``trace`` (the dispatch span context) rides the
        environment — the worker's telemetry sink inherits it, so the
        run's envelope joins the submit's trace without a flag."""
        cfg = self.config
        argv = [sys.executable, "-m", "parallel_heat_tpu.service.worker",
                "--root", self.store.root, *job_args,
                "--worker", worker_id,
                "--hb-interval", str(cfg.worker_heartbeat_s)]
        env = dict(os.environ)
        # Always set or CLEAR the trace variables: the daemon's own
        # environment may carry foreign HEATTRACE_* values (started by
        # a traced harness), and an untraced job's worker inheriting
        # them would stamp its whole stream into an unrelated trace.
        for k in (ENV_TRACE_ID, ENV_SPAN_ID, ENV_PARENT_SPAN_ID):
            env.pop(k, None)
        if trace is not None:
            env.update(trace.to_env())
        # The worker must import this package regardless of the
        # daemon's cwd (the CLI may be launched from anywhere).
        import parallel_heat_tpu

        pkg_root = os.path.dirname(
            os.path.dirname(os.path.abspath(parallel_heat_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH")
            else "")
        env.update(cfg.worker_env or {})
        log = open(self.store.worker_log_path(worker_id), "ab")
        try:
            return subprocess.Popen(argv, stdout=log,
                                    stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()  # Popen holds its own duplicate

    def _trace_for(self, v: JobView, attempt: int
                   ) -> Optional[TraceContext]:
        """The dispatch span context this attempt inherits: the job's
        journaled trace id with the deterministic dispatch span as the
        current hop (parent = the client's submit span). None for
        untraced (pre-trace) jobs."""
        if v.trace_id is None:
            return None
        return TraceContext(v.trace_id,
                            dispatch_span_id(v.job_id, attempt),
                            submit_span_id(v.job_id))

    def _launch(self, v: JobView, worker_id: str, attempt: int):
        cfg = self.config
        if cfg.launcher is not None:
            return cfg.launcher(job_id=v.job_id, worker_id=worker_id,
                                attempt=attempt, deadline_t=v.deadline_t)
        job_args = ["--job", v.job_id, "--attempt", str(attempt)]
        if v.deadline_t is not None:
            job_args += ["--deadline-t", repr(v.deadline_t)]
        return self._spawn_worker(job_args, worker_id,
                                  trace=self._trace_for(v, attempt))

    def _launch_pack(self, batch, worker_id: str):
        """Spawn ONE worker process running ``batch`` as a packed
        ensemble dispatch (``service/worker.py --jobs``). Injectable
        like the solo launcher: a configured ``launcher`` receives the
        extra ``job_ids`` keyword (inline test harnesses run
        ``worker.execute_pack`` directly)."""
        cfg = self.config
        job_ids = [v.job_id for v in batch]
        if cfg.launcher is not None:
            return cfg.launcher(job_id=job_ids[0], worker_id=worker_id,
                                attempt=1, deadline_t=None,
                                job_ids=job_ids)
        # One env can carry one context: the pack's shared stream
        # traces under the LEADER's trace (per-member journal lines
        # keep each member's own trace_id; heattrace renders member
        # lanes from the stream's `member` fields).
        return self._spawn_worker(["--jobs", ",".join(job_ids)],
                                  worker_id,
                                  trace=self._trace_for(batch[0], 1))

    # -- phase 6: status heartbeat ---------------------------------------

    def _publish_status(self, now: float) -> dict:
        jobs, anomalies = self._replay()
        counts: Dict[str, int] = {}
        for v in jobs.values():
            counts[v.state] = counts.get(v.state, 0) + 1
        doc = {"pid": os.getpid(), "t_wall": now,
               "state": "draining" if self._draining else "serving",
               "slots": self.config.slots,
               # Distinct processes: a packed dispatch maps several
               # jobs onto one worker handle.
               "running_workers": len({id(h)
                                       for h in self._procs.values()}),
               "poll_interval_s": self.config.poll_interval_s,
               "counts": counts, "anomalies": len(anomalies)}
        if self.cache is not None:
            entries = self.cache.entries()
            doc["cache"] = {
                "entries": len(entries),
                "bytes": sum(e.get("bytes") or 0
                             for e in entries.values()),
                "hits": sum(e.get("hits") or 0
                            for e in entries.values()),
                "prefix_hits": sum(e.get("prefix_hits") or 0
                                   for e in entries.values())}
        self.store.write_daemon_status(doc)
        return doc

    # -- lifecycle -------------------------------------------------------

    def serve(self, max_seconds: Optional[float] = None) -> int:
        """Poll loop until SIGTERM/SIGINT (or ``max_seconds``, for
        harnesses), then graceful drain. Returns the process exit code
        (``EXIT_PREEMPTED`` after a drain — restart loops treat the
        daemon like any preempted supervised run: start it again and
        it resumes from the journal)."""
        cfg = self.config
        stop = _StopFlag()

        def handler(signum, frame):
            stop.signum = signum  # flag only — drain at the loop top

        prev = {}
        try:
            for s in (signal.SIGTERM, signal.SIGINT):
                prev[s] = signal.signal(s, handler)
        except ValueError:  # not the main thread (tests)
            prev = {}
        t0 = cfg.clock()
        try:
            while stop.signum is None:
                self.step()
                if max_seconds is not None \
                        and cfg.clock() - t0 >= max_seconds:
                    break
                cfg.sleep_fn(cfg.poll_interval_s)
            return self.drain(
                reason=(signal.Signals(stop.signum).name
                        if stop.signum is not None else "max_seconds"))
        finally:
            for s, h in prev.items():
                signal.signal(s, h)

    def drain(self, reason: str = "drain") -> int:
        """Graceful shutdown: stop admitting (pending spool entries are
        rejected with a retry-after), interrupt in-flight workers
        through the supervisor's flag-only signal path, wait for their
        checkpoint flushes, journal every in-flight job's resume state,
        and exit ``EXIT_PREEMPTED``. Queued jobs stay queued — they are
        already durable; the restarted daemon dispatches them."""
        cfg = self.config
        self._draining = True
        self.store.journal.append("daemon_drain", reason=reason)
        now = cfg.clock()
        self._admit(now)  # draining=True -> loud rejections
        for jid in list(self._procs):
            self._interrupt_worker(jid, now)  # handles exist here
        deadline = now + cfg.drain_grace_s
        while self._procs and cfg.clock() < deadline:
            self.step()
            if self._procs:
                cfg.sleep_fn(cfg.poll_interval_s)
        for handle in self._procs.values():  # wedged past the grace
            try:
                handle.kill()
            except OSError:
                pass
        self.step()  # final reconcile: orphan anything SIGKILLed above
        self.store.journal.append("daemon_exit", outcome="drained")
        self._publish_status(cfg.clock())
        self.close()
        return EXIT_PREEMPTED

    def abandon(self) -> None:
        """Lost-lease teardown (service/fleet.py): the partition now
        belongs to a peer, so this daemon must stop WITHOUT journaling
        — it no longer owns the journal (the single-writer-per-
        partition invariant is exactly this stop). SIGKILL our workers
        (the adopting host's re-dispatches own the checkpoint stems
        now; the stem lock would fence a straggler anyway, but a split
        brain must not keep computing) and release the handles."""
        for handle in self._procs.values():
            try:
                handle.kill()
            except OSError:
                pass
        self._procs.clear()
        self._term_sent.clear()
        self._term_pid.clear()
        self.close()

    def close(self) -> None:
        """Release the daemon's journal handles — store AND cache
        index. The teardown every non-``serve()`` driver (tests,
        benches, chaos cells) should call; ``drain()`` routes through
        it too."""
        if self.cache is not None:
            self.cache.close()
        self.store.close()
