"""Profiling — the analog of the reference's Paraver trace study.

The reference's report dedicates a section (Heat.pdf §7) to Paraver
traces of the MPI runs: blocking-send phases, per-step communication
cost, the Allreduce stall pattern. The TPU-native equivalents:

- :func:`trace`: wrap any region in a ``jax.profiler`` trace viewable
  in Perfetto/XProf/TensorBoard — kernel timeline, DMA, collectives.
- :func:`step_stats`: cheap quantitative summary (steps/sec,
  Mcells*steps/sec, effective HBM GB/s) without a trace viewer.

On transports with deeply asynchronous dispatch, ``block_until_ready``
alone may under-synchronize; :func:`sync` forces a device->host read,
which is a true pipeline flush (used by bench.py between repetitions).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass

import jax


def sync(x) -> None:
    """True synchronization: a device->host read of one element.

    Element indexing, not ``ravel()[0]`` — ravel would materialize a
    full copy of the grid just to read one value.
    """
    x = getattr(x, "grid", x)  # accept a HeatResult directly
    jax.block_until_ready(x)
    float(x[(0,) * x.ndim])


def chain_time(step_fn, u0, reps: int) -> float:
    """Wall-clock seconds for ``reps`` chained ``step_fn`` applications.

    The chained-slope timing protocol shared by ``bench.py`` and the
    tuning tools: copy ``u0`` first (compiled runners donate their input
    buffer — the copy protects the caller's array), apply
    ``g = step_fn(g)`` ``reps`` times with no intermediate host sync,
    then one terminal :func:`sync` as the true pipeline flush. Timing
    the slope between two batch sizes cancels the constant
    dispatch+readback latency (~0.2 s per call on the axon tunnel).
    ``step_fn`` must return the next grid (unwrap any extra outputs).
    """
    import jax.numpy as jnp

    g = jnp.copy(u0)
    jax.block_until_ready(g)
    t0 = time.perf_counter()
    # heatlint: begin dispatch-region
    for _ in range(reps):
        g = step_fn(g)
    # heatlint: end dispatch-region
    sync(g)
    return time.perf_counter() - t0


def chain_slope(step_fn, u0, reps_a: int, reps_b: int,
                batches: int = 1) -> float:
    """Steady-state seconds per ``step_fn`` call via the chained slope.

    Measures each endpoint ``batches`` times, takes the minimum of the
    *raw times* (transport noise — dispatch jitter, host scheduling —
    is strictly additive on wall-clock, so min converges on the true
    time; a min over per-batch *slopes* would instead be biased low,
    preferentially keeping batches whose short endpoint got inflated),
    then returns ``(min t_b - min t_a) / (reps_b - reps_a)``. Raises
    ``RuntimeError`` when the slope is non-positive (noise swamped the
    measurement — e.g. the per-call compute is far below the
    transport's dispatch latency); callers must surface that rather
    than report a garbage throughput number.
    """
    assert reps_b > reps_a >= 1 and batches >= 1
    t_a = min(chain_time(step_fn, u0, reps_a) for _ in range(batches))
    t_b = min(chain_time(step_fn, u0, reps_b) for _ in range(batches))
    per = (t_b - t_a) / (reps_b - reps_a)
    if per <= 0:
        raise RuntimeError(
            f"non-positive chained slope ({t_b:.4f}s for {reps_b} reps vs "
            f"{t_a:.4f}s for {reps_a}): measurement noise exceeds per-call "
            f"compute; increase the batch budget"
        )
    return per


def calibrated_slope(step_fn, u0, span_s: float = 0.5,
                     batches: int = 3, max_reps: int = 3000) -> float:
    """:func:`chain_slope` with the long endpoint sized so it holds
    ``span_s`` seconds of REAL device work.

    The failure mode this prevents (seen repeatedly on the axon
    tunnel): a caller guesses the rep count from a single warm call,
    whose time is dominated by the ~0.2 s dispatch+readback floor; for
    sub-millisecond kernels the guessed span ends up a few ms of
    device work, noise swamps the slope, and the tool prints garbage
    rates (e.g. the same kernel reading 56 / 119 / 480 Gcells*steps/s
    across three invocations). Calibration here is itself a slope —
    ``(t_33 - t_1) / 32`` cancels the floor — so the final endpoint
    really spans ``span_s`` of device time. Raises ``RuntimeError``
    (from :func:`chain_slope`, or directly when even ``max_reps``
    cannot fill the span) rather than returning a garbage number.
    """
    t1 = chain_time(step_fn, u0, 1)
    t33 = chain_time(step_fn, u0, 33)
    per_est = (t33 - t1) / 32
    if per_est <= 0:
        per_est = span_s / max_reps  # fall through to the reps cap
    reps_b = 1 + max(32, int(span_s / per_est))
    if reps_b > max_reps:
        # Tolerate a modest shortfall (clock drift makes per_est fuzzy
        # anyway); a span under ~60% of the requested device work is
        # the garbage-rate regime this function exists to refuse.
        if max_reps * per_est < 0.6 * span_s:
            raise RuntimeError(
                f"per-call compute ~{per_est*1e6:.1f} us: even "
                f"{max_reps} reps span <{0.6 * span_s:.2f} s of device "
                f"work; raise max_reps or use a larger problem")
        reps_b = max_reps
    return chain_slope(step_fn, u0, 1, reps_b, batches=batches)


def calibrated_slope_paired(named_fns, u0, span_s: float = 0.5,
                            batches: int = 3, max_reps: int = 3000):
    """Paired :func:`calibrated_slope` over several step fns.

    Device clock state drifts on tens-of-seconds scales (the same
    kernel read 86 and 123 Gcells*steps/s in back-to-back invocations
    while its competitor held steady), so sequential per-variant
    timing can misrank variants. Here every batch interleaves ALL
    variants' endpoint measurements, so drift lands on each variant
    alike and the min-of-raw-endpoints slope compares like with like.
    Returns ``{name: seconds per call}``; a variant whose slope comes
    out non-positive maps to ``None`` (surface it, don't guess), and so
    does one whose ``max_reps`` cannot hold at least 60% of ``span_s``
    of device work — the same garbage-rate regime
    :func:`calibrated_slope` refuses with an exception (here a ``None``
    keeps the other variants' paired comparison alive).
    """
    reps = {}
    short_span = set()
    for name, fn in named_fns.items():
        t1 = chain_time(fn, u0, 1)
        t33 = chain_time(fn, u0, 33)
        per_est = (t33 - t1) / 32
        if per_est <= 0:
            per_est = span_s / max_reps
        want = 1 + max(32, int(span_s / per_est))
        # >= 2 so the slope divisor below is never zero, whatever
        # max_reps a caller passes.
        reps[name] = max(2, min(want, max_reps))
        if reps[name] < want and reps[name] * per_est < 0.6 * span_s:
            short_span.add(name)
    timed = [n for n in named_fns if n not in short_span]
    t_a = {n: [] for n in timed}
    t_b = {n: [] for n in timed}
    for _ in range(batches):
        for name in timed:
            t_a[name].append(chain_time(named_fns[name], u0, 1))
            t_b[name].append(chain_time(named_fns[name], u0, reps[name]))
    out = {}
    for name in named_fns:
        if name in short_span:
            out[name] = None
            continue
        per = (min(t_b[name]) - min(t_a[name])) / (reps[name] - 1)
        out[name] = per if per > 0 else None
    return out


def bench_rounds_paired(named_fns, u0, steps_per_call, span_s: float = 0.5,
                        batches: int = 3, max_reps: int = 3000):
    """Jit, warm, and time a set of round fns with
    :func:`calibrated_slope_paired`; print one line per variant and
    return ``{name: Gcells*steps/s}``.

    The shared driver of the A/B tools (``tools/ab_fused_g.py`` /
    ``ab_fused_h.py``): a variant that fails to compile prints FAILED
    and is excluded; a variant whose slope is noise prints so rather
    than reporting a garbage rate. ``steps_per_call[name]`` is how many
    stencil steps one call advances (K for temporal rounds).
    """
    import math

    runs = {}
    for name, fn in named_fns.items():
        run = jax.jit(fn)
        try:
            sync(run(u0))
        except Exception as e:  # noqa: BLE001 — surface, don't crash the A/B
            print(f"{name:26s}: FAILED {type(e).__name__}: {e}")
            continue
        runs[name] = run
    pers = calibrated_slope_paired(runs, u0, span_s=span_s,
                                   batches=batches, max_reps=max_reps)
    cells = math.prod(u0.shape)
    out = {}
    for name, per in pers.items():
        if per is None:
            print(f"{name:26s}: no trustworthy slope "
                  f"(non-positive, or max_reps spans <60% of span_s)")
            continue
        k = steps_per_call[name]
        g = cells * k / per / 1e9
        print(f"{name:26s}: {per*1e3:8.2f} ms/call {per/k*1e6:9.1f} "
              f"us/step {g:7.1f} Gcells*steps/s")
        out[name] = g
    return out


@contextlib.contextmanager
def trace(log_dir: str):
    """``jax.profiler`` trace context; view with TensorBoard/XProf.

    Yields a one-argument callable: pass it the result array (produced
    *inside* the region) and it synchronizes before the trace closes, so
    the profile contains the full device computation, not just its
    dispatch::

        with trace("/tmp/prof") as done:
            res = solve(cfg)
            done(res.grid)
    """
    targets = []
    with jax.profiler.trace(str(log_dir)):
        yield targets.append
        for t in targets:
            jax.block_until_ready(t)


@dataclass
class StepStats:
    """Throughput summary of a timed run."""

    cells: int
    steps: int
    elapsed_s: float
    bytes_per_cell: int = 8  # one read + one write of f32 per step

    @property
    def steps_per_s(self) -> float:
        return self.steps / self.elapsed_s

    @property
    def mcells_steps_per_s(self) -> float:
        return self.cells * self.steps / self.elapsed_s / 1e6

    @property
    def effective_hbm_gb_s(self) -> float:
        """Lower bound on achieved HBM bandwidth for a streaming step."""
        return (self.cells * self.bytes_per_cell * self.steps
                / self.elapsed_s / 1e9)

    def summary(self) -> str:
        return (f"{self.steps} steps on {self.cells} cells in "
                f"{self.elapsed_s:.4f}s: "
                f"{self.mcells_steps_per_s:,.0f} Mcells*steps/s, "
                f"{self.steps_per_s:,.0f} steps/s, "
                f">= {self.effective_hbm_gb_s:.0f} GB/s effective")


def cell_count(config) -> int:
    """Total grid cells of a config — the throughput denominator."""
    cells = 1
    for n in config.shape:
        cells *= n
    return cells


def bytes_per_cell(config) -> int:
    """HBM traffic model: one read + one write of the storage dtype per
    cell per step (f32chunk's f32 carry lives in VMEM, so it shares the
    storage-dtype model). The single source for :func:`step_stats` and
    the telemetry chunk events — they must never disagree."""
    import jax.numpy as jnp

    return 2 * jnp.dtype(config.dtype).itemsize


def step_stats(result, config) -> StepStats:
    """Build :class:`StepStats` from a solver result + config."""
    return StepStats(
        cells=cell_count(config),
        steps=max(result.steps_run, 1),
        elapsed_s=result.elapsed_s,
        bytes_per_cell=bytes_per_cell(config),
    )


class Timeline:
    """Lightweight phase timer for driver-level instrumentation
    (the ``MPI_Wtime`` bracketing of the reference, ``mpi/...stat.c:88``,
    generalized to named phases)."""

    def __init__(self):
        self.phases: list[tuple[str, float]] = []
        self._t0 = time.perf_counter()

    def mark(self, name: str, sync_on=None) -> float:
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        now = time.perf_counter()
        dt = now - self._t0
        self.phases.append((name, dt))
        self._t0 = now
        return dt

    def summary(self) -> str:
        if not self.phases:
            return "  (no phases marked)"
        total = sum(dt for _, dt in self.phases)
        # total == 0 (sub-resolution phases): percentages are
        # meaningless, not a ZeroDivisionError — print them as 0.
        denom = total if total > 0 else 1.0
        lines = [f"  {name:<24s} {dt:9.4f}s ({dt/denom*100:5.1f}%)"
                 for name, dt in self.phases]
        return "\n".join(lines + [f"  {'total':<24s} {total:9.4f}s"])
