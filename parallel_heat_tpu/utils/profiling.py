"""Profiling — the analog of the reference's Paraver trace study.

The reference's report dedicates a section (Heat.pdf §7) to Paraver
traces of the MPI runs: blocking-send phases, per-step communication
cost, the Allreduce stall pattern. The TPU-native equivalents:

- :func:`trace`: wrap any region in a ``jax.profiler`` trace viewable
  in Perfetto/XProf/TensorBoard — kernel timeline, DMA, collectives.
- :func:`step_stats`: cheap quantitative summary (steps/sec,
  Mcells*steps/sec, effective HBM GB/s) without a trace viewer.

On transports with deeply asynchronous dispatch, ``block_until_ready``
alone may under-synchronize; :func:`sync` forces a device->host read,
which is a true pipeline flush (used by bench.py between repetitions).

The chained-slope / interleaved min-of-N timing protocol itself lives
in ``utils/measure.py`` (one home, injectable clock); this module
re-exports it unchanged for the existing tool imports and keeps the
trace/stats helpers.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass

import jax

from parallel_heat_tpu.utils.measure import (  # noqa: F401 — re-exports
    bench_rounds_paired, calibrated_slope, calibrated_slope_paired,
    chain_slope, chain_time, sync)


@contextlib.contextmanager
def trace(log_dir: str):
    """``jax.profiler`` trace context; view with TensorBoard/XProf.

    Yields a one-argument callable: pass it the result array (produced
    *inside* the region) and it synchronizes before the trace closes, so
    the profile contains the full device computation, not just its
    dispatch::

        with trace("/tmp/prof") as done:
            res = solve(cfg)
            done(res.grid)
    """
    targets = []
    with jax.profiler.trace(str(log_dir)):
        yield targets.append
        for t in targets:
            jax.block_until_ready(t)


@dataclass
class StepStats:
    """Throughput summary of a timed run."""

    cells: int
    steps: int
    elapsed_s: float
    bytes_per_cell: int = 8  # one read + one write of f32 per step

    @property
    def steps_per_s(self) -> float:
        return self.steps / self.elapsed_s

    @property
    def mcells_steps_per_s(self) -> float:
        return self.cells * self.steps / self.elapsed_s / 1e6

    @property
    def effective_hbm_gb_s(self) -> float:
        """Lower bound on achieved HBM bandwidth for a streaming step."""
        return (self.cells * self.bytes_per_cell * self.steps
                / self.elapsed_s / 1e9)

    def summary(self) -> str:
        return (f"{self.steps} steps on {self.cells} cells in "
                f"{self.elapsed_s:.4f}s: "
                f"{self.mcells_steps_per_s:,.0f} Mcells*steps/s, "
                f"{self.steps_per_s:,.0f} steps/s, "
                f">= {self.effective_hbm_gb_s:.0f} GB/s effective")


def cell_count(config) -> int:
    """Total grid cells of a config — the throughput denominator."""
    cells = 1
    for n in config.shape:
        cells *= n
    return cells


def bytes_per_cell(config) -> int:
    """HBM traffic model: one read + one write of the storage dtype per
    cell per step (f32chunk's f32 carry lives in VMEM, so it shares the
    storage-dtype model). The single source for :func:`step_stats` and
    the telemetry chunk events — they must never disagree."""
    import jax.numpy as jnp

    return 2 * jnp.dtype(config.dtype).itemsize


def step_stats(result, config) -> StepStats:
    """Build :class:`StepStats` from a solver result + config."""
    return StepStats(
        cells=cell_count(config),
        steps=max(result.steps_run, 1),
        elapsed_s=result.elapsed_s,
        bytes_per_cell=bytes_per_cell(config),
    )


class Timeline:
    """Lightweight phase timer for driver-level instrumentation
    (the ``MPI_Wtime`` bracketing of the reference, ``mpi/...stat.c:88``,
    generalized to named phases)."""

    def __init__(self):
        self.phases: list[tuple[str, float]] = []
        self._t0 = time.perf_counter()

    def mark(self, name: str, sync_on=None) -> float:
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        now = time.perf_counter()
        dt = now - self._t0
        self.phases.append((name, dt))
        self._t0 = now
        return dt

    def summary(self) -> str:
        if not self.phases:
            return "  (no phases marked)"
        total = sum(dt for _, dt in self.phases)
        # total == 0 (sub-resolution phases): percentages are
        # meaningless, not a ZeroDivisionError — print them as 0.
        denom = total if total > 0 else 1.0
        lines = [f"  {name:<24s} {dt:9.4f}s ({dt/denom*100:5.1f}%)"
                 for name, dt in self.phases]
        return "\n".join(lines + [f"  {'total':<24s} {total:9.4f}s"])
