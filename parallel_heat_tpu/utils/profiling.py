"""Profiling — the analog of the reference's Paraver trace study.

The reference's report dedicates a section (Heat.pdf §7) to Paraver
traces of the MPI runs: blocking-send phases, per-step communication
cost, the Allreduce stall pattern. The TPU-native equivalents:

- :func:`trace`: wrap any region in a ``jax.profiler`` trace viewable
  in Perfetto/XProf/TensorBoard — kernel timeline, DMA, collectives.
- :func:`step_stats`: cheap quantitative summary (steps/sec,
  Mcells*steps/sec, effective HBM GB/s) without a trace viewer.

On transports with deeply asynchronous dispatch, ``block_until_ready``
alone may under-synchronize; :func:`sync` forces a device->host read,
which is a true pipeline flush (used by bench.py between repetitions).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass

import jax


def sync(x) -> None:
    """True synchronization: a device->host read of one element.

    Element indexing, not ``ravel()[0]`` — ravel would materialize a
    full copy of the grid just to read one value.
    """
    x = getattr(x, "grid", x)  # accept a HeatResult directly
    jax.block_until_ready(x)
    float(x[(0,) * x.ndim])


def chain_time(step_fn, u0, reps: int) -> float:
    """Wall-clock seconds for ``reps`` chained ``step_fn`` applications.

    The chained-slope timing protocol shared by ``bench.py`` and the
    tuning tools: copy ``u0`` first (compiled runners donate their input
    buffer — the copy protects the caller's array), apply
    ``g = step_fn(g)`` ``reps`` times with no intermediate host sync,
    then one terminal :func:`sync` as the true pipeline flush. Timing
    the slope between two batch sizes cancels the constant
    dispatch+readback latency (~0.2 s per call on the axon tunnel).
    ``step_fn`` must return the next grid (unwrap any extra outputs).
    """
    import jax.numpy as jnp

    g = jnp.copy(u0)
    jax.block_until_ready(g)
    t0 = time.perf_counter()
    for _ in range(reps):
        g = step_fn(g)
    sync(g)
    return time.perf_counter() - t0


def chain_slope(step_fn, u0, reps_a: int, reps_b: int,
                batches: int = 1) -> float:
    """Steady-state seconds per ``step_fn`` call via the chained slope.

    Measures each endpoint ``batches`` times, takes the minimum of the
    *raw times* (transport noise — dispatch jitter, host scheduling —
    is strictly additive on wall-clock, so min converges on the true
    time; a min over per-batch *slopes* would instead be biased low,
    preferentially keeping batches whose short endpoint got inflated),
    then returns ``(min t_b - min t_a) / (reps_b - reps_a)``. Raises
    ``RuntimeError`` when the slope is non-positive (noise swamped the
    measurement — e.g. the per-call compute is far below the
    transport's dispatch latency); callers must surface that rather
    than report a garbage throughput number.
    """
    assert reps_b > reps_a >= 1 and batches >= 1
    t_a = min(chain_time(step_fn, u0, reps_a) for _ in range(batches))
    t_b = min(chain_time(step_fn, u0, reps_b) for _ in range(batches))
    per = (t_b - t_a) / (reps_b - reps_a)
    if per <= 0:
        raise RuntimeError(
            f"non-positive chained slope ({t_b:.4f}s for {reps_b} reps vs "
            f"{t_a:.4f}s for {reps_a}): measurement noise exceeds per-call "
            f"compute; increase the batch budget"
        )
    return per


@contextlib.contextmanager
def trace(log_dir: str):
    """``jax.profiler`` trace context; view with TensorBoard/XProf.

    Yields a one-argument callable: pass it the result array (produced
    *inside* the region) and it synchronizes before the trace closes, so
    the profile contains the full device computation, not just its
    dispatch::

        with trace("/tmp/prof") as done:
            res = solve(cfg)
            done(res.grid)
    """
    targets = []
    with jax.profiler.trace(str(log_dir)):
        yield targets.append
        for t in targets:
            jax.block_until_ready(t)


@dataclass
class StepStats:
    """Throughput summary of a timed run."""

    cells: int
    steps: int
    elapsed_s: float
    bytes_per_cell: int = 8  # one read + one write of f32 per step

    @property
    def steps_per_s(self) -> float:
        return self.steps / self.elapsed_s

    @property
    def mcells_steps_per_s(self) -> float:
        return self.cells * self.steps / self.elapsed_s / 1e6

    @property
    def effective_hbm_gb_s(self) -> float:
        """Lower bound on achieved HBM bandwidth for a streaming step."""
        return (self.cells * self.bytes_per_cell * self.steps
                / self.elapsed_s / 1e9)

    def summary(self) -> str:
        return (f"{self.steps} steps on {self.cells} cells in "
                f"{self.elapsed_s:.4f}s: "
                f"{self.mcells_steps_per_s:,.0f} Mcells*steps/s, "
                f"{self.steps_per_s:,.0f} steps/s, "
                f">= {self.effective_hbm_gb_s:.0f} GB/s effective")


def step_stats(result, config) -> StepStats:
    """Build :class:`StepStats` from a solver result + config."""
    cells = 1
    for n in config.shape:
        cells *= n
    import jax.numpy as jnp

    return StepStats(
        cells=cells,
        steps=max(result.steps_run, 1),
        elapsed_s=result.elapsed_s,
        bytes_per_cell=2 * jnp.dtype(config.dtype).itemsize,
    )


class Timeline:
    """Lightweight phase timer for driver-level instrumentation
    (the ``MPI_Wtime`` bracketing of the reference, ``mpi/...stat.c:88``,
    generalized to named phases)."""

    def __init__(self):
        self.phases: list[tuple[str, float]] = []
        self._t0 = time.perf_counter()

    def mark(self, name: str, sync_on=None) -> float:
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        now = time.perf_counter()
        dt = now - self._t0
        self.phases.append((name, dt))
        self._t0 = now
        return dt

    def summary(self) -> str:
        total = sum(dt for _, dt in self.phases)
        lines = [f"  {name:<24s} {dt:9.4f}s ({dt/total*100:5.1f}%)"
                 for name, dt in self.phases]
        return "\n".join(lines + [f"  {'total':<24s} {total:9.4f}s"])
