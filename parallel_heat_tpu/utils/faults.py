"""Deterministic fault injection for chaos-testing the run supervisor.

The reference has nothing to test here — no failure detection, no
checkpointing (SURVEY.md §5) — so this framework's recovery machinery
needs its own adversary. A :class:`FaultPlan` injects the three failure
shapes a long preemptible-TPU campaign actually sees, each at an exact,
reproducible point:

- **silent data corruption**: NaN written into one interior cell of a
  chunk's output at the first chunk boundary at-or-after step ``k``
  (models a flipped bit / bad HBM read — the thing the isfinite guard
  exists to catch);
- **transient dispatch failure**: a synthetic
  :class:`InjectedTransientError` raised before dispatching chunk
  ordinal ``n`` (models a runtime hiccup the retry policy should
  absorb);
- **preemption**: a real OS signal (default ``SIGTERM``) delivered to
  this process before dispatching chunk ordinal ``n`` (models the
  maintenance-event kill; drives the flush-checkpoint-and-exit path);
- **process death**: ``kill_worker_at_chunk`` SIGKILLs this process —
  uncatchable, no flush — before dispatching chunk ordinal ``n``
  (models the OOM kill / hard preemption; run inside a service WORKER
  so the daemon's orphan-detect/requeue/resume path faces a true
  corpse). Mutually exclusive with every in-process fault kind.
- **single-host death in an SPMD run**: ``kill_process_at_chunk``
  likewise SIGKILLs, but is meant to be rank-scoped (below) so exactly
  one rank of a real multi-process run dies — the surviving ranks'
  dead-peer detection (``parallel/coordinator.py``) is what the
  ``mp_peer_lost`` chaos cell certifies.

**Per-rank scoping** (``only_process=``): on a multi-process SPMD run
every rank constructs the same plan, but a real fault lands on ONE
host — ``only_process=1`` makes every firing hook a no-op on the other
ranks (ordinals still count, so the schedule stays aligned). The
supervisor binds its coordinator rank via :meth:`FaultPlan.
bind_process`; unbound plans resolve the runtime's process index
lazily. Rank-scoped corruption of a grid that spans non-addressable
devices rewrites only THIS rank's addressable shards (host round trip
+ ``jax.make_array_from_single_device_arrays`` — a process-local
construction, no collective), which is exactly the split-brain
injection: the corrupt rank's local guard verdict trips while its
peers' stay clean, and only the consensus layer can make them act
together.

Faults fire at supervisor hook points — ``before_chunk`` pre-dispatch,
``corrupt`` on each chunk's output — never inside compiled programs,
so the simulation numerics under test are exactly production's.
Determinism contract: every fault names its firing point; one-shot
faults (the default) record having fired, so the supervisor's
rolled-back retry sees a clean rerun (the *transient* model), while
``recurring=True`` re-fires on every pass (the *permanent* model that
must exhaust the retry budget).
"""

from __future__ import annotations

import os
import signal as _signal
from dataclasses import dataclass, field
from typing import Optional, Tuple

import numpy as np


class InjectedTransientError(RuntimeError):
    """Synthetic transient dispatch failure (chaos harness only).

    The supervisor's classifier treats this exactly like a retryable
    runtime error (preempted collective, transient RPC failure):
    rollback to the last good checkpoint, backoff, retry.
    """


@dataclass
class FaultPlan:
    """A deterministic schedule of injected faults for one supervised
    run. All fields are optional; an empty plan is a no-op."""

    # Corrupt one interior cell of the chunk output with NaN at the
    # first chunk boundary at-or-after this ABSOLUTE step count.
    nan_at_step: Optional[int] = None
    # Corrupt with a FINITE spike value instead (the bad-HBM-read that
    # lands on an exponent bit: huge but not NaN — invisible to the
    # isfinite guard, caught by the progress guard's extrema envelope).
    # Same firing rules as nan_at_step. `spike_region` > 1 corrupts a
    # centered region x region interior block instead of one cell —
    # the buggy-exchange model: values that stay INSIDE the extrema
    # envelope but move total heat faster than any boundary flux can
    # (caught by the progress guard's heat-rate bound).
    spike_at_step: Optional[int] = None
    spike_value: float = 1e12
    spike_region: int = 1
    # False (default): the corruption is one-shot — a rolled-back retry
    # reruns clean (transient-fault model). True: re-fires every time
    # the step is re-reached (permanent-fault model).
    recurring: bool = False

    # Raise InjectedTransientError before dispatching these chunk
    # ordinals. Ordinals count every before_chunk() call GLOBALLY
    # across retries (dispatch attempts, not simulated steps), so a
    # retried schedule naturally advances past a fired ordinal.
    transient_on_chunks: Tuple[int, ...] = ()

    # Deliver `signum` to this process before dispatching this chunk
    # ordinal (once).
    signal_at_chunk: Optional[int] = None
    signum: int = int(_signal.SIGTERM)

    # SIGKILL this process before dispatching this chunk ordinal — REAL
    # process death (uncatchable, no cleanup, no flush), the thing an
    # OOM kill or a preemption hard-stop actually does. The service
    # chaos cells run this inside a child WORKER process so the daemon
    # sees a true mid-job corpse: orphan detection, requeue, and
    # checkpoint-lineage resume are exercised against genuine process
    # death rather than a polite in-process exception.
    kill_worker_at_chunk: Optional[int] = None

    # SIGKILL this process before dispatching this chunk ordinal, like
    # kill_worker_at_chunk, but intended for SPMD rank scoping: with
    # only_process=r, rank r of a real multi-process run dies mid-run
    # while its peers live — the mp_peer_lost chaos cell's injection
    # (the surviving ranks must detect the corpse within one barrier
    # timeout and exit preempted with an elastic resume command).
    kill_process_at_chunk: Optional[int] = None

    # Fire every fault of this plan ONLY on this process index (None:
    # every process). Ordinals still advance on non-matching ranks so
    # the firing schedule reads the same everywhere.
    only_process: Optional[int] = None

    def __post_init__(self):
        if self.nan_at_step is not None and self.spike_at_step is not None:
            # The two corruptions share the one-shot firing state and
            # the injection site; allowing both would silently drop the
            # spike (and a chaos cell would certify a drift detection
            # that never ran). Loud, like every other plan error.
            raise ValueError(
                "FaultPlan: set nan_at_step or spike_at_step, not both "
                "(they share the corruption slot; use two plans/runs)")
        kills = [k for k in (self.kill_worker_at_chunk,
                             self.kill_process_at_chunk)
                 if k is not None]
        if len(kills) > 1:
            raise ValueError(
                "FaultPlan: set kill_worker_at_chunk or "
                "kill_process_at_chunk, not both (one SIGKILL per "
                "plan — the second could never fire)")
        if kills and (self.nan_at_step is not None
                      or self.spike_at_step is not None
                      or self.transient_on_chunks
                      or self.signal_at_chunk is not None):
            # SIGKILL ends the process: any in-process fault scheduled
            # alongside it either fires first (masking the death the
            # cell certifies) or never fires at all (certifying a
            # detection that never ran). Loud, like nan+spike.
            raise ValueError(
                "FaultPlan: kill_worker_at_chunk/kill_process_at_chunk "
                "model true process death (SIGKILL) and cannot be "
                "combined with in-process fault kinds (nan_at_step/"
                "spike_at_step/transient_on_chunks/signal_at_chunk) — "
                "use separate plans/runs")

    # -- firing state (not part of the schedule) -------------------------
    _chunks_seen: int = field(default=0, repr=False)
    _nan_fired: bool = field(default=False, repr=False)
    _transients_fired: set = field(default_factory=set, repr=False)
    _signal_fired: bool = field(default=False, repr=False)
    _bound_process: Optional[int] = field(default=None, repr=False)

    def bind_process(self, process_index: int) -> "FaultPlan":
        """Pin the rank ``only_process`` is judged against (the
        supervisor binds its coordinator rank — thread-simulated ranks
        share one OS process, so the runtime's own process index would
        be wrong there). Unbound plans resolve it lazily from the
        runtime."""
        self._bound_process = int(process_index)
        return self

    def _on_scoped_process(self) -> bool:
        if self.only_process is None:
            return True
        rank = self._bound_process
        if rank is None:
            from parallel_heat_tpu.utils.telemetry import _process_info

            rank = _process_info()[0]
        return rank == self.only_process

    def before_chunk(self) -> int:
        """Pre-dispatch hook; returns this dispatch's global ordinal.
        May raise :class:`InjectedTransientError` or deliver a signal,
        per the plan."""
        i = self._chunks_seen
        self._chunks_seen += 1
        if not self._on_scoped_process():
            return i
        if self.kill_worker_at_chunk == i or self.kill_process_at_chunk == i:
            # No fired-flag: SIGKILL is uncatchable and ends the
            # process here — a retried schedule only re-reaches this
            # ordinal in a NEW process (the service re-dispatch), where
            # the plan is attempt-gated by the caller.
            os.kill(os.getpid(), int(_signal.SIGKILL))
        if self.signal_at_chunk == i and not self._signal_fired:
            self._signal_fired = True
            # A real signal through the real delivery path: the
            # supervisor's handler (not this hook) must observe it,
            # exactly as a preemption notice would arrive.
            os.kill(os.getpid(), self.signum)
        if i in self.transient_on_chunks and i not in self._transients_fired:
            self._transients_fired.add(i)
            raise InjectedTransientError(
                f"injected transient dispatch error on chunk ordinal {i}")
        return i

    def corrupt(self, grid, step: int, observed: bool = True):
        """Chunk-output hook: returns ``grid``, NaN-corrupted in one
        interior cell if the plan says step ``step`` is past the
        corruption point (a NEW array — the stream's own state is
        untouched, like real corruption landing in a snapshot copy;
        a tripped guard abandons the stream anyway).

        ``observed=False`` (the supervisor passes its guard-due flag)
        defers the fault: the supervisor only looks at chunk outputs it
        guards, so corruption landing on an unobserved boundary would
        be dropped with the next ``cur = res.grid`` and the one-shot
        fault silently consumed — the chaos cell would then certify a
        detection that never happened. Deferring keeps the injection
        pending until the first boundary a guard actually inspects,
        preserving determinism: fires at the first GUARDED boundary
        at-or-after ``nan_at_step`` (or ``spike_at_step``)."""
        at = (self.nan_at_step if self.nan_at_step is not None
              else self.spike_at_step)
        if at is None or step < at:
            return grid
        if not observed or not self._on_scoped_process():
            return grid
        if self._nan_fired and not self.recurring:
            return grid
        self._nan_fired = True
        import jax
        import jax.numpy as jnp

        value = (jnp.nan if self.nan_at_step is not None
                 else self.spike_value)
        if not getattr(grid, "is_fully_addressable", True):
            # Rank-scoped corruption of a multi-process grid: rewrite
            # only THIS rank's addressable shards (host round trip +
            # make_array_from_single_device_arrays — process-local, no
            # collective). The peers' local views stay clean: the
            # split-brain injection the consensus layer exists for.
            shards = sorted(grid.addressable_shards,
                            key=lambda s: s.device.id)
            locals_ = []
            for n, sh in enumerate(shards):
                a = np.asarray(sh.data).copy()
                if n == 0:
                    a[tuple(1 for _ in a.shape)] = float(value)
                locals_.append(jax.device_put(a, sh.device))
            return jax.make_array_from_single_device_arrays(
                grid.shape, grid.sharding, locals_)
        if self.spike_at_step is not None and self.spike_region > 1:
            # Centered interior block (the grid center carries the
            # largest values, so an in-envelope overwrite there moves
            # real heat).
            idx = tuple(slice((n - self.spike_region) // 2,
                              (n - self.spike_region) // 2
                              + self.spike_region)
                        for n in grid.shape)
        else:
            idx = tuple(1 for _ in grid.shape)
        return jax.jit(lambda u: u.at[idx].set(value))(grid)
