"""Wall-clock timing — the analog of the reference's ``timestamp.h``
(``getTimestamp``/``getElapsedtime``, ``cuda/timestamp.h:8-26``) and the
``MPI_Wtime`` pairs (``mpi/...stat.c:88,298``).

On an async backend like JAX, a bare ``perf_counter`` delta measures
dispatch, not compute; ``Timer`` therefore blocks on the provided arrays
before reading the clock.
"""

from __future__ import annotations

import time

import jax


class Timer:
    """Context-manager wall-clock timer with device synchronization."""

    def __init__(self, sync_on=None):
        self._sync_on = sync_on
        self.elapsed_s: float = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._sync_on is not None:
            jax.block_until_ready(self._sync_on)
        self.elapsed_s = time.perf_counter() - self._t0
        return False

    def stop(self, sync_on=None) -> float:
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        self.elapsed_s = time.perf_counter() - self._t0
        return self.elapsed_s
