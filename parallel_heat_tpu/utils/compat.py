"""Version portability shims for the narrow band of jax APIs whose
spelling changed between the versions this framework supports.

The framework targets current jax, where shard_map replication checking
is the varying-manual-axes (vma) system: outputs annotate their varying
axes (``ShapeDtypeStruct(..., vma=...)``), ``lax.pcast`` broadens a
value's varying set, and ``shard_map(check_vma=...)`` switches the
checker. Pre-0.5 jax spells the same machinery ``check_rep`` with no
per-output annotations and no ``pcast``. Everything else in the
codebase is version-independent; these helpers are the single place
the difference lives, so kernels and drivers never branch on version.
"""

from __future__ import annotations

import os

import jax
from jax import lax

try:  # jax >= 0.6 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover (version-dependent)
    from jax.experimental.shard_map import shard_map as _shard_map

# Probe once: does this jax annotate varying manual axes on avals?
try:
    jax.ShapeDtypeStruct((1,), "float32", vma=frozenset())
    _HAS_VMA = True
except TypeError:  # pre-0.5: check_rep world
    _HAS_VMA = False


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the replication-check flag translated to
    whatever this jax calls it.

    On vma-aware jax the flag passes through as ``check_vma``. On
    pre-0.5 jax the legacy ``check_rep`` checker has no replication
    rule for ``while`` (every converge-mode loop), so it is forced off
    there — the scalar outputs' replication is guaranteed by the
    ``pmax`` in the residual round either way (the same argument the
    pallas paths already rely on under the new checker).
    """
    if _HAS_VMA:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma)
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def pcast(x, axes, to="varying"):
    """``lax.pcast`` where it exists; identity elsewhere (the broadened
    annotation only feeds the vma checker, which old jax doesn't run)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to=to)
    return x


def vma_kw(vma) -> dict:
    """ShapeDtypeStruct kwargs carrying the varying-manual-axes
    annotation: ``{"vma": frozenset(...)}`` on vma-aware jax, ``{}``
    when ``vma`` is None or this jax predates the annotation."""
    if vma is None or not _HAS_VMA:
        return {}
    return {"vma": frozenset(vma)}


def tpu_compiler_params(**kw):
    """``pltpu.CompilerParams`` (new name) / ``TPUCompilerParams`` (old)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return cls(**kw)


def request_cpu_devices(n: int) -> None:
    """Ask for ``n`` virtual CPU devices, portably.

    New jax has the ``jax_num_cpu_devices`` config; old jax only honors
    the XLA flag, and only if the backend has not initialized yet —
    callers must invoke this before touching ``jax.devices()``. The
    env flag is set only on the old-jax path: it would leak into every
    spawned subprocess (and stack up across calls), which the config
    API avoids.
    """
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:  # pre-0.5: only the XLA flag works
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}")
