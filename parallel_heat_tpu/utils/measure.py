"""Measured timing — THE home of the interleaved min-of-N protocol.

Every tool that times kernels against each other (``bench.py``, the
``tools/ab_*.py`` A/B harnesses, the ``tune/`` autotuner) used to carry
its own copy of the same three ideas; the round-14/16 timing flakes
were copies drifting apart. The ideas live here once:

- **Chained dispatch, one terminal flush** (:func:`chain_time`): run
  ``reps`` donated calls back to back with no host sync between them,
  then one true device->host read. The slope between two rep counts
  cancels the constant dispatch+readback latency (~0.2 s per call on
  the axon tunnel).
- **Min of raw endpoints** (:func:`chain_slope`): transport noise is
  strictly additive on wall-clock, so min over the *raw endpoint
  times* converges on the true time; a min over per-batch slopes would
  be biased low.
- **Interleaving** (:func:`calibrated_slope_paired`,
  :func:`interleaved_min_of_n`): device/host clock state drifts on
  tens-of-seconds scales (the same kernel read 86 and 123
  Gcells*steps/s back to back while its competitor held steady).
  Interleaving every variant inside each round lands the drift on all
  variants alike, so min-per-variant compares like with like.

Every entry point takes an injectable ``clock`` (a zero-arg callable
returning seconds, default ``time.perf_counter``), so the min/interleave
arithmetic is testable against a deterministic fake clock and a future
transport can substitute its own timebase without forking the protocol.
``utils/profiling.py`` re-exports the chained-slope family for
backwards compatibility — import new code from here.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax

Clock = Callable[[], float]


def default_clock() -> float:
    """The default injectable timebase (``time.perf_counter``)."""
    return time.perf_counter()


def sync(x) -> None:
    """True synchronization: a device->host read of one element.

    Element indexing, not ``ravel()[0]`` — ravel would materialize a
    full copy of the grid just to read one value.
    """
    x = getattr(x, "grid", x)  # accept a HeatResult directly
    jax.block_until_ready(x)
    float(x[(0,) * x.ndim])


def sync_floor(u0, samples: int = 3, *,
               clock: Optional[Clock] = None) -> float:
    """Median device->host scalar-read latency for this transport —
    the constant the one-shot timings subtract (``bench.py``'s
    converge rows)."""
    clock = clock or default_clock
    times = []
    for _ in range(max(1, samples)):
        t0 = clock()
        sync(u0)
        times.append(clock() - t0)
    return sorted(times)[len(times) // 2]


def timed_call(fn: Callable[[], Any], *, flush=sync,
               clock: Optional[Clock] = None) -> Tuple[float, Any]:
    """One bracketed measurement: ``(wall_seconds, fn())``.

    ``flush`` is applied to the result before the closing clock read
    (the true pipeline flush); pass ``flush=None`` when ``fn`` already
    brackets its own synchronization.
    """
    clock = clock or default_clock
    t0 = clock()
    out = fn()
    if flush is not None and out is not None:
        flush(out)
    return clock() - t0, out


def min_of_n(fn: Callable[[], Any], rounds: int = 3, *, flush=sync,
             clock: Optional[Clock] = None) -> Tuple[float, Any]:
    """Min-of-N wall for one already-warmed measurement:
    ``(min_wall_seconds, last_result)``. Warm ``fn`` (compile + first
    dispatch) before calling — a cold compile inside the bracket is the
    classic garbage-rate bug."""
    best, out = float("inf"), None
    for _ in range(max(1, rounds)):
        wall, out = timed_call(fn, flush=flush, clock=clock)
        best = min(best, wall)
    return best, out


def interleaved_min_of_n(named_fns: Dict[str, Callable[[], Any]],
                         rounds: int = 3, *, flush=sync,
                         clock: Optional[Clock] = None
                         ) -> Dict[str, float]:
    """THE interleaved min-of-N protocol over whole measured calls:
    every round measures ALL variants once, in dict order, so clock
    drift lands on each variant alike; returns ``{name: min wall}``.

    This is the wall-bracket flavor (``bench.py``'s stream/ensemble
    rows, the autotuner's candidate race); use
    :func:`calibrated_slope_paired` when the per-call compute is small
    enough that the dispatch floor must be cancelled by a slope.
    """
    walls: Dict[str, list] = {name: [] for name in named_fns}
    for _ in range(max(1, rounds)):
        for name, fn in named_fns.items():
            wall, _ = timed_call(fn, flush=flush, clock=clock)
            walls[name].append(wall)
    return {name: min(ts) for name, ts in walls.items()}


def interleaved_min_self_timed(named_fns: Dict[str, Callable[[], float]],
                               rounds: int = 3) -> Dict[str, float]:
    """:func:`interleaved_min_of_n` for SELF-TIMED callables: each fn
    returns its own measured wall seconds (use when the bracket must
    exclude per-call setup — e.g. ``bench.py``'s stream row, whose
    bracket starts after the telemetry sinks open). Same interleave
    discipline: every round runs ALL variants in dict order."""
    walls: Dict[str, list] = {name: [] for name in named_fns}
    for _ in range(max(1, rounds)):
        for name, fn in named_fns.items():
            walls[name].append(float(fn()))
    return {name: min(ts) for name, ts in walls.items()}


# ---------------------------------------------------------------------------
# The chained-slope family (dispatch-floor cancellation)
# ---------------------------------------------------------------------------

def chain_time(step_fn, u0, reps: int, *,
               clock: Optional[Clock] = None) -> float:
    """Wall-clock seconds for ``reps`` chained ``step_fn`` applications.

    Copy ``u0`` first (compiled runners donate their input buffer — the
    copy protects the caller's array), apply ``g = step_fn(g)`` ``reps``
    times with no intermediate host sync, then one terminal
    :func:`sync` as the true pipeline flush. ``step_fn`` must return
    the next grid (unwrap any extra outputs).
    """
    import jax.numpy as jnp

    clock = clock or default_clock
    g = jnp.copy(u0)
    jax.block_until_ready(g)
    t0 = clock()
    # heatlint: begin dispatch-region
    for _ in range(reps):
        g = step_fn(g)
    # heatlint: end dispatch-region
    sync(g)
    return clock() - t0


def chain_slope(step_fn, u0, reps_a: int, reps_b: int,
                batches: int = 1, *,
                clock: Optional[Clock] = None) -> float:
    """Steady-state seconds per ``step_fn`` call via the chained slope.

    Measures each endpoint ``batches`` times, takes the minimum of the
    *raw times* (transport noise — dispatch jitter, host scheduling —
    is strictly additive on wall-clock, so min converges on the true
    time; a min over per-batch *slopes* would instead be biased low,
    preferentially keeping batches whose short endpoint got inflated),
    then returns ``(min t_b - min t_a) / (reps_b - reps_a)``. Raises
    ``RuntimeError`` when the slope is non-positive (noise swamped the
    measurement — e.g. the per-call compute is far below the
    transport's dispatch latency); callers must surface that rather
    than report a garbage throughput number.
    """
    assert reps_b > reps_a >= 1 and batches >= 1
    t_a = min(chain_time(step_fn, u0, reps_a, clock=clock)
              for _ in range(batches))
    t_b = min(chain_time(step_fn, u0, reps_b, clock=clock)
              for _ in range(batches))
    per = (t_b - t_a) / (reps_b - reps_a)
    if per <= 0:
        raise RuntimeError(
            f"non-positive chained slope ({t_b:.4f}s for {reps_b} reps vs "
            f"{t_a:.4f}s for {reps_a}): measurement noise exceeds per-call "
            f"compute; increase the batch budget"
        )
    return per


def _calibrate_reps(step_fn, u0, span_s: float, max_reps: int, *,
                    clock: Optional[Clock] = None) -> Tuple[int, bool]:
    """Size the long endpoint to hold ``span_s`` seconds of REAL device
    work -> ``(reps_b, short_span)``. Calibration is itself a slope —
    ``(t_33 - t_1) / 32`` cancels the dispatch floor, so the endpoint
    really spans ``span_s`` of device time (guessing from one warm call
    is the classic garbage-rate bug: that call is dominated by the
    ~0.2 s dispatch+readback floor). ``short_span`` is True when even
    ``max_reps`` cannot hold 60% of the requested device work — the
    garbage-rate regime callers must refuse or surface."""
    t1 = chain_time(step_fn, u0, 1, clock=clock)
    t33 = chain_time(step_fn, u0, 33, clock=clock)
    per_est = (t33 - t1) / 32
    if per_est <= 0:
        per_est = span_s / max_reps  # fall through to the reps cap
    want = 1 + max(32, int(span_s / per_est))
    # >= 2 so the slope divisor is never zero, whatever max_reps a
    # caller passes.
    reps_b = max(2, min(want, max_reps))
    short = reps_b < want and reps_b * per_est < 0.6 * span_s
    return reps_b, short


def calibrated_slope(step_fn, u0, span_s: float = 0.5,
                     batches: int = 3, max_reps: int = 3000, *,
                     clock: Optional[Clock] = None) -> float:
    """:func:`chain_slope` with the long endpoint sized by
    :func:`_calibrate_reps` so it holds ``span_s`` seconds of real
    device work. Raises ``RuntimeError`` (from :func:`chain_slope`, or
    directly in the short-span regime) rather than returning a garbage
    number."""
    reps_b, short = _calibrate_reps(step_fn, u0, span_s, max_reps,
                                    clock=clock)
    if short:
        raise RuntimeError(
            f"per-call compute too small: even {max_reps} reps span "
            f"<{0.6 * span_s:.2f} s of device work; raise max_reps or "
            f"use a larger problem")
    return chain_slope(step_fn, u0, 1, reps_b, batches=batches,
                       clock=clock)


def bench_rounds_paired(named_fns, u0, steps_per_call,
                        span_s: float = 0.5, batches: int = 3,
                        max_reps: int = 3000):
    """Jit, warm, and time a set of round fns with
    :func:`calibrated_slope_paired`; print one line per variant and
    return ``{name: Gcells*steps/s}``.

    The shared driver of the A/B tools (``tools/ab_fused_g.py`` /
    ``ab_fused_h.py`` / ``ab_uni_single.py``): a variant that fails to
    compile prints FAILED and is excluded; a variant whose slope is
    noise prints so rather than reporting a garbage rate.
    ``steps_per_call[name]`` is how many stencil steps one call
    advances (K for temporal rounds).
    """
    import math

    runs = {}
    for name, fn in named_fns.items():
        run = jax.jit(fn)
        try:
            sync(run(u0))
        except Exception as e:  # noqa: BLE001 — surface, don't crash the A/B
            print(f"{name:26s}: FAILED {type(e).__name__}: {e}")
            continue
        runs[name] = run
    pers = calibrated_slope_paired(runs, u0, span_s=span_s,
                                   batches=batches, max_reps=max_reps)
    cells = math.prod(u0.shape)
    out = {}
    for name, per in pers.items():
        if per is None:
            print(f"{name:26s}: no trustworthy slope "
                  f"(non-positive, or max_reps spans <60% of span_s)")
            continue
        k = steps_per_call[name]
        g = cells * k / per / 1e9
        print(f"{name:26s}: {per*1e3:8.2f} ms/call {per/k*1e6:9.1f} "
              f"us/step {g:7.1f} Gcells*steps/s")
        out[name] = g
    return out


def calibrated_slope_paired(named_fns, u0, span_s: float = 0.5,
                            batches: int = 3, max_reps: int = 3000, *,
                            clock: Optional[Clock] = None):
    """Paired :func:`calibrated_slope` over several step fns.

    Every batch interleaves ALL variants' endpoint measurements, so
    clock drift lands on each variant alike and the
    min-of-raw-endpoints slope compares like with like. Returns
    ``{name: seconds per call}``; a variant whose slope comes out
    non-positive maps to ``None`` (surface it, don't guess), and so
    does one in the short-span regime (here a ``None`` keeps the other
    variants' paired comparison alive where :func:`calibrated_slope`
    would raise).
    """
    reps = {}
    short_span = set()
    for name, fn in named_fns.items():
        reps[name], short = _calibrate_reps(fn, u0, span_s, max_reps,
                                            clock=clock)
        if short:
            short_span.add(name)
    timed = [n for n in named_fns if n not in short_span]
    t_a = {n: [] for n in timed}
    t_b = {n: [] for n in timed}
    for _ in range(batches):
        for name in timed:
            t_a[name].append(chain_time(named_fns[name], u0, 1,
                                        clock=clock))
            t_b[name].append(chain_time(named_fns[name], u0,
                                        reps[name], clock=clock))
    out = {}
    for name in named_fns:
        if name in short_span:
            out[name] = None
            continue
        per = (min(t_b[name]) - min(t_a[name])) / (reps[name] - 1)
        out[name] = per if per > 0 else None
    return out
