"""Run telemetry: a structured JSONL event stream + liveness heartbeat.

The reference project's report studies its runs through Paraver traces
(Heat.pdf §7: blocking-send phases, per-step communication cost, the
Allreduce stall pattern) — numbers read off a screen, never machine
artifacts. Production TPU simulation stacks (the CFD framework of
arXiv:2108.11076, the Ising campaign driver of arXiv:1903.11714) treat
per-step telemetry as a framework feature instead: every chunk of work
leaves a record a tool can aggregate, and an external probe can tell a
live run from a hung one without attaching a debugger.

:class:`Telemetry` is that sink. One JSON object per line, append-only
(a resumed run continues the same file), schema-versioned. Events share
an envelope — ``schema``, ``event``, ``t_wall`` (unix seconds),
``t_mono`` (monotonic seconds, robust to clock steps) — and carry:

- ``run_header``: the full config, ``solver.explain``'s resolved
  execution path, mesh/topology, jax/backend versions (one per run
  segment; idempotent within one sink);
- ``chunk``: per stream-chunk progress — absolute ``step``, ``steps``
  advanced, chunk ``wall_s``, throughput (``steps_per_s``,
  ``mcells_steps_per_s``, ``hbm_gb_s`` via
  :class:`utils.profiling.StepStats`), ``residual``/``converged`` when
  converge-mode checks ran, the guard verdict ``finite``;
- ``checkpoint_save``: save latency + generation (rollback LOAD
  latency rides the ``rollback`` event as ``load_wall_s``);
- supervisor lifecycle: ``guard_trip``, ``retry``, ``rollback``,
  ``signal``, ``permanent_failure``, ``run_end``.

The contract matches the runtime guard's (SEMANTICS.md "Runtime guard
and supervisor"): telemetry OBSERVES, it never participates. No event
is computed inside a traced/compiled region, no config field changes,
and the compiled programs a telemetry-enabled run executes are the
same cached executables an un-instrumented run uses (pinned by
``tests/test_telemetry.py::test_telemetry_does_not_change_compiled_
programs``). A sink that hits an I/O error (disk full, path yanked)
warns once and goes quiet rather than killing a week-long run.

``tools/metrics_report.py`` ingests the JSONL and renders the run
summary (throughput percentiles, outliers, retry/guard timeline,
checkpoint overhead share).
"""

from __future__ import annotations

import json
import os
import time
import warnings
from typing import Optional

SCHEMA_VERSION = 1


class Telemetry:
    """Append-only JSONL event sink + optional heartbeat file.

    ``path`` may be None for a heartbeat-only sink. The heartbeat file
    is rewritten atomically (tmp + rename) at most every
    ``heartbeat_interval_s`` seconds, on each event, so an external
    probe can ``stat``/read it without ever seeing a torn write::

        {"t_wall": ..., "t_mono": ..., "pid": ..., "step": ...,
         "events": ..., "last_event": ...}

    Use as a context manager or call :meth:`close`; either flushes and
    closes the stream (events are flushed per line regardless, so a
    SIGKILL loses at most the line being written).
    """

    def __init__(self, path=None, heartbeat=None,
                 heartbeat_interval_s: float = 0.0):
        self.path = str(path) if path is not None else None
        self.heartbeat_path = (str(heartbeat) if heartbeat is not None
                               else None)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        for p in (self.path, self.heartbeat_path):
            # Parent dirs are created like the checkpoint writer's
            # (utils/checkpoint.py): `--metrics runs/plate.jsonl` must
            # not require a pre-existing runs/.
            if p is not None and os.path.dirname(p):
                os.makedirs(os.path.dirname(p), exist_ok=True)
        self._f = open(self.path, "a") if self.path is not None else None
        self._dead = False
        self._header_done = False
        self._events = 0
        self._last_event: Optional[str] = None
        self._last_step: Optional[int] = None
        self._last_heartbeat_mono: Optional[float] = None
        # Absolute-step offset for chunk events: solve_stream counts
        # steps from its own start, the supervisor restarts streams on
        # rollback — it sets this to each segment's base so events
        # carry absolute steps.
        self.step_offset = 0

    # -- core ------------------------------------------------------------

    def emit(self, event: str, **fields) -> None:
        """Write one event line. Never raises: telemetry is an
        observer, and an observer's disk-full must not kill the run —
        the sink warns once and goes quiet instead."""
        if self._dead:
            return
        rec = {"schema": SCHEMA_VERSION, "event": event,
               "t_wall": time.time(), "t_mono": time.monotonic()}
        rec.update(fields)
        try:
            if self._f is not None:
                self._f.write(json.dumps(rec) + "\n")
                self._f.flush()
        except (OSError, ValueError, TypeError) as e:
            self._dead = True
            warnings.warn(f"telemetry sink {self.path!r} disabled after "
                          f"write failure: {e}", RuntimeWarning)
            return
        self._events += 1
        self._last_event = event
        if "step" in fields:
            self._last_step = fields["step"]
        self._maybe_heartbeat(rec["t_mono"])

    def _maybe_heartbeat(self, t_mono: float) -> None:
        if self.heartbeat_path is None:
            return
        if (self._last_heartbeat_mono is not None
                and t_mono - self._last_heartbeat_mono
                < self.heartbeat_interval_s):
            return
        self.heartbeat()

    def heartbeat(self) -> None:
        """Atomically rewrite the heartbeat file (tmp + rename — a
        reader never sees a torn write). Safe to call directly from a
        long host-side wait."""
        if self.heartbeat_path is None or self._dead:
            return
        doc = {"t_wall": time.time(), "t_mono": time.monotonic(),
               "pid": os.getpid(), "events": self._events,
               "last_event": self._last_event, "step": self._last_step}
        tmp = f"{self.heartbeat_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, self.heartbeat_path)
        except OSError as e:
            # Disable ONLY the heartbeat: the JSONL stream is an
            # independent sink and must keep its terminal run_end even
            # when the probe file's filesystem goes away.
            self.heartbeat_path = None
            warnings.warn(f"telemetry heartbeat disabled after write "
                          f"failure: {e}", RuntimeWarning)
            return
        self._last_heartbeat_mono = doc["t_mono"]

    # -- typed events ----------------------------------------------------

    def run_header(self, config, **extra) -> None:
        """Emit the run-header event: config, resolved execution path
        (``solver.explain``), topology, versions. Idempotent per sink —
        the supervisor's rollback segments re-enter ``solve_stream``
        without duplicating headers."""
        if self._header_done or self._dead:
            return
        self._header_done = True
        import jax

        doc = {"config": json.loads(config.to_json()),
               "schema_version": SCHEMA_VERSION,
               "jax_version": jax.__version__}
        try:
            import numpy as np

            doc["numpy_version"] = np.__version__
            devs = jax.devices()
            doc["platform"] = devs[0].platform
            doc["device_count"] = len(devs)
            doc["process_index"] = jax.process_index()
            doc["process_count"] = jax.process_count()
            doc["mesh"] = (list(config.mesh_shape)
                           if config.mesh_shape is not None else None)
        except Exception as e:  # noqa: BLE001 — observation-only
            doc["topology_error"] = f"{type(e).__name__}: {e}"
        try:
            from parallel_heat_tpu.solver import explain

            ex = explain(config)
            ex["shape"] = list(ex["shape"])
            if ex.get("mesh"):
                ex["mesh"] = list(ex["mesh"])
            doc["explain"] = ex
        except Exception as e:  # noqa: BLE001 — a config explain can't
            # resolve must still produce a header, not kill the run
            doc["explain_error"] = f"{type(e).__name__}: {e}"
        doc.update(extra)
        self.emit("run_header", **doc)

    def chunk(self, *, step: int, steps: int, wall_s: float, cells: int,
              bytes_per_cell: int, residual=None, converged=None,
              finite=None) -> None:
        """Emit one per-chunk progress event. ``step`` is absolute
        (``step_offset`` already applied by the caller or applied here
        via the offset the supervisor set); rates come from
        :class:`utils.profiling.StepStats` and are null when the chunk
        wall time is too small to divide by."""
        from parallel_heat_tpu.utils.profiling import StepStats

        if wall_s > 0:
            st = StepStats(cells=cells, steps=steps, elapsed_s=wall_s,
                           bytes_per_cell=bytes_per_cell)
            rates = {"steps_per_s": st.steps_per_s,
                     "mcells_steps_per_s": st.mcells_steps_per_s,
                     "hbm_gb_s": st.effective_hbm_gb_s}
        else:
            rates = {"steps_per_s": None, "mcells_steps_per_s": None,
                     "hbm_gb_s": None}
        self.emit("chunk", step=self.step_offset + step, steps=steps,
                  wall_s=wall_s, cells=cells,
                  bytes_per_cell=bytes_per_cell, residual=residual,
                  converged=converged, finite=finite, **rates)

    def run_end(self, *, outcome: str, **fields) -> None:
        """Terminal event: ``outcome`` is ``complete`` /
        ``interrupted`` / ``permanent_failure``."""
        self.emit("run_end", outcome=outcome, **fields)

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
