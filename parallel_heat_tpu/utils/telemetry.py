"""Run telemetry: a structured JSONL event stream + liveness heartbeat.

The reference project's report studies its runs through Paraver traces
(Heat.pdf §7: blocking-send phases, per-step communication cost, the
Allreduce stall pattern) — numbers read off a screen, never machine
artifacts. Production TPU simulation stacks (the CFD framework of
arXiv:2108.11076, the Ising campaign driver of arXiv:1903.11714) treat
per-step telemetry as a framework feature instead: every chunk of work
leaves a record a tool can aggregate, and an external probe can tell a
live run from a hung one without attaching a debugger.

:class:`Telemetry` is that sink. One JSON object per line, append-only
(a resumed run continues the same file), schema-versioned. Events share
an envelope — ``schema``, ``event``, ``t_wall`` (unix seconds),
``t_mono`` (monotonic seconds, robust to clock steps), ``hostname``,
plus ``job_id`` and the causal trace triple ``trace_id`` / ``span_id``
/ ``parent_span_id`` when set (heatd workers stamp both, so a run
joins its job and its submit's trace by content — ``utils/tracing.py``
and ``tools/heattrace.py`` are the consumers) — and carry:

- ``run_header``: the full config, ``solver.explain``'s resolved
  execution path, mesh/topology, jax/backend versions (one per run
  segment; idempotent within one sink);
- ``chunk``: per stream-chunk progress — absolute ``step``, ``steps``
  advanced, chunk ``wall_s``, throughput (``steps_per_s``,
  ``mcells_steps_per_s``, ``hbm_gb_s`` via
  :class:`utils.profiling.StepStats`), ``residual``/``converged`` when
  converge-mode checks ran, the guard verdict ``finite``;
- ``diagnostics``: fused grid-stats samples (``solver.grid_stats``
  under ``HeatConfig.diag_interval``): ``min``/``max``/``heat``/
  ``update_l2``/``update_linf`` + ``steps_since``;
- ``checkpoint_save``: save latency + generation (rollback LOAD
  latency rides the ``rollback`` event as ``load_wall_s``);
- supervisor lifecycle: ``guard_trip``, ``progress_trip`` (residual
  stall / heat-content drift), ``retry``, ``rollback``, ``signal``,
  ``permanent_failure``, ``run_end``;
- distributed supervision (``parallel/coordinator.py``, SEMANTICS.md
  "Distributed supervision" — multi-process runs only, each with the
  emitting rank in the envelope's ``process_index``):
  ``barrier_wait`` (per chunk boundary: seconds this rank spent in
  the consensus exchanges — the per-rank straggler signal
  ``tools/metrics_report.py``'s shard-glob mode renders as p50/p99
  rows), ``consensus_verdict`` (a boundary whose MERGED verdict
  demanded an action: ``action`` nan/drift/transient/interrupt plus
  the merged fields — every rank's shard must carry the identical
  action at the identical step), ``peer_lost`` (a dead peer detected:
  ``lost`` ranks, ``survivors``, ``waited_s`` vs ``timeout_s``);
- ensemble events (the batched engine, SEMANTICS.md "Ensemble" —
  member-scoped events carry a ``member`` field, the member-axis
  extension of this schema): ``ensemble_window`` (per dispatch window:
  ``step``/``batch``/``live``/``done``), ``member_converged`` (a
  member's epsilon verdict latched: ``member``/``step``/``residual``),
  ``member_end`` (per-member terminal row: ``member``/``step``/
  ``converged``/``residual``/``finite``), ``ensemble_compaction``
  (``step``/``from_members``/``to_members``), ``pack_header`` (a
  packed heatd dispatch: ``pack``/``members``/``job_ids``/
  ``est_hbm_bytes``); per-member ``diagnostics`` samples likewise
  carry ``member``. ``tools/metrics_report.py``'s ensemble section
  aggregates these;
- ``cache_prefix_resume`` (heatd workers, SEMANTICS.md "Cache
  soundness"): this run resumed from a cache-seeded donor generation
  instead of step 0 — ``key``/``donor``/``generation_step`` attribute
  the skipped prefix; the O(1) exact-hit path never runs a worker, so
  its provenance lives on the JOURNAL (``cache_hit`` line, rendered
  as a span by ``tools/heattrace.py``), not in any telemetry stream.

The envelope also carries ``process_index``/``process_count``;
multi-process runs shard the JSONL and heartbeat per process
(:func:`shard_path`, ``.pN`` suffix) so hosts never interleave writes.

The contract matches the runtime guard's (SEMANTICS.md "Runtime guard
and supervisor"): telemetry OBSERVES, it never participates. No event
is computed inside a traced/compiled region, no config field changes,
and the compiled programs a telemetry-enabled run executes are the
same cached executables an un-instrumented run uses (pinned by
``tests/test_telemetry.py::test_telemetry_does_not_change_compiled_
programs``). A sink that hits an I/O error (disk full, path yanked)
warns once and goes quiet rather than killing a week-long run.

``tools/metrics_report.py`` ingests the JSONL and renders the run
summary (throughput percentiles, outliers, retry/guard timeline,
checkpoint overhead share).
"""

from __future__ import annotations

import json
import os
import queue
import socket
import threading
import time
import warnings
from typing import Optional

from parallel_heat_tpu.utils.tracing import TraceContext

# Schema 2 (heattrace): the envelope gained `hostname` (fleet joins —
# a rank is a host, and straggler attribution must name one) and, when
# set, `job_id` (heatd workers stamp it so a run joins its job by
# content, not path convention) and the causal trace triple
# `trace_id`/`span_id`/`parent_span_id` (utils/tracing.py). Consumers
# ignore unknown envelope fields by contract, so v1 readers keep
# working.
SCHEMA_VERSION = 2

# Bounded writer queue (async_io mode): deep enough that bursts (a
# rollback's retry/rollback/chunk cluster) never block the run loop,
# bounded so a wedged filesystem exerts backpressure instead of
# growing an unbounded heap of pending events.
_ASYNC_QUEUE_DEPTH = 1024
# Events that must reach the heartbeat immediately, throttle or not:
# an external probe reading a terminal state must never see a stale
# mid-run heartbeat for up to min_interval afterwards.
_FORCE_HEARTBEAT_EVENTS = ("run_end", "permanent_failure", "signal")


def _process_info():
    """(process_index, process_count) of this runtime, (0, 1) when jax
    is unavailable or not yet set up. Deliberately side-effect-free:
    ``jax.process_index()`` force-initializes the backend, and a sink
    constructed before ``jax.distributed.initialize()`` must neither
    break that later call nor lock in a single-process view it caused
    itself — so the backend is queried only when ALREADY initialized,
    with ``jax.distributed``'s coordination state as the pre-backend
    source of truth."""
    try:
        import jax
        from jax._src import xla_bridge

        if getattr(xla_bridge, "backends_are_initialized",
                   lambda: False)():
            return int(jax.process_index()), int(jax.process_count())
        from jax._src import distributed

        st = distributed.global_state
        pi = getattr(st, "process_id", None)
        pc = getattr(st, "num_processes", None)
        if pi is not None and pc:
            return int(pi), int(pc)
    except Exception:  # noqa: BLE001 — observation-only
        pass
    return 0, 1


def shard_path(path: str, process_index: int, process_count: int) -> str:
    """Per-process sink path: ``runs/m.jsonl`` -> ``runs/m.p3.jsonl``
    when ``process_count > 1`` (unchanged for single-process runs).

    Multi-host runs must never interleave appends into one file — JSONL
    has no record framing beyond the newline, so concurrent writers
    from different hosts tear each other's lines. Each process writes
    its own shard; ``tools/metrics_report.py`` accepts a glob
    (``runs/m*.jsonl``) and merges shards by ``t_mono``.
    """
    if process_count <= 1:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.p{process_index}{ext}"


class Telemetry:
    """Append-only JSONL event sink + optional heartbeat file.

    ``path`` may be None for a heartbeat-only sink. The heartbeat file
    is rewritten atomically (tmp + rename) at most every
    ``heartbeat_interval_s`` seconds (the throttle ``min_interval``,
    default 1 s — short chunks must not pay a write+rename per
    boundary; terminal events and :meth:`close` force a final rewrite
    so probes never read a stale end state), so an external probe can
    ``stat``/read it without ever seeing a torn write::

        {"t_wall": ..., "t_mono": ..., "pid": ..., "step": ...,
         "events": ..., "last_event": ..., "interval_s": ...}

    ``async_io=True`` moves all file I/O (JSONL append + heartbeat
    rename) to a bounded-queue background writer thread: ``emit``
    stamps the envelope on the caller's clock and returns after an
    enqueue, so the run loop never blocks on the filesystem (a full
    queue — a wedged disk — exerts backpressure rather than dropping
    events). Event order is the emit order either way. The default
    stays synchronous: same-thread writes are simpler to reason about
    for tests and short tools; the CLI and the pipelined stream opt
    in.

    Use as a context manager or call :meth:`close`; either drains the
    writer (async mode), rewrites a final heartbeat, and closes the
    stream (events are flushed per line regardless, so a SIGKILL loses
    at most the lines still queued).
    """

    def __init__(self, path=None, heartbeat=None,
                 heartbeat_interval_s: float = 1.0,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 async_io: bool = False,
                 trace: Optional[TraceContext] = None,
                 job_id: Optional[str] = None):
        # Causal trace context (utils/tracing.py): explicit argument,
        # else inherited from the environment — a daemon-spawned
        # worker's sink joins its submit's trace without the worker
        # threading a context through every call site. None = the
        # envelope simply carries no trace triple.
        self.trace = trace if trace is not None \
            else TraceContext.from_env()
        self.job_id = job_id
        try:
            self.hostname = socket.gethostname()
        except OSError:  # pragma: no cover — observation-only
            self.hostname = None
        if process_index is None or process_count is None:
            pi, pc = _process_info()
            process_index = pi if process_index is None else process_index
            process_count = pc if process_count is None else process_count
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        # Multi-process runs shard both sinks per process (JSONL appends
        # from several hosts would tear each other's lines; concurrent
        # heartbeat renames would flap between processes' views).
        if path is not None:
            path = shard_path(str(path), self.process_index,
                              self.process_count)
        if heartbeat is not None:
            heartbeat = shard_path(str(heartbeat), self.process_index,
                                   self.process_count)
        self.path = path
        self.heartbeat_path = heartbeat
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        for p in (self.path, self.heartbeat_path):
            # Parent dirs are created like the checkpoint writer's
            # (utils/checkpoint.py): `--metrics runs/plate.jsonl` must
            # not require a pre-existing runs/.
            if p is not None and os.path.dirname(p):
                os.makedirs(os.path.dirname(p), exist_ok=True)
        self._f = open(self.path, "a") if self.path is not None else None
        self._dead = False
        self._header_done = False
        self._events = 0
        self._last_event: Optional[str] = None
        self._last_step: Optional[int] = None
        self._last_residual: Optional[float] = None
        self._last_heartbeat_mono: Optional[float] = None
        self._events_at_heartbeat = 0
        # One lock around the write+state path: the async checkpointer
        # and the writer thread emit from worker threads while the run
        # loop emits from the main thread — interleaved JSONL lines
        # must never tear each other.
        self._io_lock = threading.RLock()
        self._queue: Optional[queue.Queue] = None
        self._writer: Optional[threading.Thread] = None
        if async_io:
            self._queue = queue.Queue(maxsize=_ASYNC_QUEUE_DEPTH)
            self._writer = threading.Thread(
                target=self._writer_loop, name="telemetry-writer",
                daemon=True)
            self._writer.start()
        # Absolute-step offset for chunk events: solve_stream counts
        # steps from its own start, the supervisor restarts streams on
        # rollback — it sets this to each segment's base so events
        # carry absolute steps.
        self.step_offset = 0

    # -- core ------------------------------------------------------------

    def emit(self, event: str, **fields) -> None:
        """Write one event line (enqueue it in ``async_io`` mode — the
        envelope is stamped here, on the caller's clock). Never raises:
        telemetry is an observer, and an observer's disk-full must not
        kill the run — the sink warns once and goes quiet instead."""
        if self._dead:
            return
        rec = {"schema": SCHEMA_VERSION, "event": event,
               "t_wall": time.time(), "t_mono": time.monotonic(),
               "process_index": self.process_index,
               "process_count": self.process_count,
               "hostname": self.hostname}
        if self.job_id is not None:
            rec["job_id"] = self.job_id
        if self.trace is not None:
            rec.update(self.trace.to_dict())
        rec.update(fields)
        if self._queue is not None:
            # Blocking put: a full queue (wedged filesystem) slows the
            # run instead of silently dropping lifecycle events the
            # chaos matrix certifies on.
            self._queue.put(rec)
            return
        self._write_record(rec)

    def _writer_loop(self) -> None:
        q = self._queue
        while True:
            rec = q.get()
            if rec is None:  # close() sentinel
                q.task_done()
                return
            try:
                self._write_record(rec)
            except Exception as e:  # noqa: BLE001 — a writer-thread
                # crash must never take the run down OR wedge close()
                with self._io_lock:
                    already = self._dead
                    self._dead = True
                if not already:
                    warnings.warn(
                        f"telemetry writer thread disabled after "
                        f"unexpected error: {e}", RuntimeWarning)
            finally:
                q.task_done()

    def _write_record(self, rec) -> None:
        """Serialize + append one record and update the heartbeat
        state. Runs on the writer thread in ``async_io`` mode, inline
        otherwise; the lock also serializes direct emits from other
        threads (the async checkpointer's commit callback)."""
        with self._io_lock:
            if self._dead:
                return
            event = rec["event"]
            try:
                if self._f is not None:
                    self._f.write(json.dumps(rec) + "\n")
                    self._f.flush()
            except (OSError, ValueError, TypeError) as e:
                self._dead = True
                warnings.warn(f"telemetry sink {self.path!r} disabled "
                              f"after write failure: {e}",
                              RuntimeWarning)
                return
            self._events += 1
            self._last_event = event
            if rec.get("step") is not None:
                self._last_step = rec["step"]
            if rec.get("residual") is not None:
                self._last_residual = rec["residual"]
            self._maybe_heartbeat(rec["t_mono"],
                                  force=event in _FORCE_HEARTBEAT_EVENTS)

    def _maybe_heartbeat(self, t_mono: float, force: bool = False) -> None:
        if self.heartbeat_path is None:
            return
        if (not force and self._last_heartbeat_mono is not None
                and t_mono - self._last_heartbeat_mono
                < self.heartbeat_interval_s):
            # Throttled (min_interval): short chunks must not pay a
            # write+fsync-rename per boundary; close()/terminal events
            # still publish the final state.
            return
        self.heartbeat()

    def heartbeat(self) -> None:
        """Atomically rewrite the heartbeat file (tmp + rename — a
        reader never sees a torn write). Safe to call directly from a
        long host-side wait."""
        with self._io_lock:
            if self.heartbeat_path is None or self._dead:
                return
            # `last_step`/`last_event`/`residual` make the heartbeat
            # self-sufficient: an external liveness probe (or
            # `tools/monitor.py --once`) can report progress without
            # parsing the JSONL at all. `step` is kept as a legacy
            # alias of `last_step`; `interval_s` tells probes how
            # stale a healthy heartbeat may legitimately be.
            doc = {"t_wall": time.time(), "t_mono": time.monotonic(),
                   "pid": os.getpid(), "events": self._events,
                   "last_event": self._last_event,
                   "step": self._last_step,
                   "last_step": self._last_step,
                   "residual": self._last_residual,
                   "interval_s": self.heartbeat_interval_s}
            tmp = f"{self.heartbeat_path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(doc, f)
                os.replace(tmp, self.heartbeat_path)
            except OSError as e:
                # Disable ONLY the heartbeat: the JSONL stream is an
                # independent sink and must keep its terminal run_end
                # even when the probe file's filesystem goes away.
                self.heartbeat_path = None
                warnings.warn(f"telemetry heartbeat disabled after "
                              f"write failure: {e}", RuntimeWarning)
                return
            self._last_heartbeat_mono = doc["t_mono"]
            self._events_at_heartbeat = self._events

    # -- typed events ----------------------------------------------------

    def run_header(self, config, **extra) -> None:
        """Emit the run-header event: config, resolved execution path
        (``solver.explain``), topology, versions. Idempotent per sink —
        the supervisor's rollback segments re-enter ``solve_stream``
        without duplicating headers."""
        if self._header_done or self._dead:
            return
        self._header_done = True
        import jax

        doc = {"config": json.loads(config.to_json()),
               # The ABSOLUTE step target: a resumed run's config.steps
               # counts only the REMAINING steps while chunk events
               # carry absolute steps (step_offset was set before this
               # header) — consumers (tools/monitor.py) must read the
               # target from here, not from config.steps, or a resumed
               # run's progress fraction exceeds 100%.
               "steps_total": self.step_offset + config.steps,
               "schema_version": SCHEMA_VERSION,
               "jax_version": jax.__version__}
        try:
            import numpy as np

            doc["numpy_version"] = np.__version__
            devs = jax.devices()
            doc["platform"] = devs[0].platform
            doc["device_count"] = len(devs)
            # Schema 2: the ENVELOPE's process_index/process_count are
            # authoritative for rank identity (thread-simulated ranks
            # set them explicitly; heattrace lanes key off them). The
            # runtime's own view stays available under distinct names
            # instead of clobbering the envelope on this one event.
            doc["runtime_process_index"] = jax.process_index()
            doc["runtime_process_count"] = jax.process_count()
            doc["mesh"] = (list(config.mesh_shape)
                           if config.mesh_shape is not None else None)
        except Exception as e:  # noqa: BLE001 — observation-only
            doc["topology_error"] = f"{type(e).__name__}: {e}"
        try:
            from parallel_heat_tpu.solver import explain

            ex = explain(config)
            ex["shape"] = list(ex["shape"])
            if ex.get("mesh"):
                ex["mesh"] = list(ex["mesh"])
            doc["explain"] = ex
        except Exception as e:  # noqa: BLE001 — a config explain can't
            # resolve must still produce a header, not kill the run
            doc["explain_error"] = f"{type(e).__name__}: {e}"
        doc.update(extra)
        self.emit("run_header", **doc)

    def chunk(self, *, step: int, steps: int, wall_s: float, cells: int,
              bytes_per_cell: int, residual=None, converged=None,
              finite=None, gap_s=None, dispatch_s=None,
              drain_wait_s=None, observe_s=None,
              exchange_s=None) -> None:
        """Emit one per-chunk progress event. ``step`` is absolute
        (``step_offset`` already applied by the caller or applied here
        via the offset the supervisor set); rates come from
        :class:`utils.profiling.StepStats` and are null when the chunk
        wall time is too small to divide by.

        The optional pipeline-timing fields (included only when the
        stream measured them): ``gap_s`` — device idle charged to this
        chunk (sync loop: host time between the previous chunk's
        completion and this dispatch, the observer/checkpoint/caller
        tax; pipelined loop: the measured starvation lower bound from
        the drain-time is_ready probe); ``dispatch_s`` — host
        time inside the async dispatch call; ``drain_wait_s`` — host
        time blocked waiting for this chunk's first scalar (the
        device-bound signal: ~0 everywhere means the host, not the
        device, is the bottleneck); ``observe_s`` — host time spent on
        this chunk's observers after completion. ``tools/
        metrics_report.py``'s pipeline section aggregates these.

        ``exchange_s`` — halo-exchange wall attributed to this chunk's
        critical path, when the producer measured it (the scaling
        study's standalone timing of the exchange ops inside the
        ``heat_halo_exchange_*`` named scopes, or a profiler-derived
        import); ``metrics_report`` turns it into the gateable
        ``exchange_share`` metric. Never measured by ``solve_stream``
        itself — the exchange lives inside the compiled chunk."""
        from parallel_heat_tpu.utils.profiling import StepStats

        if wall_s > 0:
            st = StepStats(cells=cells, steps=steps, elapsed_s=wall_s,
                           bytes_per_cell=bytes_per_cell)
            rates = {"steps_per_s": st.steps_per_s,
                     "mcells_steps_per_s": st.mcells_steps_per_s,
                     "hbm_gb_s": st.effective_hbm_gb_s}
        else:
            rates = {"steps_per_s": None, "mcells_steps_per_s": None,
                     "hbm_gb_s": None}
        timing = {k: v for k, v in (("gap_s", gap_s),
                                    ("dispatch_s", dispatch_s),
                                    ("drain_wait_s", drain_wait_s),
                                    ("observe_s", observe_s),
                                    ("exchange_s", exchange_s))
                  if v is not None}
        self.emit("chunk", step=self.step_offset + step, steps=steps,
                  wall_s=wall_s, cells=cells,
                  bytes_per_cell=bytes_per_cell, residual=residual,
                  converged=converged, finite=finite, **rates,
                  **timing)

    def diagnostics(self, *, step: int, **stats) -> None:
        """Emit one grid-diagnostics sample (``solver.grid_stats`` under
        ``HeatConfig.diag_interval``): min/max/heat/update_l2/
        update_linf plus ``steps_since`` (steps since the previous
        sample). ``step`` is stream-relative; the supervisor's
        ``step_offset`` is applied here, same as :meth:`chunk`."""
        self.emit("diagnostics", step=self.step_offset + step, **stats)

    def run_end(self, *, outcome: str, **fields) -> None:
        """Terminal event: ``outcome`` is ``complete`` /
        ``interrupted`` / ``permanent_failure``."""
        self.emit("run_end", outcome=outcome, **fields)

    # -- lifecycle -------------------------------------------------------

    def close(self) -> None:
        """Drain the writer (async mode), publish a final heartbeat,
        close the stream. Idempotent."""
        if self._writer is not None:
            # Sentinel + join: every queued record lands before the
            # file closes. The timeout is defensive — a wedged disk
            # must not hang process exit forever; the warn-once dead
            # path inside the worker normally guarantees progress.
            self._queue.put(None)
            self._writer.join(timeout=30.0)
            self._writer = None
            self._queue = None
        if (self.heartbeat_path is not None and not self._dead
                and self._events > self._events_at_heartbeat):
            # Events landed since the last (throttled) rewrite: the
            # probe file must reflect the final state.
            self.heartbeat()
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    def __enter__(self) -> "Telemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
