"""Grid file I/O — byte-compatible with the reference's ``prtdat``.

``prtdat`` format (identical in both reference programs,
``mpi/mpi_heat_improved_persistent_stat.c:326-341``,
``cuda/cuda_heat.cu:285-300``): iterate ``iy`` from ``ny-1`` down to 0
(outer) and ``ix`` from 0 to ``nx-1`` (inner), printing ``u[ix, iy]`` with
C ``"%6.1f"``, a single space between values, and a newline after each
``iy`` row. So each output *line* is one ``iy`` column of the array.

A native C++ fast path (``parallel_heat_tpu/native``) is used when its
shared library has been built; the NumPy/Python path below is the always-
available fallback and the semantics oracle.
"""

from __future__ import annotations

import os

import numpy as np


def _format_dat_python(u: np.ndarray) -> str:
    """Pure-Python reference formatter (slow, exact)."""
    nx, ny = u.shape
    lines = []
    for iy in range(ny - 1, -1, -1):
        lines.append(" ".join(f"{float(u[ix, iy]):6.1f}" for ix in range(nx)))
    return "\n".join(lines) + "\n"


def write_dat(path: str | os.PathLike, u, use_native: bool = True) -> None:
    """Write a 2D grid in the reference ``.dat`` text format."""
    u = np.asarray(u, dtype=np.float32)
    if u.ndim != 2:
        raise ValueError(f".dat format is 2D-only, got shape {u.shape}")
    if use_native:
        try:
            from parallel_heat_tpu.native import binding as _native

            if _native.available():
                _native.write_dat(str(path), u)
                return
        except Exception:
            pass  # fall back to Python writer
    with open(path, "w") as fp:
        fp.write(_format_dat_python(u))


def read_dat(path: str | os.PathLike, use_native: bool = True) -> np.ndarray:
    """Read a ``.dat`` file back into the ``(nx, ny)`` array convention."""
    if use_native:
        try:
            from parallel_heat_tpu.native import binding as _native

            if _native.available():
                return _native.read_dat(str(path))
        except Exception:
            pass  # fall back to Python parser
    rows = []
    with open(path) as fp:
        for line in fp:
            line = line.strip("\n")
            if not line.strip():
                continue
            rows.append([float(tok) for tok in line.split()])
    arr = np.array(rows, dtype=np.float32)  # (ny, nx), iy descending
    return arr[::-1].T.copy()  # back to u[ix, iy]
