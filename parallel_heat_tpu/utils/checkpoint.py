"""Checkpoint / resume — a capability gap in the reference (SURVEY.md §5:
state lives only in the two buffers; output only at the end).

Two layouts, selected automatically (``layout="auto"``):

- **gathered** (small grids): one ``.npz`` (grid + step counter +
  config fingerprint) with the grid gathered to host — cheap,
  dependency-free, human-greppable.
- **sharded** (large sharded grids): a ``<name>.ckpt/`` directory with
  a JSON manifest plus one ``.npz`` per process holding only that
  process's addressable shards, written shard-by-shard — the full grid
  is never materialized on any host (a 32768^2 f32 grid would cost a
  4 GiB host spike per snapshot through the gathered path), and resume
  rebuilds the global array via
  ``jax.make_array_from_single_device_arrays`` with no gather either.
  Multi-process runs write concurrently (each process owns its file);
  process 0 writes the manifest last, so a torn save leaves the
  previous generation's manifest — and therefore the previous
  snapshot — intact.
"""

from __future__ import annotations

import json
import os
import queue
import re
import threading
import time
from typing import Optional, Tuple

import numpy as np

from parallel_heat_tpu.config import HeatConfig

_FORMAT_VERSION = 1
_MANIFEST_VERSION = 2
# Shard files are generation-named; loaders and the pruner match this
# EXACT pattern so orphaned temp files can never be mistaken for data.
_SHARD_RE_TMPL = r"shards_{gen}_p\d{{5}}\.npz"
# Per-process LOCAL manifests of the coordinated two-phase commit
# (save_generation_coordinated): rename-committed alongside the shard
# file, pruned with the same generation discipline.
_LOCAL_MANIFEST_RE_TMPL = r"local_{gen}_p\d{{5}}\.json"
# Auto layout: shard when the grid is device-sharded and big enough
# that a host gather hurts; below this, one gathered file is simpler.
_SHARD_THRESHOLD_BYTES = 64 * 1024 * 1024


def _num_devices_of(grid) -> int:
    sharding = getattr(grid, "sharding", None)
    if sharding is None:
        return 1
    try:
        return len(sharding.device_set)
    except AttributeError:  # pragma: no cover - older jax
        return 1


def _wants_sharded_layout(grid, layout: str) -> bool:
    """The ONE sharded-vs-gathered decision (``layout="auto"``'s rule),
    shared by :func:`save_checkpoint` and the async checkpointer's
    verify path so the two can never diverge — a split predicate would
    let the worker gather a grid the writer then shards (or vice
    versa), paying a second full device->host transfer per save.
    Raises the explicit gathered+non-addressable error for both
    callers."""
    if layout not in ("auto", "gathered", "sharded"):
        raise ValueError(f"unknown checkpoint layout {layout!r}")
    fully_addressable = getattr(grid, "is_fully_addressable", True)
    if layout == "gathered" and not fully_addressable:
        raise ValueError(
            "layout='gathered' cannot snapshot a grid that spans "
            "non-addressable devices (multi-process run); use "
            "'sharded' or 'auto'")
    return (layout == "sharded"
            or (layout == "auto"
                and (not fully_addressable
                     or (_num_devices_of(grid) > 1
                         and grid.size * grid.dtype.itemsize
                         >= _SHARD_THRESHOLD_BYTES))))


def save_checkpoint(path, grid, step: int, config: HeatConfig,
                    compress: bool = False, layout: str = "auto") -> str:
    """Write a snapshot; returns the actual path written.

    ``layout``: ``"gathered"`` (one .npz, grid gathered to host),
    ``"sharded"`` (per-process shard directory, no host gather), or
    ``"auto"`` — sharded when the grid spans non-addressable devices
    (a multi-process run, where gathering is impossible, not merely
    slow) or is sharded over more than one device and large enough
    that gathering hurts (>= 64 MiB). See the module docstring for the
    formats.
    """
    if _wants_sharded_layout(grid, layout):
        return _save_sharded(path, grid, step, config, compress)
    return _save_gathered(path, grid, step, config, compress)


def _fsync_replace(tmp: str, dst: str) -> None:
    """Durable atomic publish: fsync the temp file, rename it over the
    destination, fsync the directory entry — a power loss at any point
    leaves either the old or the new file complete, never a torn one.
    """
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, dst)
    dirfd = os.open(os.path.dirname(os.path.abspath(dst)) or ".",
                    os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def _save_gathered(path, grid, step: int, config: HeatConfig,
                   compress: bool = False) -> str:
    """One .npz with the grid gathered to host; returns the path
    written (always .npz — normalized here rather than letting
    np.savez append it silently).

    The write is atomic (temp file + ``os.replace``): the periodic
    checkpointing driver (``solve_stream`` / ``--checkpoint-every``)
    overwrites one rolling file, and a crash mid-write must leave the
    previous snapshot intact — a torn file would defeat the feature's
    whole purpose.

    ``compress`` defaults to off: deflate on f32 field data measured
    8x slower for ~10% size (256 MB grid: 1.5 s vs 12 s) — at this
    framework's benchmark sizes a compressed periodic checkpoint would
    stall the run for minutes per snapshot. ``load_checkpoint`` reads
    either format.
    """
    path = str(path)
    if not path.endswith(".npz"):
        path += ".npz"
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        # The sharded layout creates its .ckpt directory (parents
        # included); the gathered layout must extend the same courtesy
        # to a not-yet-existing parent (`--checkpoint runs/ck` on a
        # fresh host) instead of dying inside np.savez.
        os.makedirs(parent, exist_ok=True)
    # Pid-unique temp name (must end .npz or np.savez appends it): two
    # concurrent savers of the same rolling file can never clobber each
    # other's in-flight temp, and a SIGKILLed writer's orphan is
    # recognizably stale (pruned below) instead of being the next
    # writer's target. The destination itself is only ever touched by
    # the atomic _fsync_replace, so a kill at ANY point leaves either
    # the previous complete .npz or the new complete one — never a
    # truncated file as the only copy.
    tmp = f"{path}.tmp-{os.getpid()}.npz"
    _prune_gathered_orphans(path, keep=tmp)
    saver = np.savez_compressed if compress else np.savez
    try:
        saver(
            tmp,
            grid=np.asarray(grid),
            step=np.int64(step),
            config=np.frombuffer(config.to_json().encode(), dtype=np.uint8),
            version=np.int64(_FORMAT_VERSION),
        )
        _fsync_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def _prune_gathered_orphans(path: str, keep: str) -> None:
    """Remove stale ``<path>.tmp-<pid>.npz`` temps a SIGKILLed writer
    left next to a gathered checkpoint (exception paths clean up in
    ``finally``; a hard kill cannot). Loaders never read temps — the
    load path takes the exact destination name — so orphans are only a
    disk-space leak, but a rolling ``--checkpoint-every`` run would
    accumulate one per crashed generation forever. A temp whose
    embedded pid is still ALIVE on this host is a concurrent writer's
    in-flight file, not an orphan — left alone (the pid-unique names
    exist precisely so concurrent savers cannot clobber each other)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    base = os.path.basename(path) + ".tmp-"
    try:
        names = os.listdir(d)
    except OSError:
        return
    mine = os.path.basename(keep)
    for name in names:
        if not (name.startswith(base) and name.endswith(".npz")) \
                or name == mine:
            continue
        try:
            pid = int(name[len(base):-len(".npz")])
        except ValueError:
            pid = None
        if pid is not None:
            try:
                os.kill(pid, 0)  # alive (or not ours): not an orphan
                continue
            except ProcessLookupError:
                pass  # dead -> genuinely orphaned
            except OSError:
                continue  # EPERM etc.: exists, leave it
        try:
            os.unlink(os.path.join(d, name))
        except OSError:
            pass


def _ckpt_dir_of(path: str) -> str:
    """Directory path for the sharded layout of a checkpoint name."""
    path = str(path)
    if path.endswith(".ckpt"):
        return path
    if path.endswith(".npz"):
        path = path[:-4]
    return path + ".ckpt"


def _write_shard_file(d: str, grid, gen: str, proc: int,
                      compress: bool = False,
                      verify_finite: bool = False):
    """Write one process's shard ``.npz`` (rename-committed). Streams
    one zip member per shard — each device->host copy is released
    before the next is made, so peak host memory is one shard, never
    the grid. With ``verify_finite`` every gathered shard is checked
    finite on the SAME host copy the writer serializes (no second
    transfer); a non-finite shard aborts the write (no file lands) and
    returns ``(None, False)``. Returns ``(fname, finite)``."""
    import zipfile

    shards = sorted(grid.addressable_shards, key=lambda s: s.device.id)
    fname = f"shards_{gen}_p{proc:05d}.npz"
    # Leading dot: temp names must never match the shard-file pattern a
    # loader or pruner scans for (a crash can orphan them).
    tmp = os.path.join(d, f".tmp-{os.getpid()}-{fname}")
    try:
        mode = zipfile.ZIP_DEFLATED if compress else zipfile.ZIP_STORED
        with zipfile.ZipFile(tmp, "w", mode) as zf:
            for sh in shards:
                host = np.asarray(sh.data)
                if verify_finite and not bool(np.isfinite(host).all()):
                    return None, False
                with zf.open(f"d{sh.device.id}.npy", "w",
                             force_zip64=True) as fh:
                    np.lib.format.write_array(fh, host,
                                              allow_pickle=False)
        _fsync_replace(tmp, os.path.join(d, fname))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return fname, True


def _manifest_doc(grid, gen: str, step: int, config: HeatConfig,
                  process_count: int) -> dict:
    """The global generation manifest: device id -> block index for
    every process, computable on p0 without communication."""
    index_map = grid.sharding.devices_indices_map(grid.shape)
    devices = {}
    for dev, idx in index_map.items():
        devices[str(dev.id)] = {
            "process": dev.process_index,
            "index": [[sl.start or 0,
                       sl.stop if sl.stop is not None else n]
                      for sl, n in zip(idx, grid.shape)],
        }
    return {
        "version": _MANIFEST_VERSION,
        "generation": gen,
        "step": int(step),
        "config": config.to_json(),
        "shape": list(grid.shape),
        "dtype": str(grid.dtype),
        "mesh_shape": list(config.mesh_or_unit()),
        "process_count": process_count,
        "devices": devices,
    }


def _commit_manifest_and_prune(d: str, manifest: dict) -> None:
    """Atomically publish ``manifest.json`` (THE commit point of a
    sharded generation) and prune stale shard files, orphaned temps and
    foreign-generation local manifests — run only on process 0, only
    after every live process's shard file is known committed."""
    gen = manifest["generation"]
    mtmp = os.path.join(d, f".tmp-{os.getpid()}-manifest")
    with open(mtmp, "w") as f:
        json.dump(manifest, f)
    _fsync_replace(mtmp, os.path.join(d, "manifest.json"))
    live = _SHARD_RE_TMPL.format(gen=gen)
    live_local = _LOCAL_MANIFEST_RE_TMPL.format(gen=gen)
    for old in os.listdir(d):
        if old == "manifest.json":
            continue
        if re.fullmatch(live, old) or re.fullmatch(live_local, old):
            continue
        if old.startswith((".tmp-", "shards_", "local_")):
            try:
                os.unlink(os.path.join(d, old))
            except OSError:
                pass
    # A stale gathered .npz from an earlier, smaller run of the
    # same name must not shadow this directory at load time
    # (load_checkpoint prefers an existing file).
    stem_npz = d[:-5] + ".npz"
    if os.path.exists(stem_npz):
        try:
            os.unlink(stem_npz)
        except OSError:
            pass


def _save_sharded(path, grid, step: int, config: HeatConfig,
                  compress: bool = False) -> str:
    """Per-process shard directory; returns the ``.ckpt`` dir written.

    Each process writes ONE ``.npz`` holding its addressable shards
    (keyed ``d<device_id>``), copied device->host one shard at a time —
    peak host memory is a single shard, never the grid. Process 0
    writes ``manifest.json`` LAST (atomic temp+replace), stamping a
    fresh generation id: shard files are generation-named, so readers
    always see a consistent (old or new) set and a crash between the
    shard writes and the manifest write leaves the previous snapshot
    live. Stale generations are pruned after the manifest lands.

    Multi-process runs under a supervisor coordinator should go through
    :func:`save_generation_coordinated` instead: it replaces the
    device-collective barriers below with bounded KV-store exchanges
    and gates the manifest commit on every process's finite verdict.
    """
    import jax

    d = _ckpt_dir_of(path)
    os.makedirs(d, exist_ok=True)
    proc = jax.process_index()
    # The generation id must agree across processes without
    # communication; the step count (monotone within a run) is exactly
    # that, with the process count folded in so a re-save of the same
    # step from a different topology cannot leave stale shard files
    # (e.g. higher p-indices from a larger earlier run) matching the
    # live generation's pattern — they get pruned as a foreign
    # generation instead. A same-step same-topology re-save still
    # overwrites file-atomically.
    gen = f"s{int(step):012d}c{jax.process_count():04d}"
    _write_shard_file(d, grid, gen, proc, compress)

    if jax.process_count() > 1:  # pragma: no cover (multi-host barrier)
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("heat_ckpt_shards_written")

    if proc == 0:
        _commit_manifest_and_prune(
            d, _manifest_doc(grid, gen, step, config,
                             jax.process_count()))
    if jax.process_count() > 1:  # pragma: no cover (multi-host barrier)
        # Make save a proper collective: no process returns (and e.g.
        # immediately resumes) before the manifest is live.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("heat_ckpt_manifest_written")
    return d


def _load_sharded(d: str, expect_config: HeatConfig | None):
    """Load a ``.ckpt`` directory; returns ``(grid, step, config)``.

    Fast path (no gather): when the current topology matches the saved
    one (same process count; the saved mesh buildable on the current
    devices), every process loads only its own shard file and the
    global array is assembled with
    ``jax.make_array_from_single_device_arrays`` — device-resident,
    correctly sharded for the resuming solve. Single-process fallback
    for a topology mismatch: assemble the full grid on host from all
    shard files (the operational-resume path; still no *device* gather).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from parallel_heat_tpu.parallel.mesh import make_heat_mesh

    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    if man["version"] != _MANIFEST_VERSION:
        raise ValueError(f"unsupported checkpoint version {man['version']}")
    saved = HeatConfig.from_json(man["config"])
    step = int(man["step"])
    shape = tuple(man["shape"])
    if expect_config is not None and saved.shape != expect_config.shape:
        raise ValueError(
            f"checkpoint grid {saved.shape} != configured "
            f"{expect_config.shape}")
    gen = man["generation"]
    mesh_shape = tuple(man["mesh_shape"])
    n_needed = 1
    for m in mesh_shape:
        n_needed *= m

    same_topology = (jax.process_count() == man["process_count"]
                     and len(jax.devices()) >= n_needed)
    if same_topology:
        mesh = make_heat_mesh(mesh_shape)
        sharding = NamedSharding(mesh, P(*mesh.axis_names))
        index_map = sharding.devices_indices_map(shape)
        proc = jax.process_index()
        fname = os.path.join(d, f"shards_{gen}_p{proc:05d}.npz")
        arrays = []
        try:
            with np.load(fname) as z:
                for dev, idx in index_map.items():
                    if dev.process_index != proc:
                        continue
                    key = f"d{dev.id}"
                    info = man["devices"].get(str(dev.id))
                    want = [[sl.start or 0,
                             sl.stop if sl.stop is not None else n]
                            for sl, n in zip(idx, shape)]
                    if (key not in z or info is None
                            or info["index"] != want):
                        # Device numbering or the device->block
                        # assignment moved between runs (topology-aware
                        # mesh reorder, a different host layout, an
                        # explicit devices= mesh at save time):
                        # reassembling by id would place blocks at the
                        # wrong coordinates — fall back to host
                        # assembly, which trusts only the manifest's
                        # indices.
                        arrays = None
                        break
                    arrays.append(jax.device_put(z[key], dev))
        except OSError:
            # A missing/unreadable per-process shard file is a
            # topology mismatch in disguise (e.g. this process index
            # had no shard in the saved run), not a crash.
            arrays = None
        ok = arrays is not None
        if jax.process_count() > 1:  # pragma: no cover (multi-host)
            # The fast-path-vs-fall-back decision must be COLLECTIVE:
            # if some processes assembled their shards while others
            # hit an index mismatch, the mixed control flow would hang
            # at the next sync instead of failing cleanly.
            from jax.experimental import multihost_utils

            ok = bool(multihost_utils.process_allgather(
                np.array([ok])).all())
        if ok:
            grid = jax.make_array_from_single_device_arrays(
                shape, sharding, arrays)
            return grid, step, saved

    # Host assembly (topology changed): read every shard file and place
    # each block into a full host grid. Single-process operational
    # resume, AND the elastic-degrade path for a SMALLER multi-process
    # set: when every shard file of the saved (larger) run is visible
    # on this filesystem, each surviving process assembles the full
    # grid identically and `_replace_on_mesh` re-places it for the
    # resuming mesh via `_prepare_initial`'s per-shard slice transfers
    # — a 4-process checkpoint resumes on 2 processes (or 1) bit-
    # exactly, which is what a peer-lost exit's printed resume command
    # relies on (SEMANTICS.md "Distributed supervision").
    full = np.empty(shape, dtype=np.dtype(man["dtype"]))
    placed = 0
    pat = _SHARD_RE_TMPL.format(gen=re.escape(gen))
    for fname in sorted(os.listdir(d)):
        if not re.fullmatch(pat, fname):
            continue
        with np.load(os.path.join(d, fname)) as z:
            for key in z.files:
                info = man["devices"].get(key[1:])
                if info is None:
                    raise ValueError(
                        f"shard {key} in {fname} missing from manifest")
                sl = tuple(slice(a, b) for a, b in info["index"])
                full[sl] = z[key]
                placed += 1
    if placed != len(man["devices"]):
        raise ValueError(
            f"sharded checkpoint {d} incomplete: {placed} shard(s) "
            f"found, {len(man['devices'])} expected (saved from "
            f"{man['process_count']} process(es), loading on "
            f"{jax.process_count()}). Each process of the saving run "
            f"wrote its own shard file — if the save was multi-process, "
            f"copy every shards_{gen}_p*.npz onto one filesystem "
            f"(every resuming host must see all of them) before "
            f"resuming here.")
    return _replace_on_mesh(full, step, saved, expect_config)


def _replace_on_mesh(full: np.ndarray, step: int, saved: HeatConfig,
                     expect_config: HeatConfig | None):
    """Reshard-on-load: after host assembly (the topology-changed path),
    re-place the grid for the mesh the RESUMING run wants, when one is
    requested and fits the current devices. Reuses
    ``solver._prepare_initial``'s slice-transfer path — per-shard
    host->device slices, never a full-grid transfer to one device — so
    a checkpoint written on 8 devices resumes onto 4 (or 32) with the
    same memory profile as a fresh sharded start. Without a placeable
    ``expect_config`` mesh the host array is returned unchanged (the
    caller's solve re-places it)."""
    if expect_config is None:
        return full, step, saved
    mesh_wanted = expect_config.mesh_or_unit()
    if not any(dd > 1 for dd in mesh_wanted):
        return full, step, saved
    import jax

    n_dev = 1
    for dd in mesh_wanted:
        n_dev *= dd
    if n_dev > len(jax.devices()):
        return full, step, saved
    from parallel_heat_tpu.solver import _prepare_initial

    return _prepare_initial(expect_config, full), step, saved


# ---------------------------------------------------------------------------
# Retained generations (the supervisor's rollback targets)
# ---------------------------------------------------------------------------
#
# A supervised run keeps N checkpoints, not one: the newest may be the
# thing that needs rolling back FROM (a guard trip lands between the
# corruption and its detection at the next boundary, and a preemption
# can land mid-save). Each generation is an ordinary checkpoint (either
# layout, each individually crash-atomic) named
# ``<stem>.g<step:012>.npz`` / ``.ckpt``; discovery sorts by the step
# embedded in the name, and pruning keeps the newest ``keep`` steps.

_GEN_RE = re.compile(r"\.g(\d{12})(\.npz|\.ckpt)$")


def checkpoint_stem(path) -> str:
    """Normalize a user-facing checkpoint name to its generation stem:
    strips a trailing ``.npz``/``.ckpt`` and any ``.g<step>`` suffix, so
    every spelling of the same checkpoint family maps to one stem."""
    p = str(path)
    if p.endswith(".npz"):
        p = p[:-4]
    elif p.endswith(".ckpt"):
        p = p[:-5]
    m = re.search(r"\.g\d{12}$", p)
    if m:
        p = p[:m.start()]
    return p


def generation_paths(path) -> list:
    """``(step, path)`` for every COMPLETE retained generation of
    ``path``'s stem, ascending by step. Completeness is what the save
    protocol guarantees survives a crash: a ``.npz`` exists only as an
    atomic rename, a ``.ckpt`` counts only once its ``manifest.json``
    landed — a generation killed between shard write and manifest write
    is invisible here, so discovery falls back to the previous one."""
    stem = checkpoint_stem(path)
    d = os.path.dirname(os.path.abspath(stem)) or "."
    base = os.path.basename(stem)
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in names:
        if not name.startswith(base + ".g"):
            continue
        m = _GEN_RE.search(name)
        if m is None or name[:m.start()] != base:
            continue
        full = os.path.join(d, name)
        if name.endswith(".ckpt"):
            if not (os.path.isdir(full)
                    and os.path.isfile(os.path.join(full,
                                                    "manifest.json"))):
                continue
        elif not os.path.isfile(full):
            continue
        out.append((int(m.group(1)), full))
    out.sort()
    return out


def save_generation(path, grid, step: int, config: HeatConfig,
                    keep: int = 3, layout: str = "auto",
                    compress: bool = False) -> str:
    """Write checkpoint generation ``step`` of ``path``'s stem and prune
    generations beyond the newest ``keep`` steps; returns the path
    written. ``keep=0`` disables pruning (unbounded retention). The
    write itself is the ordinary :func:`save_checkpoint` atomicity;
    pruning runs only AFTER the new generation is complete, so a crash
    anywhere leaves at least the previously retained set intact."""
    if keep < 0:
        raise ValueError(f"keep must be >= 0, got {keep}")
    stem = checkpoint_stem(path)
    written = save_checkpoint(f"{stem}.g{int(step):012d}", grid, step,
                              config, compress=compress, layout=layout)
    if keep:
        _prune_generations(stem, keep)
    return written


def _prune_generations(stem: str, keep: int) -> None:
    """Drop complete generations beyond the newest ``keep`` steps —
    runs only AFTER a new generation is complete, so a crash anywhere
    leaves at least the previously retained set intact."""
    gens = generation_paths(stem)
    keep_steps = set(sorted({s for s, _ in gens})[-keep:])
    for s, p in gens:
        if s in keep_steps:
            continue
        try:
            if os.path.isdir(p):
                import shutil

                shutil.rmtree(p, ignore_errors=True)
            else:
                os.unlink(p)
        except OSError:
            pass


def save_generation_coordinated(path, grid, step: int,
                                config: HeatConfig, coordinator,
                                keep: int = 3, layout: str = "auto",
                                compress: bool = False):
    """Two-phase commit of one checkpoint generation across a
    coordinator's process set; returns ``(path_or_None, skipped)``.

    The distributed extension of the AsyncCheckpointer commit gate
    (SEMANTICS.md "Distributed supervision"): a generation must never
    be discoverable while any host's shard is missing or non-finite.

    Phase 1 — every process verifies its ADDRESSABLE shards finite on
    the host copy it serializes, rename-commits its shard file plus a
    per-process local manifest, then reports ``{finite}`` over the
    coordinator (the jax.distributed KV store — host-side only, so no
    device collective can wedge on a dead peer; a SIGKILLed host
    surfaces as a bounded :class:`~parallel_heat_tpu.parallel.
    coordinator.PeerLostError` instead).

    Phase 2 — only when EVERY process reported finite does process 0
    commit the global generation manifest (the atomic rename
    ``latest_checkpoint`` discovery keys on) and prune old
    generations; a final exchange keeps save a proper barrier (no
    process returns before the manifest is live). Any non-finite
    report skips the generation GLOBALLY — the previous generation
    stays newest on every host — and a crash between a local commit
    and the global one leaves no manifest, so the previous generation
    remains authoritative (chaos-certified).

    Fully-addressable grids (single-process shardings under
    thread-simulated ranks, replicated single-device SPMD runs) take
    the same two phases with rank 0 as the only writer.
    """
    if keep < 0:
        raise ValueError(f"keep must be >= 0, got {keep}")
    stem = checkpoint_stem(path)
    name = f"{stem}.g{int(step):012d}"
    rank, nproc = coordinator.process_index, coordinator.process_count
    if _wants_sharded_layout(grid, layout) \
            and not getattr(grid, "is_fully_addressable", True):
        d = _ckpt_dir_of(name)
        os.makedirs(d, exist_ok=True)
        gen = f"s{int(step):012d}c{nproc:04d}"
        fname, finite = _write_shard_file(d, grid, gen, rank, compress,
                                          verify_finite=True)
        if finite:
            # Local manifest: which shards this process verified and
            # committed, rename-published next to the shard file — the
            # post-mortem record p0's global commit is conditioned on.
            lname = f"local_{gen}_p{rank:05d}.json"
            ltmp = os.path.join(d, f".tmp-{os.getpid()}-{lname}")
            doc = {"generation": gen, "step": int(step),
                   "process_index": rank, "finite": True,
                   "shard_file": fname, "t_wall": time.time()}
            with open(ltmp, "w") as f:
                json.dump(doc, f)
            _fsync_replace(ltmp, os.path.join(d, lname))
        reports = coordinator.exchange(
            "ckpt", {"step": int(step), "finite": bool(finite)})
        ok = all(r.get("finite") for r in reports)
        if ok and rank == 0:
            _commit_manifest_and_prune(
                d, _manifest_doc(grid, gen, step, config, nproc))
            if keep:
                _prune_generations(stem, keep)
        # Commit barrier: nobody returns (or rolls back into
        # discovery) before the manifest rename has landed on p0.
        coordinator.exchange("ckpt", {"committed": ok})
        return (d, False) if ok else (None, True)

    # Fully-addressable: rank 0 is the only writer; every rank still
    # contributes a finite verdict and waits for the commit.
    finite = _host_all_finite(grid)
    reports = coordinator.exchange(
        "ckpt", {"step": int(step), "finite": bool(finite)})
    ok = all(r.get("finite") for r in reports)
    written = None
    if ok and rank == 0:
        written = save_generation(name, grid, step, config, keep=keep,
                                  layout=layout, compress=compress)
    done = coordinator.exchange(
        "ckpt", {"committed": ok,
                 "path": str(written) if written else None})
    if ok:
        written = written or next(
            (v["path"] for v in done if v.get("path")), None)
        return written, False
    return None, True


def latest_checkpoint(path):
    """Discover the newest loadable checkpoint for ``path``: the
    highest-step complete generation of its stem, else the plain
    (generation-less) ``<stem>.npz`` / ``<stem>.ckpt``, else the exact
    path itself, else ``None``. This is what ``--resume auto`` and the
    supervisor's rollback resolve through — after any crash, the answer
    is the newest snapshot whose save protocol COMPLETED."""
    gens = generation_paths(path)
    if gens:
        return gens[-1][1]
    stem = checkpoint_stem(path)
    if os.path.isfile(stem + ".npz"):
        return stem + ".npz"
    d = stem + ".ckpt"
    if os.path.isdir(d) and os.path.isfile(os.path.join(d,
                                                        "manifest.json")):
        return d
    p = str(path)
    if os.path.isfile(p):
        return p
    if os.path.isdir(p) and os.path.isfile(os.path.join(p,
                                                        "manifest.json")):
        return p
    return None


def link_snapshot(src: str, dst: str) -> None:
    """Publish an already-COMMITTED gathered generation file at a
    second path: hardlink when the filesystem allows (O(1), shares
    bytes), else copy + fsync-rename. Either way ``dst`` appears
    complete or not at all — the source is immutable once its own
    rename landed, so a link is exactly as committed as the original.
    This is how the heatd result cache captures donor lineages and
    seeds a new job's stem from one (``service/cache.py``) without a
    second serialization of the grid. No-op when ``dst`` exists: both
    spellings of one committed generation hold identical bytes."""
    if os.path.exists(dst):
        return
    try:
        os.link(src, dst)
        return
    except OSError:
        pass
    import shutil

    tmp = os.path.join(os.path.dirname(dst) or ".",
                       f".tmp-{os.getpid()}-{os.path.basename(dst)}")
    shutil.copyfile(src, tmp)
    _fsync_replace(tmp, dst)


# ---------------------------------------------------------------------------
# Stem interlock (one writer per checkpoint generation family)
# ---------------------------------------------------------------------------
#
# save_generation's pid-unique temps already make concurrent WRITES
# crash-safe, but two supervised runs sharing one stem would still race
# DISCOVERY: each would prune the other's generations and roll back to
# snapshots from a different trajectory. The stem lock makes that an
# actionable startup error instead — one lockfile per stem, held for
# the life of the supervised run, stale locks (dead pid) reclaimed so
# a SIGKILLed run never wedges its own resume.


class StemLockError(RuntimeError):
    """Another live run holds this checkpoint stem. The message names
    the holder (pid, started-at, lockfile path) and the three ways out:
    wait for it, pick a different stem, or remove the lockfile if the
    holder is truly gone (e.g. alive-pid reuse on another container)."""


def _stem_lock_path(stem: str) -> str:
    return checkpoint_stem(stem) + ".lock"


def _stem_lock_mutex(path):
    """flock-held critical section for lock acquisition/reclaim. The
    sidecar mutex file is NEVER unlinked, so there is no TOCTOU on the
    mutex itself, and the kernel drops the flock on process death —
    two racing starters that both judge a lock stale serialize here
    instead of one unlinking the other's freshly-taken lock. Held only
    across the acquire, never for the run. Returns a release callable
    (no-op where flock is unavailable — best effort off-POSIX)."""
    try:
        import fcntl
    except ImportError:  # pragma: no cover — non-POSIX fallback
        return lambda: None
    fd = os.open(path + ".mutex", os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
    except OSError:  # pragma: no cover — e.g. NFS without lockd
        os.close(fd)
        return lambda: None

    def release(_fd=fd):
        try:
            import fcntl as _f

            _f.flock(_fd, _f.LOCK_UN)
        finally:
            os.close(_fd)

    return release


def acquire_stem_lock(stem, heartbeat_glob=None,
                      heartbeat_timeout_s=None):
    """Take the exclusive writer lock on ``stem``'s generation family;
    returns a zero-argument release callable. O_CREAT|O_EXCL makes the
    take atomic; a lockfile whose recorded pid no longer exists is
    stale (the holder was SIGKILLed — exactly the crash the supervisor
    exists to survive) and is reclaimed, with the reclaim serialized
    by an flock sidecar so two racing starters cannot both "reclaim"
    and end up co-holding the stem. Raises :class:`StemLockError`
    when a LIVE process holds it.

    Multi-process SPMD runs are one logical run whose lock is held by
    PROCESS 0 — a dead holder pid alone cannot prove the run over
    (process 0 can crash while ranks >= 1 still stream into the same
    generation family). ``heartbeat_glob`` closes that gap: the lock
    records the pattern of the run's per-rank coordinator heartbeat
    probe files (``<stem>.hb.p*.json`` — the telemetry heartbeat-file
    format ``parallel/coordinator.py`` rewrites), and a reclaimer
    treats the lock as live while ANY matching file is fresher than
    the recorded ``heartbeat_timeout_s``. Surviving ranks stop beating
    within one barrier timeout of losing process 0 (their own
    peer-lost exit), so the lock becomes reclaimable exactly when the
    run is actually gone."""
    path = _stem_lock_path(stem)
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    unlock = _stem_lock_mutex(path)
    try:
        return _acquire_stem_lock_locked(path, heartbeat_glob,
                                         heartbeat_timeout_s)
    finally:
        unlock()


def _fresh_heartbeats(hb_glob: str, timeout_s: float) -> list:
    """Heartbeat probe files under ``hb_glob`` whose mtime is within
    ``timeout_s`` of now — evidence of live peers of a multi-process
    run whose lock-holding process 0 died."""
    import glob as _glob

    fresh = []
    now = time.time()
    for p in _glob.glob(hb_glob):
        try:
            if now - os.path.getmtime(p) < timeout_s:
                fresh.append(p)
        except OSError:
            continue
    return fresh


def _acquire_stem_lock_locked(path, heartbeat_glob=None,
                              heartbeat_timeout_s=None):
    for _ in range(2):  # second pass: retake after reclaiming a stale lock
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            try:
                with open(path) as f:
                    doc = json.load(f)
                holder = int(doc.get("pid", -1))
            except (OSError, ValueError):
                doc = {}
                holder = -1  # torn/foreign lockfile: treat as stale
            alive = False
            if holder > 0:
                try:
                    os.kill(holder, 0)
                    alive = True
                except ProcessLookupError:
                    alive = False
                except OSError:
                    alive = True  # EPERM: exists but not ours
            if not alive and doc.get("hb_glob"):
                # Dead holder pid, but the lock belongs to a
                # multi-process run: ranks >= 1 may still be streaming
                # into this generation family. Any FRESH peer
                # heartbeat probe file keeps the lock live.
                fresh = _fresh_heartbeats(
                    doc["hb_glob"], float(doc.get("hb_timeout_s", 60.0)))
                if fresh:
                    raise StemLockError(
                        f"checkpoint stem {path[:-len('.lock')]!r} is "
                        f"held by a multi-process run whose lock holder "
                        f"(pid {holder}) died but whose peer ranks are "
                        f"still alive (fresh heartbeats: {fresh}) — "
                        f"reclaiming now would race their checkpoint "
                        f"generations. Wait for their peer-lost exit "
                        f"(bounded by the run's barrier timeout), or "
                        f"remove {path!r} if every rank is truly "
                        f"gone.") from None
            if alive:
                # Our own pid counts as live too: two supervised runs
                # in ONE process (threads) sharing a stem are the same
                # discovery race as two processes.
                raise StemLockError(
                    f"checkpoint stem {path[:-len('.lock')]!r} is held "
                    f"by a live supervised run (pid {holder}, started "
                    f"{doc.get('t_wall', '?')}) — two runs sharing a "
                    f"stem would prune and roll back to each other's "
                    f"generations. Wait for it, use a different "
                    f"--checkpoint stem, or remove {path!r} if that "
                    f"run is truly gone.") from None
            # Stale (dead holder / our own pid after exec / torn file):
            # reclaim and retake atomically on the next pass.
            try:
                os.unlink(path)
            except OSError:
                pass
            continue
        try:
            lock_doc = {"pid": os.getpid(), "t_wall": time.time()}
            if heartbeat_glob:
                lock_doc["hb_glob"] = heartbeat_glob
                lock_doc["hb_timeout_s"] = float(
                    heartbeat_timeout_s if heartbeat_timeout_s
                    is not None else 60.0)
            os.write(fd, json.dumps(lock_doc).encode())
        finally:
            os.close(fd)

        def release(_path=path):
            try:
                os.unlink(_path)
            except OSError:
                pass

        return release
    raise StemLockError(  # pragma: no cover — needs a perfectly-timed
        # re-take race; the message still names the remedy
        f"could not acquire checkpoint stem lock {path!r} (another "
        f"writer kept re-taking it); use a different stem")


# ---------------------------------------------------------------------------
# Asynchronous checkpointing (the supervisor's overlap path)
# ---------------------------------------------------------------------------

def _host_all_finite(grid) -> bool:
    """Host-side finite verification of a (possibly sharded) snapshot,
    shard-by-shard — peak host memory is one shard, never the grid.
    This is the async save protocol's commit gate: a generation is only
    published after every gathered value checked finite."""
    shards = getattr(grid, "addressable_shards", None)
    if shards is not None:
        return all(bool(np.isfinite(np.asarray(s.data)).all())
                   for s in shards)
    return bool(np.isfinite(np.asarray(grid)).all())


class AsyncCheckpointer:
    """Background writer of retained checkpoint generations: the save
    cost (device->host gather, serialization, fsync-rename, pruning)
    moves off the run loop's critical path so the device stays busy
    through every snapshot.

    Per :meth:`submit` the protocol is:

    1. **caller thread** — a donation-protected device copy of the grid
       is enqueued (an async device op: ``submit`` returns at dispatch,
       and the caller may immediately advance the stream, whose next
       chunk donates the live buffer);
    2. **worker thread** — waits for the copy, gathers it host-side
       (overlapping the next chunks' compute), verifies every value
       finite, and only then commits the generation through
       :func:`save_generation` (each layout's own crash-atomic rename
       protocol; the retained-generation set — and for the sharded
       layout the manifest — lands strictly after the verify). A
       non-finite snapshot is SKIPPED, leaving the previous generation
       newest: the supervisor's retained-generations-are-good invariant
       holds even when a corruption races an in-flight save.

    Commits happen strictly in submit order (one worker, FIFO queue),
    so generation discovery and pruning see the same monotone history a
    synchronous saver writes — committed bytes are identical to the
    synchronous path's (the copy and the gather are value-preserving).
    ``max_pending`` bounds in-flight snapshots (device memory:
    one extra grid buffer per pending save — a slow disk exerts
    backpressure instead of accumulating copies).

    :meth:`drain` blocks until everything submitted has committed or
    been skipped and re-raises the first worker error — the
    supervisor's rollback/exit barrier: a rollback NEVER loads while a
    save is in flight, so it cannot restore an uncommitted generation.
    ``throttle_s`` delays each commit (chaos/testing only: it widens
    the in-flight window the barrier contract is certified against).
    """

    def __init__(self, keep: int = 3, layout: str = "auto",
                 compress: bool = False, max_pending: int = 2,
                 throttle_s: float = 0.0):
        if keep < 0:
            raise ValueError(f"keep must be >= 0, got {keep}")
        self.keep = keep
        self.layout = layout
        self.compress = compress
        self.throttle_s = float(throttle_s)
        self._q: queue.Queue = queue.Queue(maxsize=max(1, max_pending))
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._records: list = []
        self._closed = False
        self._worker = threading.Thread(target=self._run,
                                        name="async-checkpointer",
                                        daemon=True)
        self._worker.start()

    # -- caller side -----------------------------------------------------

    def submit(self, path, grid, step: int, config: HeatConfig,
               on_done=None, protect: bool = True,
               coordinator=None) -> None:
        """Queue one generation save of ``path``'s stem. ``on_done``
        (optional) is called on the worker thread with the commit
        record ``{step, path, skipped, wall_s, gather_s, error}`` —
        the supervisor's bookkeeping/telemetry hook.

        ``protect=False`` certifies that ``grid``'s buffer will never
        be donated while the save is in flight (e.g. a pipelined
        stream's yielded grids, which are already donation-protected
        copies — SEMANTICS.md "Pipelined stream") and skips the
        device-side snapshot copy; the default copies, which is the
        only safe choice for depth-1 stream yields the next chunk
        donates.

        ``coordinator`` (a distributed
        :class:`~parallel_heat_tpu.parallel.coordinator.Coordinator`)
        routes the commit through
        :func:`save_generation_coordinated`'s two-phase protocol: the
        worker's own finite gate is superseded by the GLOBAL gate (any
        rank's non-finite shard skips the generation everywhere), and
        the KV exchanges run on this worker thread — host-side only,
        so an in-flight save can never wedge a device collective, and
        a dead peer surfaces at the next drain barrier as a bounded
        error instead of a hang."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_pending()
        if protect:
            import jax.numpy as jnp

            # The one step that MUST happen before the caller's next
            # dispatch: a device-side copy, enqueued in dispatch order,
            # so the snapshot survives the live buffer's donation.
            # Async — the copy itself overlaps whatever is already
            # queued.
            grid = jnp.copy(grid)
        self._q.put({"path": path, "snap": grid, "step": int(step),
                     "config": config, "on_done": on_done,
                     "coordinator": coordinator})

    def drain(self) -> float:
        """Block until every submitted save committed (or was skipped);
        returns the seconds waited and re-raises the first worker
        error. The rollback/exit barrier."""
        t0 = time.perf_counter()
        self._q.join()
        self._raise_pending()
        return time.perf_counter() - t0

    def close(self, drain: bool = True) -> None:
        """Stop the worker; with ``drain`` (default) pending saves
        commit first. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if drain:
            try:
                self._q.join()
            except Exception:  # pragma: no cover — defensive
                pass
        self._q.put(None)
        self._worker.join(timeout=60.0)

    @property
    def records(self) -> list:
        """Commit records so far (testing/tooling; worker-ordered)."""
        with self._lock:
            return list(self._records)

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    # -- worker side -----------------------------------------------------

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            rec = {"step": item["step"], "path": None, "skipped": False,
                   "error": None, "wall_s": 0.0, "gather_s": 0.0}
            try:
                if self.throttle_s > 0:
                    time.sleep(self.throttle_s)
                t0 = time.perf_counter()
                snap = item["snap"]
                coordinator = item.get("coordinator")
                if coordinator is not None:
                    # Distributed two-phase commit: the global gate
                    # (every rank's shard finite) supersedes this
                    # worker's local one, and the KV exchanges run
                    # HERE — host-side only, so an in-flight save can
                    # never wedge a device collective.
                    tg0 = time.perf_counter()
                    path, skipped = save_generation_coordinated(
                        item["path"], snap, item["step"],
                        item["config"], coordinator, keep=self.keep,
                        layout=self.layout, compress=self.compress)
                    rec["gather_s"] = time.perf_counter() - tg0
                    rec["path"] = path
                    rec["skipped"] = skipped
                else:
                    # One gather, not two: when the save will take the
                    # GATHERED layout anyway (the writer's own
                    # predicate — shared, so the two can never
                    # diverge), pull the snapshot to host once, verify
                    # that copy, and serialize FROM it — otherwise the
                    # verify pass and the writer would each pay a full
                    # device->host transfer. The sharded layout keeps
                    # the shard-by-shard verify (its writer also
                    # streams shard-by-shard; peak host memory stays
                    # one shard).
                    sharded = _wants_sharded_layout(snap, self.layout)
                    tg0 = time.perf_counter()
                    if sharded:
                        finite = _host_all_finite(snap)
                        payload = snap
                    else:
                        payload = np.asarray(snap)
                        finite = bool(np.isfinite(payload).all())
                    rec["gather_s"] = time.perf_counter() - tg0
                    if finite:
                        rec["path"] = save_generation(
                            item["path"], payload, item["step"],
                            item["config"], keep=self.keep,
                            layout=self.layout, compress=self.compress)
                    else:
                        # Commit gate: never publish a bad generation;
                        # the previous one stays newest and the
                        # supervisor's guard/rollback machinery
                        # handles the corruption.
                        rec["skipped"] = True
                rec["wall_s"] = time.perf_counter() - t0
            except BaseException as e:  # noqa: BLE001 — surfaced at
                # the next submit/drain barrier, exactly where a
                # synchronous save would have raised
                rec["error"] = e
                with self._lock:
                    if self._error is None:
                        self._error = e
            try:
                if item["on_done"] is not None:
                    item["on_done"](rec)
            except Exception as e:  # noqa: BLE001 — a bookkeeping
                # callback bug must not wedge the writer
                import warnings

                warnings.warn(f"async checkpoint on_done callback "
                              f"failed: {e}", RuntimeWarning)
            with self._lock:
                self._records.append(rec)
            self._q.task_done()


def load_checkpoint(path, expect_config: HeatConfig | None = None
                    ) -> Tuple[np.ndarray, int, HeatConfig]:
    """Returns ``(grid, step, saved_config)``.

    Accepts either layout: a gathered ``.npz`` file or a sharded
    ``.ckpt`` directory (also resolved from the stem the gathered
    name would use, so ``--resume ck.npz`` finds ``ck.ckpt/``). When
    ``expect_config`` is given, grid geometry must match (other fields
    — steps, eps, mesh — may legitimately differ on resume). Sharded
    checkpoints loaded on a matching topology come back as a
    device-resident sharded ``jax.Array`` (no gather); see
    :func:`_load_sharded`.
    """
    path = str(path)
    if os.path.isdir(path):
        return _load_sharded(path, expect_config)
    if not os.path.exists(path) and os.path.isdir(_ckpt_dir_of(path)):
        return _load_sharded(_ckpt_dir_of(path), expect_config)
    with np.load(path) as z:
        version = int(z["version"])
        if version != _FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version {version}")
        grid = z["grid"]
        step = int(z["step"])
        saved = HeatConfig.from_json(bytes(z["config"]).decode())
    if expect_config is not None and saved.shape != expect_config.shape:
        raise ValueError(
            f"checkpoint grid {saved.shape} != configured {expect_config.shape}"
        )
    return grid, step, saved
