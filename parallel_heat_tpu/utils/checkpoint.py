"""Checkpoint / resume — a capability gap in the reference (SURVEY.md §5:
state lives only in the two buffers; output only at the end). Snapshots
are plain ``.npz`` (grid + step counter + config fingerprint), cheap and
dependency-free; the grid is gathered to host, so this targets
operational resume, not in-flight failover.
"""

from __future__ import annotations

import json
from typing import Tuple

import numpy as np

from parallel_heat_tpu.config import HeatConfig

_FORMAT_VERSION = 1


def save_checkpoint(path, grid, step: int, config: HeatConfig) -> str:
    """Write a snapshot; returns the actual path written (always .npz —
    normalized here rather than letting np.savez append it silently)."""
    path = str(path)
    if not path.endswith(".npz"):
        path += ".npz"
    np.savez_compressed(
        path,
        grid=np.asarray(grid),
        step=np.int64(step),
        config=np.frombuffer(config.to_json().encode(), dtype=np.uint8),
        version=np.int64(_FORMAT_VERSION),
    )
    return path


def load_checkpoint(path, expect_config: HeatConfig | None = None
                    ) -> Tuple[np.ndarray, int, HeatConfig]:
    """Returns ``(grid, step, saved_config)``.

    When ``expect_config`` is given, grid geometry must match (other
    fields — steps, eps, mesh — may legitimately differ on resume).
    """
    with np.load(path) as z:
        version = int(z["version"])
        if version != _FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version {version}")
        grid = z["grid"]
        step = int(z["step"])
        saved = HeatConfig.from_json(bytes(z["config"]).decode())
    if expect_config is not None and saved.shape != expect_config.shape:
        raise ValueError(
            f"checkpoint grid {saved.shape} != configured {expect_config.shape}"
        )
    return grid, step, saved
