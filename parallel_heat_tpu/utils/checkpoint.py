"""Checkpoint / resume — a capability gap in the reference (SURVEY.md §5:
state lives only in the two buffers; output only at the end).

Two layouts, selected automatically (``layout="auto"``):

- **gathered** (small grids): one ``.npz`` (grid + step counter +
  config fingerprint) with the grid gathered to host — cheap,
  dependency-free, human-greppable.
- **sharded** (large sharded grids): a ``<name>.ckpt/`` directory with
  a JSON manifest plus one ``.npz`` per process holding only that
  process's addressable shards, written shard-by-shard — the full grid
  is never materialized on any host (a 32768^2 f32 grid would cost a
  4 GiB host spike per snapshot through the gathered path), and resume
  rebuilds the global array via
  ``jax.make_array_from_single_device_arrays`` with no gather either.
  Multi-process runs write concurrently (each process owns its file);
  process 0 writes the manifest last, so a torn save leaves the
  previous generation's manifest — and therefore the previous
  snapshot — intact.
"""

from __future__ import annotations

import json
import os
import re
from typing import Tuple

import numpy as np

from parallel_heat_tpu.config import HeatConfig

_FORMAT_VERSION = 1
_MANIFEST_VERSION = 2
# Shard files are generation-named; loaders and the pruner match this
# EXACT pattern so orphaned temp files can never be mistaken for data.
_SHARD_RE_TMPL = r"shards_{gen}_p\d{{5}}\.npz"
# Auto layout: shard when the grid is device-sharded and big enough
# that a host gather hurts; below this, one gathered file is simpler.
_SHARD_THRESHOLD_BYTES = 64 * 1024 * 1024


def _num_devices_of(grid) -> int:
    sharding = getattr(grid, "sharding", None)
    if sharding is None:
        return 1
    try:
        return len(sharding.device_set)
    except AttributeError:  # pragma: no cover - older jax
        return 1


def save_checkpoint(path, grid, step: int, config: HeatConfig,
                    compress: bool = False, layout: str = "auto") -> str:
    """Write a snapshot; returns the actual path written.

    ``layout``: ``"gathered"`` (one .npz, grid gathered to host),
    ``"sharded"`` (per-process shard directory, no host gather), or
    ``"auto"`` — sharded when the grid spans non-addressable devices
    (a multi-process run, where gathering is impossible, not merely
    slow) or is sharded over more than one device and large enough
    that gathering hurts (>= 64 MiB). See the module docstring for the
    formats.
    """
    if layout not in ("auto", "gathered", "sharded"):
        raise ValueError(f"unknown checkpoint layout {layout!r}")
    fully_addressable = getattr(grid, "is_fully_addressable", True)
    if layout == "gathered" and not fully_addressable:
        raise ValueError(
            "layout='gathered' cannot snapshot a grid that spans "
            "non-addressable devices (multi-process run); use "
            "'sharded' or 'auto'")
    if layout == "sharded" or (layout == "auto" and (
            not fully_addressable
            or (_num_devices_of(grid) > 1
                and grid.size * grid.dtype.itemsize
                >= _SHARD_THRESHOLD_BYTES))):
        return _save_sharded(path, grid, step, config, compress)
    return _save_gathered(path, grid, step, config, compress)


def _fsync_replace(tmp: str, dst: str) -> None:
    """Durable atomic publish: fsync the temp file, rename it over the
    destination, fsync the directory entry — a power loss at any point
    leaves either the old or the new file complete, never a torn one.
    """
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, dst)
    dirfd = os.open(os.path.dirname(os.path.abspath(dst)) or ".",
                    os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


def _save_gathered(path, grid, step: int, config: HeatConfig,
                   compress: bool = False) -> str:
    """One .npz with the grid gathered to host; returns the path
    written (always .npz — normalized here rather than letting
    np.savez append it silently).

    The write is atomic (temp file + ``os.replace``): the periodic
    checkpointing driver (``solve_stream`` / ``--checkpoint-every``)
    overwrites one rolling file, and a crash mid-write must leave the
    previous snapshot intact — a torn file would defeat the feature's
    whole purpose.

    ``compress`` defaults to off: deflate on f32 field data measured
    8x slower for ~10% size (256 MB grid: 1.5 s vs 12 s) — at this
    framework's benchmark sizes a compressed periodic checkpoint would
    stall the run for minutes per snapshot. ``load_checkpoint`` reads
    either format.
    """
    path = str(path)
    if not path.endswith(".npz"):
        path += ".npz"
    tmp = path + ".tmp.npz"  # must end .npz or np.savez appends it
    saver = np.savez_compressed if compress else np.savez
    try:
        saver(
            tmp,
            grid=np.asarray(grid),
            step=np.int64(step),
            config=np.frombuffer(config.to_json().encode(), dtype=np.uint8),
            version=np.int64(_FORMAT_VERSION),
        )
        _fsync_replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def _ckpt_dir_of(path: str) -> str:
    """Directory path for the sharded layout of a checkpoint name."""
    path = str(path)
    if path.endswith(".ckpt"):
        return path
    if path.endswith(".npz"):
        path = path[:-4]
    return path + ".ckpt"


def _save_sharded(path, grid, step: int, config: HeatConfig,
                  compress: bool = False) -> str:
    """Per-process shard directory; returns the ``.ckpt`` dir written.

    Each process writes ONE ``.npz`` holding its addressable shards
    (keyed ``d<device_id>``), copied device->host one shard at a time —
    peak host memory is a single shard, never the grid. Process 0
    writes ``manifest.json`` LAST (atomic temp+replace), stamping a
    fresh generation id: shard files are generation-named, so readers
    always see a consistent (old or new) set and a crash between the
    shard writes and the manifest write leaves the previous snapshot
    live. Stale generations are pruned after the manifest lands.
    """
    import jax

    d = _ckpt_dir_of(path)
    os.makedirs(d, exist_ok=True)
    proc = jax.process_index()
    shards = sorted(grid.addressable_shards, key=lambda s: s.device.id)
    # The generation id must agree across processes without
    # communication; the step count (monotone within a run) is exactly
    # that, with the process count folded in so a re-save of the same
    # step from a different topology cannot leave stale shard files
    # (e.g. higher p-indices from a larger earlier run) matching the
    # live generation's pattern — they get pruned as a foreign
    # generation instead. A same-step same-topology re-save still
    # overwrites file-atomically.
    gen = f"s{int(step):012d}c{jax.process_count():04d}"
    fname = f"shards_{gen}_p{proc:05d}.npz"
    # Leading dot: temp names must never match the shard-file pattern a
    # loader or pruner scans for (a crash can orphan them).
    tmp = os.path.join(d, f".tmp-{os.getpid()}-{fname}")
    import zipfile

    try:
        # Stream one zip member per shard (an .npz IS a zip of .npy
        # members): each device->host copy is released before the next
        # is made, so peak host memory is one shard, never the grid.
        mode = zipfile.ZIP_DEFLATED if compress else zipfile.ZIP_STORED
        with zipfile.ZipFile(tmp, "w", mode) as zf:
            for sh in shards:
                with zf.open(f"d{sh.device.id}.npy", "w",
                             force_zip64=True) as fh:
                    np.lib.format.write_array(fh, np.asarray(sh.data),
                                              allow_pickle=False)
        _fsync_replace(tmp, os.path.join(d, fname))
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)

    if jax.process_count() > 1:  # pragma: no cover (multi-host barrier)
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("heat_ckpt_shards_written")

    if proc == 0:
        # Global shard map: device id -> index, computable on p0 for
        # every process without communication.
        index_map = grid.sharding.devices_indices_map(grid.shape)
        devices = {}
        for dev, idx in index_map.items():
            devices[str(dev.id)] = {
                "process": dev.process_index,
                "index": [[sl.start or 0,
                           sl.stop if sl.stop is not None else n]
                          for sl, n in zip(idx, grid.shape)],
            }
        manifest = {
            "version": _MANIFEST_VERSION,
            "generation": gen,
            "step": int(step),
            "config": config.to_json(),
            "shape": list(grid.shape),
            "dtype": str(grid.dtype),
            "mesh_shape": list(config.mesh_or_unit()),
            "process_count": jax.process_count(),
            "devices": devices,
        }
        mtmp = os.path.join(d, f".tmp-{os.getpid()}-manifest")
        with open(mtmp, "w") as f:
            json.dump(manifest, f)
        _fsync_replace(mtmp, os.path.join(d, "manifest.json"))
        # Prune stale generations AND orphaned temps (every live
        # process has published its shard file before the barrier
        # above, so any .tmp-* here is from a crashed earlier run).
        live = _SHARD_RE_TMPL.format(gen=gen)
        for old in os.listdir(d):
            if old == "manifest.json":
                continue
            if re.fullmatch(live, old):
                continue
            if old.startswith((".tmp-", "shards_")):
                try:
                    os.unlink(os.path.join(d, old))
                except OSError:
                    pass
        # A stale gathered .npz from an earlier, smaller run of the
        # same name must not shadow this directory at load time
        # (load_checkpoint prefers an existing file).
        stem_npz = d[:-5] + ".npz"
        if os.path.exists(stem_npz):
            try:
                os.unlink(stem_npz)
            except OSError:
                pass
    if jax.process_count() > 1:  # pragma: no cover (multi-host barrier)
        # Make save a proper collective: no process returns (and e.g.
        # immediately resumes) before the manifest is live.
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("heat_ckpt_manifest_written")
    return d


def _load_sharded(d: str, expect_config: HeatConfig | None):
    """Load a ``.ckpt`` directory; returns ``(grid, step, config)``.

    Fast path (no gather): when the current topology matches the saved
    one (same process count; the saved mesh buildable on the current
    devices), every process loads only its own shard file and the
    global array is assembled with
    ``jax.make_array_from_single_device_arrays`` — device-resident,
    correctly sharded for the resuming solve. Single-process fallback
    for a topology mismatch: assemble the full grid on host from all
    shard files (the operational-resume path; still no *device* gather).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from parallel_heat_tpu.parallel.mesh import make_heat_mesh

    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    if man["version"] != _MANIFEST_VERSION:
        raise ValueError(f"unsupported checkpoint version {man['version']}")
    saved = HeatConfig.from_json(man["config"])
    step = int(man["step"])
    shape = tuple(man["shape"])
    if expect_config is not None and saved.shape != expect_config.shape:
        raise ValueError(
            f"checkpoint grid {saved.shape} != configured "
            f"{expect_config.shape}")
    gen = man["generation"]
    mesh_shape = tuple(man["mesh_shape"])
    n_needed = 1
    for m in mesh_shape:
        n_needed *= m

    same_topology = (jax.process_count() == man["process_count"]
                     and len(jax.devices()) >= n_needed)
    if same_topology:
        mesh = make_heat_mesh(mesh_shape)
        sharding = NamedSharding(mesh, P(*mesh.axis_names))
        index_map = sharding.devices_indices_map(shape)
        proc = jax.process_index()
        fname = os.path.join(d, f"shards_{gen}_p{proc:05d}.npz")
        arrays = []
        try:
            with np.load(fname) as z:
                for dev, idx in index_map.items():
                    if dev.process_index != proc:
                        continue
                    key = f"d{dev.id}"
                    info = man["devices"].get(str(dev.id))
                    want = [[sl.start or 0,
                             sl.stop if sl.stop is not None else n]
                            for sl, n in zip(idx, shape)]
                    if (key not in z or info is None
                            or info["index"] != want):
                        # Device numbering or the device->block
                        # assignment moved between runs (topology-aware
                        # mesh reorder, a different host layout, an
                        # explicit devices= mesh at save time):
                        # reassembling by id would place blocks at the
                        # wrong coordinates — fall back to host
                        # assembly, which trusts only the manifest's
                        # indices.
                        arrays = None
                        break
                    arrays.append(jax.device_put(z[key], dev))
        except OSError:
            # A missing/unreadable per-process shard file is a
            # topology mismatch in disguise (e.g. this process index
            # had no shard in the saved run), not a crash.
            arrays = None
        ok = arrays is not None
        if jax.process_count() > 1:  # pragma: no cover (multi-host)
            # The fast-path-vs-fall-back decision must be COLLECTIVE:
            # if some processes assembled their shards while others
            # hit an index mismatch, the mixed control flow would hang
            # at the next sync instead of failing cleanly.
            from jax.experimental import multihost_utils

            ok = bool(multihost_utils.process_allgather(
                np.array([ok])).all())
        if ok:
            grid = jax.make_array_from_single_device_arrays(
                shape, sharding, arrays)
            return grid, step, saved

    if jax.process_count() > 1:  # pragma: no cover
        raise ValueError(
            f"cannot resume sharded checkpoint {d}: saved topology "
            f"(mesh {mesh_shape}, {man['process_count']} processes, "
            f"generation {gen}) does not match the current one, or a "
            f"per-process shard file is missing/mismatched")
    # Single-process host assembly (topology changed): read every shard
    # file and place each block into a full host grid.
    full = np.empty(shape, dtype=np.dtype(man["dtype"]))
    placed = 0
    pat = _SHARD_RE_TMPL.format(gen=re.escape(gen))
    for fname in sorted(os.listdir(d)):
        if not re.fullmatch(pat, fname):
            continue
        with np.load(os.path.join(d, fname)) as z:
            for key in z.files:
                info = man["devices"].get(key[1:])
                if info is None:
                    raise ValueError(
                        f"shard {key} in {fname} missing from manifest")
                sl = tuple(slice(a, b) for a, b in info["index"])
                full[sl] = z[key]
                placed += 1
    if placed != len(man["devices"]):
        raise ValueError(
            f"sharded checkpoint {d} incomplete: {placed} shards found, "
            f"{len(man['devices'])} expected")
    return full, step, saved


def load_checkpoint(path, expect_config: HeatConfig | None = None
                    ) -> Tuple[np.ndarray, int, HeatConfig]:
    """Returns ``(grid, step, saved_config)``.

    Accepts either layout: a gathered ``.npz`` file or a sharded
    ``.ckpt`` directory (also resolved from the stem the gathered
    name would use, so ``--resume ck.npz`` finds ``ck.ckpt/``). When
    ``expect_config`` is given, grid geometry must match (other fields
    — steps, eps, mesh — may legitimately differ on resume). Sharded
    checkpoints loaded on a matching topology come back as a
    device-resident sharded ``jax.Array`` (no gather); see
    :func:`_load_sharded`.
    """
    path = str(path)
    if os.path.isdir(path):
        return _load_sharded(path, expect_config)
    if not os.path.exists(path) and os.path.isdir(_ckpt_dir_of(path)):
        return _load_sharded(_ckpt_dir_of(path), expect_config)
    with np.load(path) as z:
        version = int(z["version"])
        if version != _FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version {version}")
        grid = z["grid"]
        step = int(z["step"])
        saved = HeatConfig.from_json(bytes(z["config"]).decode())
    if expect_config is not None and saved.shape != expect_config.shape:
        raise ValueError(
            f"checkpoint grid {saved.shape} != configured {expect_config.shape}"
        )
    return grid, step, saved
