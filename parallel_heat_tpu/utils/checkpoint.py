"""Checkpoint / resume — a capability gap in the reference (SURVEY.md §5:
state lives only in the two buffers; output only at the end). Snapshots
are plain ``.npz`` (grid + step counter + config fingerprint), cheap and
dependency-free; the grid is gathered to host, so this targets
operational resume, not in-flight failover.
"""

from __future__ import annotations

import json
from typing import Tuple

import numpy as np

from parallel_heat_tpu.config import HeatConfig

_FORMAT_VERSION = 1


def save_checkpoint(path, grid, step: int, config: HeatConfig,
                    compress: bool = False) -> str:
    """Write a snapshot; returns the actual path written (always .npz —
    normalized here rather than letting np.savez append it silently).

    The write is atomic (temp file + ``os.replace``): the periodic
    checkpointing driver (``solve_stream`` / ``--checkpoint-every``)
    overwrites one rolling file, and a crash mid-write must leave the
    previous snapshot intact — a torn file would defeat the feature's
    whole purpose.

    ``compress`` defaults to off: deflate on f32 field data measured
    8x slower for ~10% size (256 MB grid: 1.5 s vs 12 s) — at this
    framework's benchmark sizes a compressed periodic checkpoint would
    stall the run for minutes per snapshot. ``load_checkpoint`` reads
    either format.
    """
    import os

    path = str(path)
    if not path.endswith(".npz"):
        path += ".npz"
    tmp = path + ".tmp.npz"  # must end .npz or np.savez appends it
    saver = np.savez_compressed if compress else np.savez
    try:
        saver(
            tmp,
            grid=np.asarray(grid),
            step=np.int64(step),
            config=np.frombuffer(config.to_json().encode(), dtype=np.uint8),
            version=np.int64(_FORMAT_VERSION),
        )
        # Durability, not just atomicity: flush the tmp file's data (and
        # the directory entry) to stable storage before the rename makes
        # it the live snapshot — otherwise a power loss right after
        # os.replace can leave a torn file with the old snapshot gone.
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, path)
        dirfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                        os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return path


def load_checkpoint(path, expect_config: HeatConfig | None = None
                    ) -> Tuple[np.ndarray, int, HeatConfig]:
    """Returns ``(grid, step, saved_config)``.

    When ``expect_config`` is given, grid geometry must match (other
    fields — steps, eps, mesh — may legitimately differ on resume).
    """
    with np.load(path) as z:
        version = int(z["version"])
        if version != _FORMAT_VERSION:
            raise ValueError(f"unsupported checkpoint version {version}")
        grid = z["grid"]
        step = int(z["step"])
        saved = HeatConfig.from_json(bytes(z["config"]).decode())
    if expect_config is not None and saved.shape != expect_config.shape:
        raise ValueError(
            f"checkpoint grid {saved.shape} != configured {expect_config.shape}"
        )
    return grid, step, saved
