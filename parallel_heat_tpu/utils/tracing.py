"""heattrace: causal trace contexts + the span model over telemetry.

The reference project's whole performance story was told through
Paraver timelines (Heat.pdf §trace analysis: compute/comm overlap,
imbalance read off a trace viewer). Our stack already emits the raw
material — the journal (``service/store.py``) records every queue
transition, the telemetry JSONL (``utils/telemetry.py``) records every
chunk, checkpoint, consensus barrier and lifecycle event — but nothing
connects a client submit to the chunk that ran three processes and two
rollbacks later. This module is that thread:

- :class:`TraceContext` (``trace_id`` / ``span_id`` /
  ``parent_span_id``) is born at ``service/client.py`` submit,
  rename-committed into the job record, carried on every journal line
  (``trace_id``), inherited by the spawned worker via environment
  variables (``service/daemon.py`` → ``service/worker.py``) and
  stamped on every telemetry envelope. Span ids are DETERMINISTIC
  (``submit_span_id`` / ``dispatch_span_id`` / ``worker_span_id``):
  any consumer can reconstruct the parentage chain from the ids alone,
  a daemon restart re-derives identical ids, and no RNG is involved;

- the span model (:func:`spans_from_stream` /
  :func:`spans_from_journal`) derives causal spans from the event
  streams we ALREADY emit — queue wait (accepted→dispatched), worker
  attempts, per-rank run segments, chunks, checkpoint saves, the
  two-phase commit gate (``checkpoint_barrier``), per-rank consensus
  ``barrier_wait``, rollback loads + replay segments, ensemble member
  lanes — nothing new is measured, the run pays zero extra cost;

- :func:`chrome_trace` renders the merged spans as Chrome
  trace-event JSON (the ``traceEvents`` array format) that opens
  directly in Perfetto / ``chrome://tracing`` — the modern analogue of
  the report's Paraver analysis. ``tools/heattrace.py`` is the CLI.

Timeline alignment: within one shard, span times are ``t_mono``
anchored at the nearest preceding ``run_header`` (offset =
``header.t_wall - header.t_mono`` — monotonic robustness inside a
segment, wall alignment across segments and processes). Cross-host
offsets therefore reduce to wall-clock agreement at the run headers,
which the coordinator KV handshake brackets to well under a chunk
width; ``barrier_wait`` spans make any residual skew visible rather
than hiding it.

Everything here is observation-only (SEMANTICS.md "Runtime guard and
supervisor", extended to tracing): no config field, no compiled
program, no grid byte changes when a trace context is attached —
pinned by the extended
``test_telemetry_does_not_change_compiled_programs``.
"""

from __future__ import annotations

import itertools
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

# Environment inheritance (daemon -> worker subprocess). The variables
# carry the PARENT context: the spawned process derives its own child
# span under it (TraceContext.from_env(...).child(...)).
ENV_TRACE_ID = "HEATTRACE_TRACE_ID"
ENV_SPAN_ID = "HEATTRACE_SPAN_ID"
ENV_PARENT_SPAN_ID = "HEATTRACE_PARENT_SPAN_ID"

_trace_seq = itertools.count()


def new_trace_id(clock=time.time) -> str:
    """Collision-free without randomness, like ``client.make_job_id``:
    wall-millis + pid + an in-process counter. Deterministic-entropy
    ids keep the plumbing replayable and test-friendly (and keep RNG
    out of anything a traced region could ever inhale)."""
    return (f"t{int(clock() * 1000):013d}-{os.getpid()}"
            f"-{next(_trace_seq)}")


# -- deterministic span ids --------------------------------------------------
# One naming rule shared by the writers (client/daemon/worker) and the
# reader (the span model): ids derive from stable coordinates, so the
# parentage chain reconstructs from artifacts alone — a journal line
# needs only the trace_id, never a span table.

def submit_span_id(job_id: str) -> str:
    return f"s-submit-{job_id}"


def dispatch_span_id(job_id: str, attempt: int) -> str:
    return f"s-dispatch-{job_id}-a{int(attempt):03d}"


def worker_span_id(job_id: str, attempt: int) -> str:
    return f"s-worker-{job_id}-a{int(attempt):03d}"


@dataclass(frozen=True)
class TraceContext:
    """One node of the causal chain: ``span_id`` is THIS span,
    ``parent_span_id`` links upward, ``trace_id`` names the whole
    tree. Immutable; :meth:`child` derives the next hop."""

    trace_id: str
    span_id: str
    parent_span_id: Optional[str] = None

    def child(self, span_id: str) -> "TraceContext":
        return TraceContext(self.trace_id, span_id, self.span_id)

    # -- dict round trip (JobSpec.trace, telemetry envelope) -------------

    def to_dict(self) -> dict:
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_span_id is not None:
            d["parent_span_id"] = self.parent_span_id
        return d

    @classmethod
    def from_dict(cls, d) -> Optional["TraceContext"]:
        """None on anything that is not a well-formed context — specs
        and envelopes from older writers simply have no trace."""
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not (isinstance(tid, str) and isinstance(sid, str)):
            return None
        par = d.get("parent_span_id")
        return cls(tid, sid, par if isinstance(par, str) else None)

    # -- env round trip (daemon -> worker subprocess) --------------------

    def to_env(self) -> Dict[str, str]:
        env = {ENV_TRACE_ID: self.trace_id, ENV_SPAN_ID: self.span_id}
        if self.parent_span_id is not None:
            env[ENV_PARENT_SPAN_ID] = self.parent_span_id
        return env

    @classmethod
    def from_env(cls, environ=None) -> Optional["TraceContext"]:
        environ = os.environ if environ is None else environ
        tid = environ.get(ENV_TRACE_ID)
        sid = environ.get(ENV_SPAN_ID)
        if not tid or not sid:
            return None
        return cls(tid, sid, environ.get(ENV_PARENT_SPAN_ID) or None)


# ---------------------------------------------------------------------------
# Span model: derive causal spans from the streams we already emit
# ---------------------------------------------------------------------------
#
# A span is a plain dict (JSON-ready):
#   {"name", "cat", "t0", "t1",            # wall-aligned seconds
#    "trace_id", "span_id", "parent_span_id",
#    "pid", "tid",                          # display lanes (strings)
#    "args": {...}}
# An instant drops "t1". `chrome_trace` maps lanes onto numeric
# pids/tids with metadata naming events.

_UNTRACED = "untraced"

# Lifecycle events rendered as instants (zero-duration markers).
_INSTANT_EVENTS = ("guard_trip", "progress_trip", "retry", "signal",
                   "peer_lost", "consensus_verdict", "checkpoint_skipped",
                   "member_converged", "ensemble_compaction")


def merge_spans(spans: Sequence[dict]) -> List[dict]:
    """Coalesce spans sharing one ``span_id`` — the same LOGICAL span
    observed from several artifacts (the envelope's worker span
    appears in every rank's shard; a queue root's journal and streams
    are parsed independently). Interval = union, parent/args = first
    non-null; order-preserving, first occurrence wins the lane."""
    out: List[dict] = []
    by_id: Dict[str, dict] = {}
    for s in spans:
        prev = by_id.get(s["span_id"])
        if prev is None:
            by_id[s["span_id"]] = s
            out.append(s)
            continue
        prev["t0"] = min(prev["t0"], s["t0"])
        prev["t1"] = max(prev["t1"], s["t1"])
        if prev.get("parent_span_id") is None:
            prev["parent_span_id"] = s.get("parent_span_id")
        for k, v in (s.get("args") or {}).items():
            prev["args"].setdefault(k, v)
    return out


def spans_from_stream(events: Sequence[dict],
                      pid_label: Optional[str] = None,
                      stream_key: Optional[str] = None
                      ) -> Tuple[List[dict], List[dict]]:
    """Derive ``(spans, instants)`` from one telemetry stream (one
    shard or several pre-merged ones — rank lanes come from each
    event's ``process_index``).

    Lanes key on EACH event's own envelope context, not a per-stream
    one: heatd appends every attempt of a job to the same per-job
    sink, and attempt 2's envelopes (``s-worker-<job>-a002``) must
    hang off attempt 2's dispatch span, never attempt 1's. Per lane: a
    synthetic ``worker`` span covering the lane (the envelope's own
    span when traced — the chain's hop below the journal's dispatch
    span), run segments under it (one per ``run_header``,
    t_mono-anchored there), chunks / checkpoint saves / commit gates /
    barrier waits / rollback loads + replay segments under the run
    segment, and ensemble members as per-member lanes.

    ``stream_key`` disambiguates UNTRACED streams (no envelope
    context): it seeds their synthetic span ids, so two untraced runs
    fed to one export cannot collide and merge (callers pass the file
    path; None keeps the legacy single-stream ids). Foreign or torn-in
    lines are skipped — a trace must degrade, never crash (the
    metrics_report discipline).
    """
    untraced_base = (f"stream-{stream_key}" if stream_key is not None
                     else "run")
    spans: List[dict] = []
    instants: List[dict] = []
    # Lane state per (envelope span, rank): wall offset, open run
    # segment, counters.
    ranks: Dict[Tuple[str, int], dict] = {}

    def lane(e, rank):
        ctx = TraceContext.from_dict(e)
        base = ctx.span_id if ctx else untraced_base
        st = ranks.get((base, rank))
        if st is None:
            job_id = e.get("job_id")
            job_id = job_id if isinstance(job_id, str) else None
            st = ranks[(base, rank)] = {
                "offset": None, "seg": 0, "seq": 0,
                "run_span": None, "open_segment": None,
                "pack_t0": None,
                "trace_id": ctx.trace_id if ctx else _UNTRACED,
                "pid": pid_label or (f"job {job_id}" if job_id
                                     else "run"),
                "worker": {
                    "name": "worker", "cat": "worker",
                    "t0": None, "t1": None,
                    "trace_id": ctx.trace_id if ctx else _UNTRACED,
                    "span_id": (ctx.span_id if ctx
                                else f"{base}#w{rank}"),
                    "parent_span_id": (ctx.parent_span_id if ctx
                                       else None),
                    "pid": pid_label or (f"job {job_id}" if job_id
                                         else "run"),
                    "tid": f"rank {rank}",
                    "args": ({"job_id": job_id} if job_id else {})},
            }
            spans.append(st["worker"])
        return st

    def close_segment(st, t):
        seg = st.pop("open_segment", None)
        if seg is not None:
            seg["t1"] = t
        st["open_segment"] = None

    def t_of(st, e):
        """Wall-aligned time: t_mono + the segment's run_header offset
        (monotonic inside a segment, wall-aligned across segments and
        hosts); plain t_wall before any header."""
        tm, tw = e.get("t_mono"), e.get("t_wall")
        if st["offset"] is not None and isinstance(tm, (int, float)):
            return tm + st["offset"]
        return tw if isinstance(tw, (int, float)) else 0.0

    for e in events:
        if not isinstance(e, dict) or "event" not in e:
            continue
        ev = e["event"]
        rank = e.get("process_index")
        rank = rank if isinstance(rank, int) else 0
        st = lane(e, rank)
        if ev == "run_header":
            tm, tw = e.get("t_mono"), e.get("t_wall")
            if isinstance(tm, (int, float)) and isinstance(tw,
                                                           (int, float)):
                st["offset"] = tw - tm
        t = t_of(st, e)
        if st["worker"]["t0"] is None:
            st["worker"]["t0"] = t
        st["worker"]["t1"] = t
        run = st["run_span"]

        def child(name, cat, t0, t1, args=None, tid=None,
                  parent=None):
            st["seq"] += 1
            s = {"name": name, "cat": cat, "t0": t0, "t1": t1,
                 "trace_id": st["trace_id"],
                 "span_id": f"{st['worker']['span_id']}"
                            f"/p{rank}.{st['seq']}",
                 "parent_span_id": (parent or
                                    (run["span_id"] if run
                                     else st["worker"]["span_id"])),
                 "pid": st["pid"], "tid": tid or f"rank {rank}",
                 "args": args or {}}
            spans.append(s)
            return s

        if ev == "run_header":
            st["seg"] += 1
            close_segment(st, t)
            run = st["run_span"] = {
                "name": f"run segment {st['seg']}", "cat": "run",
                "t0": t, "t1": t, "trace_id": st["trace_id"],
                "span_id": f"{st['worker']['span_id']}"
                           f"/p{rank}/seg{st['seg']}",
                "parent_span_id": st["worker"]["span_id"],
                "pid": st["pid"], "tid": f"rank {rank}",
                "args": {"process_index": rank,
                         "hostname": e.get("hostname"),
                         "platform": e.get("platform"),
                         "steps_total": e.get("steps_total")}}
            spans.append(run)
            continue
        if run is not None:
            run["t1"] = max(run["t1"], t)
        if ev == "chunk":
            w = e.get("wall_s")
            w = w if isinstance(w, (int, float)) else 0.0
            child(f"chunk @{e.get('step')}", "chunk", t - w, t,
                  args={k: e.get(k) for k in
                        ("step", "steps", "steps_per_s",
                         "mcells_steps_per_s", "residual", "finite",
                         "gap_s", "observe_s", "drain_wait_s")
                        if e.get(k) is not None})
        elif ev == "checkpoint_save":
            w = e.get("wall_s")
            w = w if isinstance(w, (int, float)) else 0.0
            child(f"checkpoint_save g{e.get('generation')}",
                  "checkpoint", t - w, t,
                  args={k: e.get(k) for k in
                        ("step", "generation", "async", "path")
                        if e.get(k) is not None})
        elif ev == "checkpoint_barrier":
            w = e.get("wait_s")
            w = w if isinstance(w, (int, float)) else 0.0
            child(f"commit gate ({e.get('reason')})", "checkpoint",
                  t - w, t, args={"reason": e.get("reason"),
                                  "wait_s": e.get("wait_s")})
        elif ev == "barrier_wait":
            w = e.get("wait_s")
            w = w if isinstance(w, (int, float)) else 0.0
            child(f"barrier_wait @{e.get('step')}", "consensus",
                  t - w, t, args={"step": e.get("step"),
                                  "wait_s": e.get("wait_s")})
        elif ev == "rollback":
            w = e.get("load_wall_s")
            w = w if isinstance(w, (int, float)) else 0.0
            child(f"rollback load -> step {e.get('step')}",
                  "rollback", t - w, t,
                  args={"step": e.get("step"), "path": e.get("path")})
            st["open_segment"] = child(
                f"replay from step {e.get('step')}", "rollback",
                t, t, args={"from_step": e.get("step")})
        elif ev == "pack_header":
            st["pack_t0"] = t
            if run is None:
                # Packed worker streams open with pack_header before
                # the engine's run_header: give the members a parent.
                run = st["run_span"] = child(
                    f"pack {e.get('pack')}", "pack", t, t,
                    args={"members": e.get("members"),
                          "job_ids": e.get("job_ids")})
        elif ev == "member_end":
            m = e.get("member")
            t0 = st["pack_t0"]
            t0 = t0 if t0 is not None else (run["t0"] if run else t)
            child(f"member {m}", "member", t0, t,
                  tid=f"rank {rank} member {m}",
                  args={k: e.get(k) for k in
                        ("member", "step", "converged", "residual",
                         "finite") if e.get(k) is not None})
        elif ev == "run_end":
            close_segment(st, t)
            if run is not None:
                run["t1"] = t
                run["args"]["outcome"] = e.get("outcome")
            st["run_span"] = None
        elif ev in _INSTANT_EVENTS:
            st["seq"] += 1
            instants.append({
                "name": ev, "cat": "lifecycle", "t0": t,
                "trace_id": st["trace_id"],
                "span_id": f"{st['worker']['span_id']}"
                           f"/p{rank}.i{st['seq']}",
                "parent_span_id": (run["span_id"] if run
                                   else st["worker"]["span_id"]),
                "pid": st["pid"],
                "tid": (f"rank {rank} member {e['member']}"
                        if e.get("member") is not None
                        else f"rank {rank}"),
                "args": {k: v for k, v in e.items()
                         if k not in ("schema", "event", "t_wall",
                                      "t_mono")}})
        # close any segment still open at stream end
    for st in ranks.values():
        close_segment(st, st["worker"]["t1"])
        if st["worker"]["t0"] is None:
            spans.remove(st["worker"])
    # Ranks of one traced stream share the envelope's worker span —
    # coalesce the per-lane observations of it into one.
    return merge_spans(spans), instants


def spans_from_journal(events: Sequence[dict]
                       ) -> Tuple[List[dict], List[dict]]:
    """Derive fleet-side spans from a heatd journal: per job a ``job``
    span (accepted → terminal), ``queue wait`` spans (accepted →
    dispatched, and requeued → re-dispatched — the live metric
    ``tools/monitor.py --daemon`` and the queue-wait SLO watch),
    and per-attempt ``dispatch`` spans whose ids the worker's
    telemetry envelope points at (``dispatch_span_id``). Instants for
    orphanings, requeues, failures and terminal verdicts."""
    spans: List[dict] = []
    instants: List[dict] = []
    jobs: Dict[str, dict] = {}
    for e in events:
        if not isinstance(e, dict):
            continue
        jid, ev, t = e.get("job_id"), e.get("event"), e.get("t_wall")
        if jid is None or ev is None or not isinstance(t, (int, float)):
            continue
        j = jobs.get(jid)
        if j is None:
            j = jobs[jid] = {"trace_id": _UNTRACED, "span": None,
                             "wait_from": None, "open_attempt": None,
                             "n": 0}
        if isinstance(e.get("trace_id"), str):
            j["trace_id"] = e["trace_id"]
        pid = f"job {jid}"

        def mark(name, args=None):
            j["n"] += 1
            instants.append({
                "name": name, "cat": "queue", "t0": t,
                "trace_id": j["trace_id"],
                "span_id": f"{submit_span_id(jid)}.i{j['n']}",
                "parent_span_id": submit_span_id(jid),
                "pid": pid, "tid": "queue",
                "args": args or {}})

        if ev == "accepted":
            j["span"] = {"name": f"job {jid}", "cat": "job",
                         "t0": t, "t1": t, "trace_id": j["trace_id"],
                         "span_id": submit_span_id(jid),
                         "parent_span_id": None,
                         "pid": pid, "tid": "queue",
                         "args": {"job_id": jid}}
            spans.append(j["span"])
            j["wait_from"] = t
            continue
        if j["span"] is None:
            continue  # rejected / pre-acceptance noise
        j["span"]["t1"] = max(j["span"]["t1"], t)
        j["span"]["trace_id"] = j["trace_id"]
        if ev == "dispatched":
            if j["wait_from"] is not None:
                j["n"] += 1
                spans.append({
                    "name": "queue wait", "cat": "queue",
                    "t0": j["wait_from"], "t1": t,
                    "trace_id": j["trace_id"],
                    "span_id": f"{submit_span_id(jid)}.q{j['n']}",
                    "parent_span_id": submit_span_id(jid),
                    "pid": pid, "tid": "queue",
                    "args": {"wait_s": t - j["wait_from"]}})
                j["wait_from"] = None
            att = int(e.get("attempt") or 1)
            a = {"name": f"attempt a{att:03d} ({e.get('worker')})",
                 "cat": "dispatch", "t0": t, "t1": t,
                 "trace_id": j["trace_id"],
                 "span_id": dispatch_span_id(jid, att),
                 "parent_span_id": submit_span_id(jid),
                 "pid": pid, "tid": "queue",
                 "args": {"worker": e.get("worker"),
                          "attempt": att, "pack": e.get("pack")}}
            spans.append(a)
            j["open_attempt"] = a
        else:
            a = j.get("open_attempt")
            if a is not None:
                a["t1"] = max(a["t1"], t)
            if ev == "cache_hit":
                # The O(1) serve: accepted -> verdict with no dispatch
                # in between. Rendered as a real span (accepted_t to
                # the hit line) so a warm submit's whole latency is
                # one visible bar — the thing the serve_cache bench
                # row measures (SEMANTICS.md "Cache soundness").
                j["n"] += 1
                spans.append({
                    "name": f"cache hit ({e.get('kind') or 'exact'})",
                    "cat": "cache", "t0": j["span"]["t0"], "t1": t,
                    "trace_id": j["trace_id"],
                    "span_id": f"{submit_span_id(jid)}.c{j['n']}",
                    "parent_span_id": submit_span_id(jid),
                    "pid": pid, "tid": "queue",
                    "args": {"key": e.get("key"),
                             "donor": e.get("donor"),
                             "generation_step": e.get("generation_step"),
                             "steps_saved": e.get("steps_saved"),
                             "bytes_saved": e.get("bytes_saved")}})
                j["wait_from"] = None
            elif ev == "cache_prefix":
                mark("cache_prefix",
                     {"key": e.get("key"), "donor": e.get("donor"),
                      "generation_step": e.get("generation_step")})
            elif ev == "requeued":
                j["wait_from"] = float(e.get("not_before") or t)
                j["open_attempt"] = None
                mark("requeued", {"reason": e.get("reason")})
            elif ev in ("orphaned", "worker_failed", "cancel_requested"):
                j["open_attempt"] = None
                mark(ev, {"reason": e.get("reason"),
                          "kind": e.get("kind")})
            elif ev in ("completed", "quarantined", "cancelled",
                        "deadline_expired"):
                j["open_attempt"] = None
                mark(ev, {"kind": e.get("kind"),
                          "steps_done": e.get("steps_done")})
    return spans, instants


# ---------------------------------------------------------------------------
# Chrome trace-event export
# ---------------------------------------------------------------------------

def counters_from_stream(events: Sequence[dict],
                         pid_label: Optional[str] = None
                         ) -> List[dict]:
    """Derive Perfetto counter-track samples from a stream's
    ``profile`` events (prof/attrib.py): one ``roofline_frac`` series
    and one stacked ``bound_share`` series (the compute/hbm/ici/host
    lane split) per lane. Same wall anchoring as
    :func:`spans_from_stream` (t_mono + the run_header offset), so the
    counters line up under the chunk spans on the shared timeline.
    Foreign or torn-in lines are skipped — degrade, never crash."""
    counters: List[dict] = []
    offsets: Dict[int, float] = {}
    for e in events:
        if not isinstance(e, dict):
            continue
        ev = e.get("event")
        rank = e.get("process_index")
        rank = rank if isinstance(rank, int) else 0
        tm, tw = e.get("t_mono"), e.get("t_wall")
        if ev == "run_header":
            if isinstance(tm, (int, float)) and isinstance(tw,
                                                           (int, float)):
                offsets[rank] = tw - tm
            continue
        if ev != "profile":
            continue
        if rank in offsets and isinstance(tm, (int, float)):
            t = tm + offsets[rank]
        elif isinstance(tw, (int, float)):
            t = tw
        else:
            continue
        job_id = e.get("job_id")
        pid = pid_label or (f"job {job_id}"
                            if isinstance(job_id, str) else "run")
        tid = f"rank {rank}"
        rf = e.get("roofline_frac")
        if isinstance(rf, (int, float)):
            counters.append({"name": "roofline_frac", "t0": t,
                             "pid": pid, "tid": tid,
                             "value": float(rf)})
        shares = e.get("shares")
        if isinstance(shares, dict):
            vals = {k: float(v) for k, v in shares.items()
                    if isinstance(v, (int, float))}
            if vals:
                counters.append({"name": "bound_share", "t0": t,
                                 "pid": pid, "tid": tid,
                                 "value": vals})
    return counters


def chrome_trace(spans: Sequence[dict],
                 instants: Sequence[dict] = (),
                 counters: Sequence[dict] = ()) -> dict:
    """Render spans + instants as a Chrome trace-event document
    (``{"traceEvents": [...], "displayTimeUnit": "ms"}``) that opens
    in Perfetto / ``chrome://tracing``. Lanes (string ``pid``/``tid``)
    map onto stable numeric ids with ``process_name`` /
    ``thread_name`` metadata events; the causal ids ride each event's
    ``args`` (``trace_id`` / ``span_id`` / ``parent_span_id``) so the
    parentage survives the export."""
    pids: Dict[str, int] = {}
    tids: Dict[Tuple[str, str], int] = {}
    meta: List[dict] = []
    out: List[dict] = []

    def ids(span):
        p = pids.get(span["pid"])
        if p is None:
            p = pids[span["pid"]] = len(pids) + 1
            meta.append({"name": "process_name", "ph": "M", "pid": p,
                         "tid": 0,
                         "args": {"name": span["pid"]}})
        key = (span["pid"], span["tid"])
        t = tids.get(key)
        if t is None:
            t = tids[key] = len(tids) + 1
            meta.append({"name": "thread_name", "ph": "M", "pid": p,
                         "tid": t, "args": {"name": span["tid"]}})
        return p, t

    t_min = min((s["t0"] for s in (list(spans) + list(instants)
                                   + list(counters))),
                default=0.0)
    for s in spans:
        p, t = ids(s)
        args = dict(s.get("args") or {})
        args.update({"trace_id": s["trace_id"],
                     "span_id": s["span_id"],
                     "parent_span_id": s.get("parent_span_id")})
        out.append({"name": s["name"], "cat": s.get("cat", "span"),
                    "ph": "X",
                    "ts": (s["t0"] - t_min) * 1e6,
                    "dur": max(0.0, (s["t1"] - s["t0"]) * 1e6),
                    "pid": p, "tid": t, "args": args})
    for s in instants:
        p, t = ids(s)
        args = dict(s.get("args") or {})
        args.update({"trace_id": s["trace_id"],
                     "span_id": s["span_id"],
                     "parent_span_id": s.get("parent_span_id")})
        out.append({"name": s["name"], "cat": s.get("cat", "mark"),
                    "ph": "i", "s": "t",
                    "ts": (s["t0"] - t_min) * 1e6,
                    "pid": p, "tid": t, "args": args})
    for c in counters:
        # Counter tracks ("C" phase): Perfetto renders one track per
        # (pid, name); a dict value becomes a stacked multi-series
        # track (the bound_share lane split).
        p, t = ids(c)
        v = c["value"]
        args = ({k: v[k] for k in sorted(v)} if isinstance(v, dict)
                else {"value": v})
        out.append({"name": c["name"], "cat": "counter", "ph": "C",
                    "ts": (c["t0"] - t_min) * 1e6,
                    "pid": p, "tid": t, "args": args})
    return {"traceEvents": meta + out, "displayTimeUnit": "ms",
            "otherData": {"t_min_wall": t_min,
                          "generator": "parallel_heat_tpu heattrace"}}


def link_streams_to_journal(stream_spans: Sequence[dict],
                            journal_spans: Sequence[dict]) -> int:
    """Stitch the two halves of the chain: a stream's synthetic
    ``worker`` span whose envelope carried no parent (an older writer,
    or a stream read without its spec) is re-parented onto the
    journal's matching dispatch span by deterministic id; worker spans
    that already point at a journal span are left alone. Returns the
    number of spans linked."""
    by_id = {s["span_id"] for s in journal_spans}
    linked = 0
    for s in stream_spans:
        if s.get("cat") != "worker":
            continue
        if s.get("parent_span_id") in by_id:
            linked += 1
            continue
        jid = (s.get("args") or {}).get("job_id")
        if not jid:
            continue
        # Newest attempt whose dispatch span exists: attempts count up.
        for att in range(999, 0, -1):
            did = dispatch_span_id(jid, att)
            if did in by_id:
                s["parent_span_id"] = did
                linked += 1
                break
    return linked
