from parallel_heat_tpu.utils.io import write_dat, read_dat
from parallel_heat_tpu.utils.timing import Timer

__all__ = ["write_dat", "read_dat", "Timer"]
