"""The recorder: journals + telemetry -> durable ring-buffer series.

One :class:`Recorder` watches a queue root (or a whole fleet root) and
folds everything the service already writes — partition state journals,
per-job telemetry sinks, daemon/host heartbeats, lease files — into
per-``(host, partition, counter)`` time series with three retention
tiers (raw points -> 1-minute rollups -> 1-hour rollups). Nothing in
the serving path changes: the recorder is a pure reader of artifacts
other processes commit, exactly like ``tools/monitor.py``, but it
PERSISTS what it reads so trends survive the recorder itself.

Durability is the store's own discipline, applied twice:

- every harvest pass appends ONE fsynced line to the active delta
  journal — ``{"event": "harvest", "t", "samples": [...], "cursors":
  {...}}`` — carrying both the new samples and the advanced source
  cursors, so a SIGKILL between any two passes loses nothing and a
  SIGKILL mid-append leaves one torn tail line the replay skips.
  Samples and cursor advance commit TOGETHER or not at all: a replayed
  recorder can never double-count a source line;
- compaction rename-commits a snapshot (``snapshot.json``, folded
  state + generation) and rotates to a fresh delta file; recovery
  loads the snapshot and refolds only delta files of its generation or
  newer. A crash inside the compaction window leaves either the old
  snapshot + full deltas (refold) or the new snapshot + stale delta
  files it ignores by generation — both exact.

The fold itself (:func:`reduce_obs`) is a pure left fold with the
journal reducers' incremental law — ``reduce(prefix) then
reduce(suffix, state) == reduce(prefix + suffix)`` at EVERY cut
(pinned by ``test_obs_fold_law_every_cut``) — which is what makes the
snapshot/delta split correct by construction rather than by protocol.
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Dict, List, Optional, Tuple

from parallel_heat_tpu.service.store import (
    Journal, read_journal_file)
from parallel_heat_tpu.utils.checkpoint import _fsync_replace

OBS_SCHEMA_VERSION = 1

# Retention tiers: raw points per series, then 1-minute and 1-hour
# downsampled rollup buckets. Caps bound the snapshot (and therefore
# recorder memory) to O(series * caps) regardless of fleet age:
# ~8.5 hours of 1 Hz raw, 24 hours of minutes, 30 days of hours.
RAW_CAP = 512
M1_CAP = 1440
H1_CAP = 720
M1_BUCKET_S = 60.0
H1_BUCKET_S = 3600.0

# Compaction threshold for the active delta journal. Small enough that
# recovery refolds are cheap, large enough that steady-state polling
# rarely compacts.
COMPACT_BYTES = 1 << 18

# State-journal event -> fleet counter. Every entry is a monotone
# per-(host, partition) event count; the fold accumulates the
# cumulative totals OpenMetrics counters want.
JOURNAL_COUNTERS = {
    "accepted": "jobs_accepted",
    "rejected": "jobs_rejected",
    "dispatched": "dispatches",
    "completed": "completed",
    "quarantined": "quarantined",
    "cancelled": "cancelled",
    "deadline_expired": "deadline_expired",
    "requeued": "requeues",
    "orphaned": "orphaned",
    "worker_failed": "worker_failures",
    "host_lost": "hosts_lost",
    "adopted": "jobs_adopted",
    "lease_claimed": "lease_claims",
}


def obs_dir_for(root) -> str:
    """The observability plane of one root lives beside the data it
    observes — ``<root>/obs/`` — so a fleet root carries exactly one
    recorder the same way it carries one ``fleet.json``."""
    return os.path.join(str(root), "obs")


def new_state() -> dict:
    return {"schema": OBS_SCHEMA_VERSION, "series": {}, "cursors": {},
            "last_t": None, "n_samples": 0, "n_harvests": 0}


def series_key(host: str, part: str, counter: str) -> str:
    return f"{host}|{part}|{counter}"


# ---------------------------------------------------------------------------
# The pure fold
# ---------------------------------------------------------------------------

def _bucket_fold(buckets: List[list], bucket_t: float, value: float,
                 cap: int) -> None:
    """Fold one point into a rollup tier (in place). Downsampling is
    itself a left fold: the newest bucket aggregates min/max/sum/count/
    last, a new bucket time appends, the cap trims from the front. A
    sample older than the newest bucket merges into its own bucket if
    that bucket is still retained and is dropped otherwise — late data
    can never reorder the ring."""
    if buckets and bucket_t < buckets[-1][0]:
        for b in reversed(buckets):
            if b[0] == bucket_t:
                agg = b[1]
                break
            if b[0] < bucket_t:
                return  # its bucket was never created: drop
        else:
            return  # older than the whole ring: drop
    elif buckets and bucket_t == buckets[-1][0]:
        agg = buckets[-1][1]
    else:
        buckets.append([bucket_t, {"min": value, "max": value,
                                   "sum": value, "count": 1,
                                   "last": value}])
        del buckets[:-cap]
        return
    agg["min"] = min(agg["min"], value)
    agg["max"] = max(agg["max"], value)
    agg["sum"] += value
    agg["count"] += 1
    agg["last"] = value


def _fold_sample(state: dict, s: dict) -> None:
    try:
        t = float(s["t"])
        value = float(s["value"])
        counter = str(s["counter"])
    except (KeyError, TypeError, ValueError):
        return  # foreign/torn sample: ignored, never fatal
    if not (math.isfinite(t) and math.isfinite(value)):
        return
    host = str(s.get("host") or "")
    part = str(s.get("part") or "")
    kind = "counter" if s.get("kind") == "counter" else "gauge"
    key = series_key(host, part, counter)
    ser = state["series"].get(key)
    if ser is None:
        ser = state["series"][key] = {
            "host": host, "part": part, "counter": counter,
            "kind": kind, "raw": [], "m1": [], "h1": []}
    if ser["kind"] == "counter":
        # Samples carry INCREMENTS; the fold owns the cumulative total
        # (what a restart-spanning OpenMetrics counter needs), so the
        # harvester stays stateless about totals.
        prev = ser["raw"][-1][1] if ser["raw"] else 0.0
        value = prev + value
    ser["raw"].append([t, value])
    del ser["raw"][:-RAW_CAP]
    _bucket_fold(ser["m1"], math.floor(t / M1_BUCKET_S) * M1_BUCKET_S,
                 value, M1_CAP)
    _bucket_fold(ser["h1"], math.floor(t / H1_BUCKET_S) * H1_BUCKET_S,
                 value, H1_CAP)
    state["n_samples"] += 1


def reduce_obs(events, state: Optional[dict] = None) -> dict:
    """Pure left fold of delta-journal events -> series state.

    Same incremental law as ``reduce_journal``/``reduce_tune_journal``:
    pass a previous call's state to fold only appended events —
    ``reduce(prefix) then reduce(suffix, state)`` equals
    ``reduce(prefix + suffix)`` at every cut. Unknown events and
    fields are ignored (forward compatibility)."""
    if state is None:
        state = new_state()
    for e in events:
        if e.get("event") != "harvest":
            continue
        for s in e.get("samples") or []:
            if isinstance(s, dict):
                _fold_sample(state, s)
        if isinstance(e.get("cursors"), dict):
            state["cursors"] = e["cursors"]
        t = e.get("t")
        if isinstance(t, (int, float)):
            state["last_t"] = (t if state["last_t"] is None
                               else max(state["last_t"], t))
        state["n_harvests"] += 1
    return state


# ---------------------------------------------------------------------------
# Harvest: source artifacts -> samples (the impure edge)
# ---------------------------------------------------------------------------

def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def _new_complete_lines(path: str, offset: int) -> Tuple[list, int]:
    """JSON records appended past ``offset``, consuming only WHOLE
    lines (the ``TuneDB.entries`` offset discipline): a read racing an
    appender re-reads the torn tail complete next pass, so a record is
    harvested exactly once or not yet — never half."""
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read()
    except OSError:
        return [], offset
    end = data.rfind(b"\n")
    if end < 0:
        return [], offset
    recs = []
    for line in data[:end + 1].split(b"\n"):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec, dict):
            recs.append(rec)
    return recs, offset + end + 1


def _is_fleet_root(root: str) -> bool:
    return os.path.isfile(os.path.join(root, "fleet.json"))


def _partition_roots(root: str) -> List[Tuple[str, str]]:
    parts_dir = os.path.join(root, "parts")
    try:
        names = sorted(n for n in os.listdir(parts_dir)
                       if not n.startswith(".")
                       and os.path.isdir(os.path.join(parts_dir, n)))
    except OSError:
        return []
    return [(n, os.path.join(parts_dir, n)) for n in names]


def _sample(samples: list, *, t, host, part, counter, kind, value
            ) -> None:
    samples.append({"t": float(t), "host": str(host or ""),
                    "part": str(part or ""), "counter": str(counter),
                    "kind": kind, "value": float(value)})


def _harvest_journal(part_root: str, part: str, pc: dict,
                     samples: list, now: float) -> None:
    recs, off = _new_complete_lines(
        os.path.join(part_root, "journal.jsonl"),
        int(pc.get("journal") or 0))
    pc["journal"] = off
    accepted = pc.setdefault("accepted", {})
    job_host = pc.setdefault("job_host", {})
    for e in recs:
        ev = e.get("event")
        if not isinstance(ev, str):
            continue
        t = e.get("t_wall")
        t = float(t) if isinstance(t, (int, float)) else now
        host = str(e.get("host") or "")
        jid = e.get("job_id")
        counter = JOURNAL_COUNTERS.get(ev)
        if counter:
            _sample(samples, t=t, host=host, part=part,
                    counter=counter, kind="counter", value=1)
        if ev == "completed" and isinstance(e.get("cache"), dict):
            _sample(samples, t=t, host=host, part=part,
                    counter="cache_hits", kind="counter", value=1)
        if ev == "lease_claimed" and e.get("kind") in ("steal",
                                                       "takeover"):
            _sample(samples, t=t, host=host, part=part,
                    counter="lease_takeovers", kind="counter", value=1)
        if not isinstance(jid, str):
            continue
        if ev == "accepted":
            accepted[jid] = t
        elif ev == "dispatched":
            job_host[jid] = host
            t_acc = accepted.pop(jid, None)
            if t_acc is not None:
                # First dispatch only (the pop is the latch): the
                # queue-wait gauge mirrors metrics_report's
                # accepted -> first-dispatch join.
                _sample(samples, t=t, host=host, part=part,
                        counter="queue_wait_s", kind="gauge",
                        value=max(0.0, t - t_acc))
        elif ev in ("completed", "quarantined", "cancelled",
                    "deadline_expired", "rejected"):
            accepted.pop(jid, None)


def _harvest_telemetry(part_root: str, part: str, pc: dict,
                       samples: list) -> None:
    tdir = os.path.join(part_root, "telemetry")
    try:
        names = sorted(n for n in os.listdir(tdir)
                       if n.endswith(".jsonl") and not n.startswith("."))
    except OSError:
        return
    offsets = pc.setdefault("telemetry", {})
    for gone in [n for n in offsets if n not in names]:
        del offsets[gone]
    job_host = pc.get("job_host") or {}
    for name in names:
        recs, off = _new_complete_lines(os.path.join(tdir, name),
                                        int(offsets.get(name) or 0))
        offsets[name] = off
        host = job_host.get(name.partition(".")[0], "")
        for e in recs:
            ev = e.get("event")
            t = e.get("t_wall")
            if not isinstance(t, (int, float)):
                continue
            if ev == "profile":
                # The efficiency plane (prof): roofline fraction as a
                # per-(host, part) gauge — the efficiency_regression
                # alert and monitor --fleet read this series — plus a
                # per-bound counter for the attribution mix.
                v = e.get("roofline_frac")
                if isinstance(v, (int, float)) and math.isfinite(v):
                    _sample(samples, t=t, host=host, part=part,
                            counter="roofline_frac", kind="gauge",
                            value=v)
                b = e.get("bound")
                if isinstance(b, str) and b in ("compute", "hbm",
                                                "ici", "host"):
                    _sample(samples, t=t, host=host, part=part,
                            counter=f"bound_{b}", kind="counter",
                            value=1)
                continue
            if ev != "chunk":
                continue
            _sample(samples, t=t, host=host, part=part,
                    counter="chunks", kind="counter", value=1)
            for gauge in ("steps_per_s", "mcells_steps_per_s",
                          "gap_s"):
                v = e.get(gauge)
                if isinstance(v, (int, float)) and math.isfinite(v):
                    _sample(samples, t=t, host=host, part=part,
                            counter=gauge, kind="gauge", value=v)


def _harvest_daemon_status(part_root: str, part: str, samples: list,
                           now: float) -> None:
    doc = _read_json(os.path.join(part_root, "heatd.json"))
    if doc is None:
        return
    t = doc.get("t_wall")
    if isinstance(t, (int, float)):
        _sample(samples, t=now, host=str(doc.get("host") or ""),
                part=part, counter="daemon_hb_age_s", kind="gauge",
                value=max(0.0, now - t))
    counts = doc.get("counts") or {}
    for gauge in ("queued", "running"):
        v = counts.get(gauge)
        if isinstance(v, (int, float)):
            _sample(samples, t=now, host=str(doc.get("host") or ""),
                    part=part, counter=gauge, kind="gauge", value=v)


def _harvest_fleet_level(root: str, samples: list, now: float) -> None:
    hosts_dir = os.path.join(root, "hosts")
    try:
        names = sorted(n for n in os.listdir(hosts_dir)
                       if n.endswith(".json") and not n.startswith("."))
    except OSError:
        names = []
    for n in names:
        doc = _read_json(os.path.join(hosts_dir, n))
        if doc is None or not doc.get("host"):
            continue
        t = doc.get("t_wall")
        if isinstance(t, (int, float)):
            _sample(samples, t=now, host=doc["host"], part="",
                    counter="host_record_age_s", kind="gauge",
                    value=max(0.0, now - t))
    leases_dir = os.path.join(root, "leases")
    held: Dict[str, int] = {}
    try:
        lnames = sorted(n for n in os.listdir(leases_dir)
                        if n.endswith(".json") and not n.startswith("."))
    except OSError:
        lnames = []
    for n in lnames:
        doc = _read_json(os.path.join(leases_dir, n))
        if doc is not None and doc.get("host"):
            held[doc["host"]] = held.get(doc["host"], 0) + 1
    for host, count in sorted(held.items()):
        _sample(samples, t=now, host=host, part="",
                counter="leases_held", kind="gauge", value=count)


def harvest(root, cursors: dict, now: Optional[float] = None
            ) -> Tuple[list, dict]:
    """One incremental pass over a queue/fleet root ->
    ``(samples, advanced_cursors)``.

    Deterministic given the disk and ``now``; never mutates its
    ``cursors`` argument (the caller commits samples and cursors
    together in one journal line, so an append that fails must leave
    the in-memory cursors untouched)."""
    now = time.time() if now is None else float(now)
    root = str(root)
    cursors = json.loads(json.dumps(cursors)) if cursors else {}
    samples: list = []
    fleet = _is_fleet_root(root)
    parts = _partition_roots(root) if fleet else [("", root)]
    pcs = cursors.setdefault("parts", {})
    for name, path in parts:
        pc = pcs.setdefault(name or "_", {})
        _harvest_journal(path, name, pc, samples, now)
        _harvest_telemetry(path, name, pc, samples)
        _harvest_daemon_status(path, name, samples, now)
    if fleet:
        _harvest_fleet_level(root, samples, now)
    return samples, cursors


# ---------------------------------------------------------------------------
# Persistence: delta journal generations + snapshot compaction
# ---------------------------------------------------------------------------

def _snapshot_path(obs_dir: str) -> str:
    return os.path.join(obs_dir, "snapshot.json")


def _delta_path(obs_dir: str, gen: int) -> str:
    return os.path.join(obs_dir, f"deltas.{int(gen):08d}.jsonl")


def _delta_gens(obs_dir: str) -> List[int]:
    try:
        names = os.listdir(obs_dir)
    except OSError:
        return []
    gens = []
    for n in names:
        if n.startswith("deltas.") and n.endswith(".jsonl"):
            try:
                gens.append(int(n[len("deltas."):-len(".jsonl")]))
            except ValueError:
                continue
    return sorted(gens)


def load_state(obs_dir: str) -> Tuple[dict, int]:
    """Recover ``(state, active_generation)`` from one obs dir — the
    read-only loader ``monitor``/``slo_gate``/``metrics_report`` share
    with the recorder's own startup.

    Snapshot generation N covers every delta file of generation < N;
    recovery folds files of generation >= N in order through the same
    pure reducer the live recorder uses, skipping torn tails. A
    missing/torn snapshot degrades to a full refold of the deltas — a
    crash can delay compaction, never lose samples."""
    obs_dir = str(obs_dir)
    state, gen = new_state(), 1
    snap = _read_json(_snapshot_path(obs_dir))
    if (snap is not None
            and snap.get("schema") == OBS_SCHEMA_VERSION
            and isinstance(snap.get("state"), dict)
            and isinstance(snap.get("gen"), int)):
        state, gen = snap["state"], snap["gen"]
    for g in _delta_gens(obs_dir):
        if g < gen:
            continue  # compaction residue: already inside the snapshot
        events, _bad, _torn = read_journal_file(_delta_path(obs_dir, g))
        reduce_obs(events, state)
        gen = max(gen, g)
    return state, gen


class Recorder:
    """The write handle of one obs dir: harvest -> fsynced delta line
    -> in-memory fold, with snapshot compaction past a size threshold.
    One recorder per root by design (like one daemon per queue root);
    the heartbeat file names the owner for ``monitor``'s
    recorder-down rendering."""

    def __init__(self, root, obs_dir: Optional[str] = None):
        self.root = str(root)
        self.obs_dir = str(obs_dir) if obs_dir else obs_dir_for(root)
        os.makedirs(self.obs_dir, exist_ok=True)
        self.state, self.gen = load_state(self.obs_dir)
        self._journal: Optional[Journal] = None

    @property
    def journal(self) -> Journal:
        if self._journal is None:
            self._journal = Journal(_delta_path(self.obs_dir, self.gen))
        return self._journal

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    def __enter__(self) -> "Recorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def poll(self, now: Optional[float] = None,
             compact: bool = True) -> int:
        """One harvest pass: samples + advanced cursors land in ONE
        journal line (commit or vanish together), then fold into the
        live state. Returns the number of new samples."""
        now = time.time() if now is None else float(now)
        samples, cursors = harvest(self.root, self.state["cursors"],
                                   now)
        rec = self.journal.append("harvest", t=now, samples=samples,
                                  cursors=cursors)
        reduce_obs([rec], self.state)
        if compact:
            try:
                if (os.path.getsize(_delta_path(self.obs_dir,
                                                self.gen))
                        > COMPACT_BYTES):
                    self.compact()
            except OSError:
                pass
        return len(samples)

    def compact(self) -> int:
        """Rename-commit the folded state as generation ``gen + 1``,
        rotate to a fresh delta file, sweep superseded delta files.
        Crash windows: before the snapshot rename -> old snapshot +
        full deltas refold; after it -> stale delta files are ignored
        by generation. Returns the new generation."""
        new_gen = self.gen + 1
        snap = {"schema": OBS_SCHEMA_VERSION, "gen": new_gen,
                "state": self.state, "t_wall": time.time()}
        path = _snapshot_path(self.obs_dir)
        tmp = os.path.join(self.obs_dir,
                           f".tmp-{os.getpid()}-snapshot.json")
        with open(tmp, "w") as f:
            json.dump(snap, f)
        _fsync_replace(tmp, path)
        self.close()
        old = self.gen
        self.gen = new_gen
        for g in _delta_gens(self.obs_dir):
            if g <= old:
                try:
                    os.unlink(_delta_path(self.obs_dir, g))
                except OSError:
                    pass
        return new_gen

    # -- recorder heartbeat (monitor's down-vs-idle discriminator) ----

    def heartbeat_path(self) -> str:
        return os.path.join(self.obs_dir, "recorder.json")

    def write_heartbeat(self, interval_s: float,
                        now: Optional[float] = None) -> None:
        now = time.time() if now is None else float(now)
        doc = {"schema": OBS_SCHEMA_VERSION, "pid": os.getpid(),
               "t_wall": now, "interval_s": float(interval_s),
               "n_samples": self.state["n_samples"],
               "n_harvests": self.state["n_harvests"],
               "last_t": self.state["last_t"]}
        tmp = os.path.join(self.obs_dir,
                           f".tmp-{os.getpid()}-recorder.json")
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f)
            _fsync_replace(tmp, self.heartbeat_path())
        except OSError:
            pass  # liveness probe only — never kill the recorder


def read_recorder_heartbeat(obs_dir: str) -> Optional[dict]:
    return _read_json(os.path.join(str(obs_dir), "recorder.json"))


# ---------------------------------------------------------------------------
# Windowed summaries (slo_gate --window / metrics_report --rollup)
# ---------------------------------------------------------------------------

def _percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    vs = sorted(values)
    idx = max(0, min(len(vs) - 1,
                     int(math.ceil(q / 100.0 * len(vs))) - 1))
    return vs[idx]


def _value_at(raw: List[list], t: float) -> float:
    """Cumulative counter value as of ``t`` (0 before the first
    retained point — a window older than the raw ring under-reports
    the delta rather than inventing one)."""
    v = 0.0
    for ts, val in raw:
        if ts > t:
            break
        v = val
    return v


def summarize_window(state: dict, t0: Optional[float] = None,
                     t1: Optional[float] = None) -> dict:
    """Aggregate the series over ``[t0, t1]`` into the flat metric doc
    the shared ``--fail-on`` grammar gates on (``None`` bounds are
    unbounded). Counters become window deltas summed across all
    (host, partition) series; gauges become percentile dicts over the
    window's raw samples. ``cache_hit_rate`` is derived from the
    windowed deltas, ``None`` until the window holds a completion —
    same unmeasured-passes convention as the snapshot summaries."""
    lo = -math.inf if t0 is None else float(t0)
    hi = math.inf if t1 is None else float(t1)
    counters: Dict[str, float] = {}
    gauges: Dict[str, List[float]] = {}
    for ser in state.get("series", {}).values():
        raw = ser.get("raw") or []
        if ser.get("kind") == "counter":
            delta = (_value_at(raw, hi)
                     - (_value_at(raw, lo) if lo > -math.inf else 0.0))
            counters[ser["counter"]] = (counters.get(ser["counter"],
                                                     0.0) + delta)
        else:
            vals = [v for t, v in raw if lo <= t <= hi]
            if vals:
                gauges.setdefault(ser["counter"], []).extend(vals)
    doc: dict = {"window": {"since": t0, "until": t1},
                 "n_samples": state.get("n_samples", 0),
                 "last_sample_t": state.get("last_t")}
    for name, v in sorted(counters.items()):
        doc[name] = v
    completed = counters.get("completed", 0.0)
    doc["cache_hit_rate"] = (counters.get("cache_hits", 0.0) / completed
                             if completed > 0 else None)
    for name, vals in sorted(gauges.items()):
        doc[name] = {"p50": _percentile(vals, 50.0),
                     "p99": _percentile(vals, 99.0),
                     "max": max(vals), "mean": sum(vals) / len(vals),
                     "last": vals[-1], "n": len(vals)}
    return doc
