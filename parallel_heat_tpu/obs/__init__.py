"""Fleet flight recorder — a journal-backed time-series metrics plane.

Three layers, each under the store's proven disk discipline
(SEMANTICS.md "Job durability"; docs/OBSERVABILITY.md "Time series"):

- :mod:`parallel_heat_tpu.obs.series` — the recorder: folds fleet/queue
  journals and telemetry streams into per-``(host, partition, counter)``
  ring-buffer series through a pure, fold-law-tested reducer. Persists
  as an append-only fsynced delta journal plus a rename-committed
  snapshot (compaction), so a SIGKILLed recorder recovers by
  construction — torn tails are invisible to the replay.
- :mod:`parallel_heat_tpu.obs.expo` — exposition: renders the live
  series as OpenMetrics/Prometheus text (atomic textfile and a stdlib
  HTTP endpoint) so standard scrapers watch a fleet with zero custom
  tooling.
- :mod:`parallel_heat_tpu.obs.alerts` — alerting: joins live run
  throughput against the tuning DB's measured winner for the same
  ``(site, topology, geometry)`` key (``perf_regression``) plus trend
  alerts (queue-wait growth, cache-hit-rate collapse, heartbeat gaps),
  journaled with a latch so each condition trips exactly once.

Everything here is OBSERVATION-ONLY orchestration state: no
``HeatConfig`` field, no cache-key input, no ``_build_runner`` memo-key
input — enabling or disabling the recorder can never perturb a grid
(the tune-DB/HL101 partition, pinned by
``test_obs_observation_only_bitwise``).
"""

from parallel_heat_tpu.obs.series import (  # noqa: F401 — package API
    OBS_SCHEMA_VERSION, Recorder, harvest, load_state, new_state,
    obs_dir_for, reduce_obs, summarize_window)
from parallel_heat_tpu.obs.expo import (  # noqa: F401 — package API
    render_openmetrics, write_textfile)
from parallel_heat_tpu.obs.alerts import (  # noqa: F401 — package API
    AlertEngine, AlertPolicy, reduce_alerts)
