"""Exposition: live series -> OpenMetrics text, two transports.

- :func:`render_openmetrics` — pure render of a recorder state into
  the OpenMetrics text exposition format (``# TYPE``/``# HELP`` per
  family, ``heat_``-prefixed sample lines, ``# EOF`` terminator), the
  grammar ``test_obs_openmetrics_grammar`` validates line by line;
- :func:`write_textfile` — rename-committed textfile export for the
  node-exporter textfile-collector pattern (a scraper never reads a
  torn file);
- :class:`ExpoServer` — a stdlib ``http.server`` endpoint serving
  ``GET /metrics`` so a standard Prometheus scrape config watches a
  fleet with zero custom tooling. Read-only by construction: the
  handler renders whatever state the recorder last folded.
"""

from __future__ import annotations

import os
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from parallel_heat_tpu.utils.checkpoint import _fsync_replace

CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")

# Family name prefix: every series this plane exposes is greppable as
# heat_* (the obs-smoke gate curls for it).
METRIC_PREFIX = "heat_"

_NAME_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_]")

_HELP = {
    "jobs_accepted": "jobs admitted into the durable queue",
    "jobs_rejected": "submissions refused by the admission gate",
    "dispatches": "job dispatches to workers (includes re-dispatch)",
    "completed": "jobs reaching the completed terminal state",
    "quarantined": "jobs quarantined as poison",
    "cancelled": "jobs cancelled",
    "deadline_expired": "jobs interrupted at their deadline",
    "requeues": "failed/preempted jobs re-admitted under backoff",
    "orphaned": "jobs orphaned by dead workers",
    "worker_failures": "worker attempts that failed",
    "hosts_lost": "stale fleet hosts detected at lease takeover",
    "jobs_adopted": "in-flight jobs adopted across hosts",
    "lease_claims": "partition lease claims",
    "lease_takeovers": "partition leases taken over from stale hosts",
    "cache_hits": "completions served from the result cache",
    "chunks": "solver chunks reported by telemetry",
    "steps_per_s": "solver throughput (steps per second)",
    "mcells_steps_per_s": "solver throughput (Mcell-steps per second)",
    "gap_s": "device idle seconds charged to a chunk",
    "queue_wait_s": "acceptance to first dispatch wait (seconds)",
    "daemon_hb_age_s": "age of the partition daemon's heartbeat",
    "host_record_age_s": "age of a fleet host's capacity record",
    "leases_held": "partition leases currently held by a host",
    "queued": "queued jobs per the daemon status heartbeat",
    "running": "running workers per the daemon status heartbeat",
}


def _metric_name(counter: str) -> str:
    return METRIC_PREFIX + _NAME_SANITIZE_RE.sub("_", str(counter))


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\"", "\\\"")
            .replace("\n", "\\n"))


def _labels(ser: dict) -> str:
    pairs = [(k, ser.get(k)) for k in ("host", "part")]
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in pairs if v)
    return "{" + inner + "}" if inner else ""


def _fmt(value: float) -> str:
    # OpenMetrics numbers: plain decimal; integral values render
    # without a trailing .0 so counter lines stay grep-friendly.
    f = float(value)
    return str(int(f)) if f == int(f) else repr(f)


def render_openmetrics(state: dict) -> str:
    """Render one recorder state as OpenMetrics text. Families are
    emitted sorted and contiguously (TYPE/HELP before their samples,
    never interleaved), counters get the ``_total`` sample suffix, and
    the document ends with the mandatory ``# EOF``."""
    families: dict = {}
    for key in sorted(state.get("series", {})):
        ser = state["series"][key]
        raw = ser.get("raw") or []
        if not raw:
            continue
        name = _metric_name(ser["counter"])
        kind = "counter" if ser.get("kind") == "counter" else "gauge"
        fam = families.setdefault(name, {"kind": kind,
                                         "counter": ser["counter"],
                                         "samples": []})
        if fam["kind"] != kind:
            continue  # same counter name with two kinds: first wins
        fam["samples"].append((_labels(ser), raw[-1][1], raw[-1][0]))
    lines = []
    for name in sorted(families):
        fam = families[name]
        lines.append(f"# TYPE {name} {fam['kind']}")
        help_text = _HELP.get(fam["counter"])
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        suffix = "_total" if fam["kind"] == "counter" else ""
        for labels, value, _t in fam["samples"]:
            lines.append(f"{name}{suffix}{labels} {_fmt(value)}")
    lines.append("# TYPE heat_obs_samples counter")
    lines.append("# HELP heat_obs_samples samples folded into the "
                 "recorder's series state")
    lines.append(f"heat_obs_samples_total "
                 f"{_fmt(state.get('n_samples', 0))}")
    lines.append("# TYPE heat_obs_harvests counter")
    lines.append("# HELP heat_obs_harvests recorder harvest passes "
                 "journaled")
    lines.append(f"heat_obs_harvests_total "
                 f"{_fmt(state.get('n_harvests', 0))}")
    last_t = state.get("last_t")
    if isinstance(last_t, (int, float)):
        lines.append("# TYPE heat_obs_last_harvest_timestamp_seconds "
                     "gauge")
        lines.append(f"heat_obs_last_harvest_timestamp_seconds "
                     f"{_fmt(last_t)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def write_textfile(path: str, text: str) -> str:
    """Rename-committed exposition export (the checkpoint discipline
    on a text file): a concurrent scraper reads the previous complete
    document or the new one, never a torn mix."""
    path = str(path)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, f".tmp-{os.getpid()}-"
                          f"{os.path.basename(path)}")
    with open(tmp, "w") as f:
        f.write(text)
    _fsync_replace(tmp, path)
    return path


class _Handler(BaseHTTPRequestHandler):
    server_version = "heatd-obs/1"

    def do_GET(self):  # noqa: N802 — http.server API
        if self.path.split("?", 1)[0] not in ("/", "/metrics"):
            self.send_error(404)
            return
        try:
            body = self.server.render().encode("utf-8")  # type: ignore[attr-defined]
        except Exception as e:  # noqa: BLE001 — a scrape must not kill the server
            self.send_error(500, explain=repr(e))
            return
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # noqa: D102 — quiet by design
        pass


class ExpoServer:
    """One scrape endpoint over a render callback. ``port=0`` binds an
    ephemeral port (read it back from :attr:`port` — the CLI publishes
    it in ``obs/expo.json`` so smoke scripts and scrapers can find
    it)."""

    def __init__(self, render: Callable[[], str],
                 bind: str = "127.0.0.1", port: int = 0):
        self._httpd = ThreadingHTTPServer((bind, int(port)), _Handler)
        self._httpd.render = render  # type: ignore[attr-defined]
        self._httpd.daemon_threads = True
        self.bind = bind
        self.port = int(self._httpd.server_address[1])
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ExpoServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="heatd-obs-expo", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
