"""Alerting: the series joined against tuned baselines and trends.

The headline alert converts PR 16's measurements from a schedule
picker into a fleet-wide performance baseline: for every dispatched
job, :func:`tune_expectation` derives the throughput the tuning DB
MEASURED the hardware can do for the job's own ``(site, topology,
geometry)`` tune key (winner's ``min_wall_s`` under the recorded
protocol), and a run whose observed ``steps_per_s`` series sustains
below ``perf_fraction`` of it trips a journaled ``perf_regression``.
Trend alerts watch the series alone: queue-wait growth, cache-hit-rate
collapse, heartbeat gaps.

Alerts are a journal like everything else: ``alert_tripped`` /
``alert_cleared`` lines in ``obs/alerts.jsonl`` (fsynced appends, torn
tails skipped), folded by the pure :func:`reduce_alerts`. The fold is
the LATCH — a condition that stays true trips exactly once until its
clear line lands, which is what lets the smoke gate assert "exactly
one journaled perf_regression" across any number of evaluation passes.

Observation-only: evaluating alerts reads journals, series state and
the tuning DB; it never touches a config, a cache key, or a runner.
"""

from __future__ import annotations

import math
import os
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from parallel_heat_tpu.service.store import (
    Journal, read_journal_file, reduce_journal)

ALERT_SCHEMA_VERSION = 1


@dataclass(frozen=True)
class AlertPolicy:
    """Thresholds for every alert kind (CLI-overridable; the defaults
    are deliberately conservative — a trend alert that cries wolf
    trains operators to ignore the one that matters)."""

    # perf_regression: sustained mean of the run's steps_per_s window
    # below this fraction of the tuned expectation, with at least this
    # many chunk samples observed.
    perf_fraction: float = 0.5
    perf_min_samples: int = 3
    # queue_wait_growth: recent-half mean exceeds growth_factor x the
    # older-half mean AND an absolute floor (tiny waits growing 10x
    # are still tiny).
    wait_growth_factor: float = 3.0
    wait_min_s: float = 5.0
    wait_min_samples: int = 6
    # cache_hit_collapse: windowed hit rate below this fraction of the
    # all-time rate, with enough windowed completions to mean it.
    cache_collapse_fraction: float = 0.5
    cache_window_s: float = 300.0
    cache_min_completed: int = 8
    # heartbeat_gap: newest sampled heartbeat age past this.
    hb_max_age_s: float = 30.0
    # efficiency_regression: a job's windowed mean roofline_frac
    # (prof's profile events via the series harvest) below this
    # fraction of the partition's own PRE-WINDOW baseline. Relative by
    # design — on CPU the peaks are the v5e row's, so every fraction
    # is honestly tiny and an absolute floor would trip on every CPU
    # run; only a collapse against the same site's history means
    # anything. Needs no TuneDB (complements perf_regression).
    eff_collapse_fraction: float = 0.5
    eff_min_samples: int = 3
    eff_min_baseline: int = 3


def reduce_alerts(events, state=None
                  ) -> Tuple[Dict[str, dict], List[str]]:
    """Pure fold of alert-journal events -> ``(active, anomalies)``.

    ``alert_tripped`` latches a key active, ``alert_cleared`` releases
    it; a duplicate trip or a clear of an unlatched key is an anomaly
    (the alert plane's double-terminal analogue). Same incremental
    fold law as every reducer in the repo."""
    active: Dict[str, dict] = state[0] if state else {}
    anomalies: List[str] = state[1] if state else []
    for e in events:
        ev = e.get("event")
        key = e.get("key")
        if not isinstance(key, str):
            continue
        if ev == "alert_tripped":
            if key in active:
                anomalies.append(f"alerts: duplicate trip of {key}")
                continue
            active[key] = {k: e.get(k) for k in
                           ("key", "kind", "host", "part", "job_id",
                            "t_wall", "detail")}
        elif ev == "alert_cleared":
            if active.pop(key, None) is None:
                anomalies.append(f"alerts: clear of unlatched {key}")
    return active, anomalies


# ---------------------------------------------------------------------------
# Tuned-baseline expectation lookup
# ---------------------------------------------------------------------------

def tune_expectation(config: dict, db_root: str,
                     topology: Optional[dict] = None
                     ) -> Optional[float]:
    """Expected ``steps_per_s`` for one job config from the tuning
    DB's measured winner, or ``None`` when the DB has no sound entry
    for the job's tune key (no alert without measured evidence —
    mirrors ``TuneDB.lookup``'s refusal to act on rejected entries).

    The join reuses the DB's own key discipline: ``tune_key(site,
    topology, geometry)`` over the ``single_2d`` geometry built from
    the job's committed config. ``topology`` defaults to
    ``tune.current_topology()`` (needs jax); tests inject it."""
    from parallel_heat_tpu import tune
    from parallel_heat_tpu.tune.db import load_tune_db, tune_key

    if not isinstance(config, dict) or config.get("nz"):
        return None  # only the 2D single-grid site carries a baseline
    try:
        nx, ny = int(config.get("nx") or 0), int(config.get("ny") or 0)
    except (TypeError, ValueError):
        return None
    if nx <= 0 or ny <= 0:
        return None
    geometry = {"shape": [nx, ny],
                "dtype": str(config.get("dtype") or "float32"),
                "accumulate": str(config.get("accumulate")
                                  or "storage")}
    if topology is None:
        try:
            topology = tune.current_topology()
        except Exception:  # noqa: BLE001 — no devices = no baseline
            return None
    try:
        key, _canon = tune_key("single_2d", topology, geometry)
    except ValueError:
        return None
    entries, _anom, _bad, _torn = load_tune_db(db_root)
    e = entries.get(key)
    if e is None or not e.get("verified"):
        return None
    record = _read_record(db_root, key)
    if record is None or record.get("choice") != e.get("choice"):
        return None
    wall = None
    for c in record.get("candidates") or []:
        if (isinstance(c, dict) and c.get("choice") == e.get("choice")
                and isinstance(c.get("min_wall_s"), (int, float))):
            wall = float(c["min_wall_s"])
    protocol = record.get("protocol") or {}
    steps = protocol.get("steps_per_call")
    if (wall is None or wall <= 0.0
            or not isinstance(steps, (int, float)) or steps <= 0):
        return None
    return float(steps) / wall


def _read_record(db_root: str, key: str) -> Optional[dict]:
    import json

    try:
        with open(os.path.join(str(db_root), "records",
                               f"{key}.json")) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# The engine: journal writer + condition evaluation
# ---------------------------------------------------------------------------

class AlertEngine:
    """The write handle of one alert journal + the evaluators.

    :meth:`evaluate` computes every condition from the series state
    (plus the job journals and tuning DB for ``perf_regression``),
    trips latched keys that became true and clears keys that became
    false; it returns the NEWLY tripped alerts so a caller can react
    (the CLI prints them, the smoke gate counts them)."""

    def __init__(self, obs_dir: str,
                 policy: Optional[AlertPolicy] = None):
        self.obs_dir = str(obs_dir)
        self.policy = policy or AlertPolicy()
        self.path = os.path.join(self.obs_dir, "alerts.jsonl")
        self._journal: Optional[Journal] = None

    @property
    def journal(self) -> Journal:
        if self._journal is None:
            os.makedirs(self.obs_dir, exist_ok=True)
            self._journal = Journal(self.path)
        return self._journal

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    def __enter__(self) -> "AlertEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def active(self) -> Dict[str, dict]:
        events, _bad, _torn = read_journal_file(self.path)
        active, _anom = reduce_alerts(events)
        return active

    # -- evaluation ------------------------------------------------------

    def evaluate(self, state: dict, *, root: Optional[str] = None,
                 tune_db: Optional[str] = None,
                 topology: Optional[dict] = None,
                 now: Optional[float] = None) -> List[dict]:
        now = time.time() if now is None else float(now)
        conditions: Dict[str, dict] = {}
        self._trend_conditions(state, conditions)
        if root and tune_db:
            self._perf_conditions(state, root, tune_db, topology,
                                  conditions)
        if root:
            self._eff_conditions(state, root, conditions)
        active = self.active()
        tripped = []
        for key, alert in sorted(conditions.items()):
            if key in active:
                continue
            rec = self.journal.append("alert_tripped", key=key,
                                      **alert)
            tripped.append(rec)
        for key in sorted(active):
            kind = key.split("|", 1)[0]
            # perf_regression / efficiency_regression latch per JOB: a
            # finished run cannot "recover", and re-clearing would
            # re-arm the latch the smoke gates count on. Trend alerts
            # clear on recovery.
            if kind in ("perf_regression", "efficiency_regression"):
                continue
            if key not in conditions:
                self.journal.append("alert_cleared", key=key)
        return tripped

    def _trend_conditions(self, state: dict,
                          conditions: Dict[str, dict]) -> None:
        p = self.policy
        series = state.get("series", {})
        by_part: Dict[Tuple[str, str], Dict[str, dict]] = {}
        for ser in series.values():
            by_part.setdefault((ser["host"], ser["part"]),
                               {})[ser["counter"]] = ser
        for (host, part), group in sorted(by_part.items()):
            wait = group.get("queue_wait_s")
            if wait:
                vals = [v for _t, v in wait["raw"]]
                if len(vals) >= p.wait_min_samples:
                    half = len(vals) // 2
                    older = sum(vals[:half]) / half
                    recent = sum(vals[half:]) / (len(vals) - half)
                    if (recent >= p.wait_min_s
                            and recent > p.wait_growth_factor
                            * max(older, 1e-9)):
                        key = f"queue_wait_growth|{host}|{part}"
                        conditions[key] = {
                            "kind": "queue_wait_growth", "host": host,
                            "part": part,
                            "detail": {"older_mean_s": older,
                                       "recent_mean_s": recent}}
            completed = group.get("completed")
            hits = group.get("cache_hits")
            if completed and hits and completed["raw"]:
                total_c = completed["raw"][-1][1]
                total_h = hits["raw"][-1][1]
                t_cut = completed["raw"][-1][0] - p.cache_window_s
                win_c = total_c - _counter_at(completed["raw"], t_cut)
                win_h = total_h - _counter_at(hits["raw"], t_cut)
                if (total_c > 0 and win_c >= p.cache_min_completed):
                    overall = total_h / total_c
                    recent = win_h / win_c
                    if (overall > 0
                            and recent < p.cache_collapse_fraction
                            * overall):
                        key = f"cache_hit_collapse|{host}|{part}"
                        conditions[key] = {
                            "kind": "cache_hit_collapse",
                            "host": host, "part": part,
                            "detail": {"overall_rate": overall,
                                       "recent_rate": recent}}
            for age_counter in ("daemon_hb_age_s",
                                "host_record_age_s"):
                ser = group.get(age_counter)
                if ser and ser["raw"]:
                    age = ser["raw"][-1][1]
                    if age > p.hb_max_age_s:
                        key = f"heartbeat_gap|{host}|{part}"
                        conditions[key] = {
                            "kind": "heartbeat_gap", "host": host,
                            "part": part,
                            "detail": {"source": age_counter,
                                       "age_s": age,
                                       "max_age_s": p.hb_max_age_s}}

    def _perf_conditions(self, state: dict, root: str, tune_db: str,
                         topology: Optional[dict],
                         conditions: Dict[str, dict]) -> None:
        """One condition per dispatched job whose observed throughput
        window sustains below the tuned baseline. The join: the job's
        partition names the ``steps_per_s`` series; the job's
        dispatch/terminal times bound the window; the job's committed
        config names the tune key."""
        p = self.policy
        expectations: Dict[str, Optional[float]] = {}
        for part, proot in _partitions(root):
            events, _bad, _torn = read_journal_file(
                os.path.join(proot, "journal.jsonl"))
            jobs, _anom = reduce_journal(events)
            for jid in sorted(jobs):
                v = jobs[jid]
                if v.first_dispatch_t is None:
                    continue
                if v.cached is not None:
                    continue  # cache-served: no solve to regress
                spec = _read_json(os.path.join(proot, "jobs",
                                               f"{jid}.json"))
                if spec is None:
                    continue
                cfg = spec.get("config")
                cfg_key = _stable(cfg)
                if cfg_key not in expectations:
                    expectations[cfg_key] = tune_expectation(
                        cfg, tune_db, topology=topology)
                expected = expectations[cfg_key]
                if expected is None:
                    continue
                t0 = v.first_dispatch_t
                t1 = v.terminal_t if v.terminal_t is not None \
                    else math.inf
                obs = []
                for ser in state.get("series", {}).values():
                    if (ser["part"] == part
                            and ser["counter"] == "steps_per_s"):
                        obs.extend(val for t, val in ser["raw"]
                                   if t0 <= t <= t1)
                if len(obs) < p.perf_min_samples:
                    continue
                sustained = sum(obs) / len(obs)
                if sustained < p.perf_fraction * expected:
                    key = f"perf_regression|{part}|{jid}"
                    conditions[key] = {
                        "kind": "perf_regression", "host": "",
                        "part": part, "job_id": jid,
                        "detail": {
                            "observed_steps_per_s": sustained,
                            "expected_steps_per_s": expected,
                            "fraction": p.perf_fraction,
                            "n_samples": len(obs)}}


    def _eff_conditions(self, state: dict, root: str,
                        conditions: Dict[str, dict]) -> None:
        """One condition per dispatched job whose windowed mean
        roofline fraction collapses against the partition's own
        pre-window history. The join mirrors ``_perf_conditions``
        (partition names the series, dispatch/terminal times bound
        the window) but the baseline is the series itself — the
        samples BEFORE the job's window — so no tuning DB and no
        absolute-peak assumption is needed (the roofline fraction is
        only meaningful relative to the same site's history; see
        ``AlertPolicy``'s field comment)."""
        p = self.policy
        for part, proot in _partitions(root):
            events, _bad, _torn = read_journal_file(
                os.path.join(proot, "journal.jsonl"))
            jobs, _anom = reduce_journal(events)
            samples: List[Tuple[float, float]] = []
            for ser in state.get("series", {}).values():
                if (ser["part"] == part
                        and ser["counter"] == "roofline_frac"):
                    samples.extend(ser["raw"])
            if not samples:
                continue
            samples.sort()
            for jid in sorted(jobs):
                v = jobs[jid]
                if v.first_dispatch_t is None:
                    continue
                if v.cached is not None:
                    continue  # cache-served: no solve to regress
                t0 = v.first_dispatch_t
                t1 = v.terminal_t if v.terminal_t is not None \
                    else math.inf
                base = [val for t, val in samples if t < t0]
                obs = [val for t, val in samples if t0 <= t <= t1]
                if (len(obs) < p.eff_min_samples
                        or len(base) < p.eff_min_baseline):
                    continue
                baseline = sum(base) / len(base)
                sustained = sum(obs) / len(obs)
                if (baseline > 0
                        and sustained < p.eff_collapse_fraction
                        * baseline):
                    key = f"efficiency_regression|{part}|{jid}"
                    conditions[key] = {
                        "kind": "efficiency_regression", "host": "",
                        "part": part, "job_id": jid,
                        "detail": {
                            "observed_roofline_frac": sustained,
                            "baseline_roofline_frac": baseline,
                            "fraction": p.eff_collapse_fraction,
                            "n_samples": len(obs),
                            "n_baseline": len(base)}}


def _counter_at(raw, t: float) -> float:
    v = 0.0
    for ts, val in raw:
        if ts > t:
            break
        v = val
    return v


def _partitions(root: str) -> List[Tuple[str, str]]:
    root = str(root)
    if os.path.isfile(os.path.join(root, "fleet.json")):
        parts_dir = os.path.join(root, "parts")
        try:
            names = sorted(n for n in os.listdir(parts_dir)
                           if not n.startswith(".") and
                           os.path.isdir(os.path.join(parts_dir, n)))
        except OSError:
            return []
        return [(n, os.path.join(parts_dir, n)) for n in names]
    return [("", root)]


def _read_json(path: str) -> Optional[dict]:
    import json

    try:
        with open(path) as f:
            doc = json.load(f)
        return doc if isinstance(doc, dict) else None
    except (OSError, ValueError):
        return None


def _stable(doc) -> str:
    import json

    try:
        return json.dumps(doc, sort_keys=True)
    except (TypeError, ValueError):
        return repr(doc)
