"""ctypes binding for the native runtime library, with auto-build.

The library is built on first use (one ``g++ -O3 -shared`` invocation via
the sibling Makefile) and cached in ``native/build/``. Every entry point
degrades gracefully: callers check :func:`available` and fall back to the
Python implementations, so the package works on machines without a
toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "build", "libheat_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # Always invoke make: a no-op when build/ is current, and the
        # only way a stale .so from an older ABI gets rebuilt (the
        # Makefile depends on heat_native.cpp).
        if not _build() and not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.heat_write_dat.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_char_p,
            ]
            lib.heat_write_dat.restype = ctypes.c_int
            lib.heat_write_dat_mt.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_char_p,
                ctypes.c_int,
            ]
            lib.heat_write_dat_mt.restype = ctypes.c_int
            lib.heat_read_dat.argtypes = [
                ctypes.c_char_p,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.heat_read_dat.restype = ctypes.c_int
            lib.heat_free.argtypes = [ctypes.POINTER(ctypes.c_float)]
            lib.heat_init_grid.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.c_int64,
            ]
            lib.heat_native_abi_version.restype = ctypes.c_int
            if lib.heat_native_abi_version() != 2:
                return None
            _lib = lib
        except (OSError, AttributeError):
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def write_dat(path: str, u: np.ndarray, threads: int | None = None) -> None:
    """Write in prtdat format; formatting parallelized for large grids."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    u = np.ascontiguousarray(u, dtype=np.float32)
    nx, ny = u.shape
    if threads is None:
        # Threaded formatting pays off once the file is tens of MB.
        threads = min(os.cpu_count() or 1, 8) if u.size >= 4_000_000 else 1
    rc = lib.heat_write_dat_mt(
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        nx, ny, str(path).encode(), int(threads),
    )
    if rc != 0:
        raise OSError(f"heat_write_dat failed with code {rc} for {path!r}")


def read_dat(path: str) -> np.ndarray:
    """Parse a prtdat file into the ``(nx, ny)`` array convention."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    out = ctypes.POINTER(ctypes.c_float)()
    nx = ctypes.c_int64()
    ny = ctypes.c_int64()
    rc = lib.heat_read_dat(str(path).encode(), ctypes.byref(out),
                           ctypes.byref(nx), ctypes.byref(ny))
    if rc != 0:
        raise OSError(f"heat_read_dat failed with code {rc} for {path!r}")
    try:
        arr = np.ctypeslib.as_array(out, shape=(nx.value, ny.value)).copy()
    finally:
        lib.heat_free(out)
    return arr


def init_grid(nx: int, ny: int) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    u = np.empty((nx, ny), dtype=np.float32)
    lib.heat_init_grid(
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), nx, ny
    )
    return u
