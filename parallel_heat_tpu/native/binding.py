"""ctypes binding for the native runtime library, with auto-build.

The library is built on first use (one ``g++ -O3 -shared`` invocation via
the sibling Makefile) and cached in ``native/build/``. Every entry point
degrades gracefully: callers check :func:`available` and fall back to the
Python implementations, so the package works on machines without a
toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "build", "libheat_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.heat_write_dat.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_char_p,
            ]
            lib.heat_write_dat.restype = ctypes.c_int
            lib.heat_init_grid.argtypes = [
                ctypes.POINTER(ctypes.c_float),
                ctypes.c_int64,
                ctypes.c_int64,
            ]
            lib.heat_native_abi_version.restype = ctypes.c_int
            if lib.heat_native_abi_version() != 1:
                return None
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def write_dat(path: str, u: np.ndarray) -> None:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    u = np.ascontiguousarray(u, dtype=np.float32)
    nx, ny = u.shape
    rc = lib.heat_write_dat(
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        nx, ny, str(path).encode(),
    )
    if rc != 0:
        raise OSError(f"heat_write_dat failed with code {rc} for {path!r}")


def init_grid(nx: int, ny: int) -> np.ndarray:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    u = np.empty((nx, ny), dtype=np.float32)
    lib.heat_init_grid(
        u.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), nx, ny
    )
    return u
