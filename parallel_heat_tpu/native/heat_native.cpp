// Native runtime pieces: fast .dat serialization and grid init.
//
// The reference's runtime is C throughout; its I/O layer is prtdat/inidat
// (mpi/mpi_heat_improved_persistent_stat.c:315-341, cuda/cuda_heat.cu:274-300).
// The TPU build keeps compute in XLA, but host-side I/O at benchmark sizes
// (e.g. a 32768^2 grid is a ~8.6 GB text file) is far too slow through
// Python string formatting, so the writer is native: identical byte output
// to C fprintf("%6.1f") — which both use snprintf semantics — with a
// buffered column-major walk.
//
// Exposed via a plain C ABI for ctypes (no pybind11 dependency).

#include <cstdio>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Write u[nx][ny] (row-major, C order) in prtdat format:
// for iy = ny-1..0: print u[0][iy] .. u[nx-1][iy], single-space
// separated, newline-terminated. Returns 0 on success, errno-style
// negative on failure.
int heat_write_dat(const float* u, int64_t nx, int64_t ny,
                   const char* path) {
    FILE* fp = std::fopen(path, "w");
    if (!fp) return -1;
    // Buffered line assembly: worst-case %6.1f of float32 is ~48 chars
    // (large magnitudes print in full), plus separator.
    std::vector<char> line;
    line.reserve(static_cast<size_t>(nx) * 16 + 64);
    char tok[64];
    int rc = 0;
    for (int64_t iy = ny - 1; iy >= 0; --iy) {
        line.clear();
        for (int64_t ix = 0; ix < nx; ++ix) {
            int n = std::snprintf(tok, sizeof tok, "%6.1f",
                                  static_cast<double>(u[ix * ny + iy]));
            if (n < 0) { rc = -2; goto done; }
            line.insert(line.end(), tok, tok + n);
            line.push_back(ix == nx - 1 ? '\n' : ' ');
        }
        if (std::fwrite(line.data(), 1, line.size(), fp) != line.size()) {
            rc = -3;
            goto done;
        }
    }
done:
    if (std::fclose(fp) != 0 && rc == 0) rc = -4;
    return rc;
}

// inidat: u[ix][iy] = ix*(nx-ix-1)*iy*(ny-iy-1), evaluated in double then
// cast (NOT the reference's int arithmetic, which overflows for nx>~215).
void heat_init_grid(float* u, int64_t nx, int64_t ny) {
    for (int64_t ix = 0; ix < nx; ++ix) {
        double fx = static_cast<double>(ix) * static_cast<double>(nx - ix - 1);
        for (int64_t iy = 0; iy < ny; ++iy) {
            double fy =
                static_cast<double>(iy) * static_cast<double>(ny - iy - 1);
            u[ix * ny + iy] = static_cast<float>(fx * fy);
        }
    }
}

int heat_native_abi_version() { return 1; }

}  // extern "C"
