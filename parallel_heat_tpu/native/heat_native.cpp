// Native runtime pieces: fast .dat serialization/parsing and grid init.
//
// The reference's runtime is C throughout; its I/O layer is prtdat/inidat
// (mpi/mpi_heat_improved_persistent_stat.c:315-341, cuda/cuda_heat.cu:274-300).
// The TPU build keeps compute in XLA, but host-side I/O at benchmark sizes
// (e.g. a 32768^2 grid is a ~8.6 GB text file) is far too slow through
// Python string formatting, so the writer/reader are native: identical byte
// output to C fprintf("%6.1f") — which both use snprintf semantics — with a
// buffered column-major walk, optionally formatted by a thread pool.
//
// Exposed via a plain C ABI for ctypes (no pybind11 dependency).

#include <cstdio>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// Format lines iy = [iy_hi .. iy_lo] (descending) into `out`.
// Each output line is one iy column: u[0][iy] .. u[nx-1][iy].
int format_lines(const float* u, int64_t nx, int64_t ny,
                 int64_t iy_hi, int64_t iy_lo, std::string& out) {
    char tok[64];
    out.clear();
    out.reserve(static_cast<size_t>(iy_hi - iy_lo + 1) * (nx * 8 + 1));
    for (int64_t iy = iy_hi; iy >= iy_lo; --iy) {
        for (int64_t ix = 0; ix < nx; ++ix) {
            int n = std::snprintf(tok, sizeof tok, "%6.1f",
                                  static_cast<double>(u[ix * ny + iy]));
            if (n < 0) return -2;
            out.append(tok, static_cast<size_t>(n));
            out.push_back(ix == nx - 1 ? '\n' : ' ');
        }
    }
    return 0;
}

}  // namespace

extern "C" {

// Write u[nx][ny] (row-major, C order) in prtdat format with a formatting
// thread pool: batches of `threads` chunks are formatted concurrently and
// written in order, so memory stays O(threads * chunk) rather than O(file).
// threads <= 1 degrades to the single-threaded walk. Returns 0 on success,
// negative on failure.
int heat_write_dat_mt(const float* u, int64_t nx, int64_t ny,
                      const char* path, int threads) {
    FILE* fp = std::fopen(path, "w");
    if (!fp) return -1;
    if (threads < 1) threads = 1;
    // ~8 MB of text per chunk keeps the pipeline balanced.
    int64_t chunk_lines = (8 << 20) / (nx * 8 + 2);
    if (chunk_lines < 1) chunk_lines = 1;
    if (chunk_lines > ny) chunk_lines = ny;

    std::vector<std::string> bufs(static_cast<size_t>(threads));
    std::vector<int> rcs(static_cast<size_t>(threads), 0);
    int rc = 0;
    for (int64_t top = ny - 1; top >= 0 && rc == 0;) {
        int live = 0;
        std::vector<std::thread> pool;
        for (int t = 0; t < threads && top >= 0; ++t, ++live) {
            int64_t hi = top;
            int64_t lo = hi - chunk_lines + 1;
            if (lo < 0) lo = 0;
            top = lo - 1;
            pool.emplace_back([&, t, hi, lo] {
                rcs[static_cast<size_t>(t)] =
                    format_lines(u, nx, ny, hi, lo,
                                 bufs[static_cast<size_t>(t)]);
            });
        }
        for (auto& th : pool) th.join();
        for (int t = 0; t < live && rc == 0; ++t) {
            const std::string& b = bufs[static_cast<size_t>(t)];
            if (rcs[static_cast<size_t>(t)] != 0) {
                rc = rcs[static_cast<size_t>(t)];
            } else if (std::fwrite(b.data(), 1, b.size(), fp) != b.size()) {
                rc = -3;
            }
        }
    }
    if (std::fclose(fp) != 0 && rc == 0) rc = -4;
    return rc;
}

// Single-threaded variant (kept for ABI stability and as the oracle).
int heat_write_dat(const float* u, int64_t nx, int64_t ny,
                   const char* path) {
    return heat_write_dat_mt(u, nx, ny, path, 1);
}

}  // extern "C"

namespace {

constexpr size_t kReadChunk = 8 << 20;  // streaming parse buffer

inline bool is_sep(char c) {
    // Must agree with strtof's skippable whitespace (minus '\n', the
    // line terminator) and the Python parser's str.split().
    return c == ' ' || c == '\t' || c == '\r' || c == '\v' || c == '\f';
}

// Stream the file line by line in O(kReadChunk) memory, invoking
// cb(line_start, line_end, line_index) for each non-empty line. Lines
// longer than the chunk are handled by a carry that grows as needed.
template <typename Fn>
int for_each_line(FILE* fp, Fn&& cb) {
    std::vector<char> buf(kReadChunk);
    std::string carry;
    int64_t line = 0;
    for (;;) {
        size_t got = std::fread(buf.data(), 1, buf.size(), fp);
        if (got == 0) {
            if (std::ferror(fp)) return -1;
            break;
        }
        const char* p = buf.data();
        const char* end = p + got;
        while (p < end) {
            const char* nl = static_cast<const char*>(
                std::memchr(p, '\n', static_cast<size_t>(end - p)));
            if (!nl) {
                carry.append(p, static_cast<size_t>(end - p));
                break;
            }
            const char* ls;
            const char* le;
            if (carry.empty()) {
                ls = p;
                le = nl;
            } else {
                carry.append(p, static_cast<size_t>(nl - p));
                ls = carry.data();
                le = ls + carry.size();
            }
            bool blank = true;
            for (const char* q = ls; q < le; ++q) {
                if (!is_sep(*q)) { blank = false; break; }
            }
            if (!blank) {
                int rc = cb(ls, le, line++);
                if (rc != 0) return rc;
            }
            carry.clear();
            p = nl + 1;
        }
    }
    if (!carry.empty()) {
        const char* ls = carry.data();
        const char* le = ls + carry.size();
        bool blank = true;
        for (const char* q = ls; q < le; ++q) {
            if (!is_sep(*q)) { blank = false; break; }
        }
        if (!blank) {
            int rc = cb(ls, le, line);
            if (rc != 0) return rc;
        }
    }
    return 0;
}

}  // namespace

extern "C" {

// Parse a .dat file (whitespace-separated float grid, one iy line per
// row, iy descending — the prtdat layout). Two streaming passes in
// O(chunk) memory (mirroring the writer's O(threads*chunk) design): the
// first counts lines and validates every line has the same token count,
// the second fills the malloc'd output. On success returns 0 and sets
// *out (heat_free() it), *nx, *ny. Negative on failure (-7: parse error
// or ragged line).
int heat_read_dat(const char* path, float** out, int64_t* nx, int64_t* ny) {
    *out = nullptr;
    *nx = 0;
    *ny = 0;
    FILE* fp = std::fopen(path, "rb");
    if (!fp) return -1;

    // Pass 1: dimensions + per-line token-count validation.
    int64_t ny_ = 0, nx_ = 0;
    int rc = for_each_line(fp, [&](const char* ls, const char* le,
                                   int64_t) -> int {
        int64_t toks = 0;
        const char* q = ls;
        while (q < le) {
            while (q < le && is_sep(*q)) ++q;
            if (q >= le) break;
            ++toks;
            while (q < le && !is_sep(*q)) ++q;
        }
        if (ny_ == 0) {
            nx_ = toks;
        } else if (toks != nx_) {
            return -7;  // ragged line: refuse rather than mis-place cells
        }
        ++ny_;
        return 0;
    });
    if (rc != 0 || nx_ <= 0 || ny_ <= 0) {
        std::fclose(fp);
        return rc != 0 ? rc : -5;
    }

    float* buf = static_cast<float*>(
        std::malloc(sizeof(float) * static_cast<size_t>(nx_) *
                    static_cast<size_t>(ny_)));
    if (!buf) { std::fclose(fp); return -6; }

    // Pass 2: parse. Line j (top-down) is iy = ny-1-j; token i is ix = i.
    // Output layout u[ix * ny + iy] (row-major (nx, ny), matching the
    // writer's input convention).
    std::rewind(fp);
    std::string tokbuf;
    rc = for_each_line(fp, [&](const char* ls, const char* le,
                               int64_t j) -> int {
        int64_t iy = ny_ - 1 - j;
        // strtof needs NUL-terminated input; copy the line once.
        tokbuf.assign(ls, static_cast<size_t>(le - ls));
        char* p = tokbuf.data();
        char* lend = p + tokbuf.size();
        for (int64_t ix = 0; ix < nx_; ++ix) {
            char* next = nullptr;
            float v = std::strtof(p, &next);
            if (next == p || next > lend) return -7;
            buf[ix * ny_ + iy] = v;
            p = next;
        }
        return 0;
    });
    std::fclose(fp);
    if (rc != 0) { std::free(buf); return rc; }
    *out = buf;
    *nx = nx_;
    *ny = ny_;
    return 0;
}

void heat_free(float* p) { std::free(p); }

// inidat: u[ix][iy] = ix*(nx-ix-1)*iy*(ny-iy-1), evaluated in double then
// cast (NOT the reference's int arithmetic, which overflows for nx>~215).
void heat_init_grid(float* u, int64_t nx, int64_t ny) {
    for (int64_t ix = 0; ix < nx; ++ix) {
        double fx = static_cast<double>(ix) * static_cast<double>(nx - ix - 1);
        for (int64_t iy = 0; iy < ny; ++iy) {
            double fy =
                static_cast<double>(iy) * static_cast<double>(ny - iy - 1);
            u[ix * ny + iy] = static_cast<float>(fx * fy);
        }
    }
}

int heat_native_abi_version() { return 2; }

}  // extern "C"
