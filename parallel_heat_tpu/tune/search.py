"""Measured schedule search — the offline half of the autotuner.

One search run, for one (site, geometry, topology):

1. **Enumerate** the site's choice vocabulary (``SITE_CHOICES``) and
   probe feasibility by pinning each choice through the REAL picker
   with ``tune.force`` — a pin the picker declines is infeasible, and
   (crucially) a feasible pin builds through the same factories
   production uses, so nothing the search times is a schedule
   production could not run. The config-keyed runner memos
   (``solver._build_runner``, the ensemble engine's runner caches) key
   on config ALONE — two candidates share the config — so each
   candidate's program is built with those memos cleared and
   snapshotted into its closure (:func:`_candidate_fn`); without the
   clear every candidate after the first would silently re-time the
   first candidate's compiled schedule.
2. **Bitwise-verify** every feasible candidate against the reference
   schedule — the ANALYTIC picker's choice on the same inputs — with
   ``np.array_equal`` BEFORE any timing (measured-only-after-bitwise-
   verify, SEMANTICS.md "Tuning soundness"). A candidate that is not
   bit-identical (e.g. the jnp fallback against a Pallas reference)
   is recorded with its verdict and can never win.
3. **Time** the verified candidates under the interleaved min-of-N
   protocol (``utils.measure.interleaved_min_of_n`` — the same one
   ``bench.py`` uses), and
4. **Persist** the winner into a :class:`tune.db.TuneDB` with the full
   per-candidate evidence table in the rename-committed record.

Driven offline by ``heat tune`` / ``tools/autotune.py``; never runs
inside a solve.
"""

from __future__ import annotations

import argparse
import json
import sys
import warnings
from typing import Any, Dict, List, Optional

import numpy as np

from parallel_heat_tpu import tune
from parallel_heat_tpu.config import HeatConfig
from parallel_heat_tpu.utils import measure


def _quiet_force(site: str, choice: str):
    """A ``tune.force`` that suppresses the loud fallback warning —
    the search TRIES infeasible pins on purpose; the picker's decline
    is the answer, not an incident."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            with tune.force(site, choice):
                yield

    return cm()


def picked_kind(site: str, config, choice: Optional[str] = None) -> str:
    """The site's resolved kind for ``config`` — under a forced pin
    when ``choice`` is given (feasibility probe: ``picked == choice``
    iff the pin is feasible), analytic otherwise."""
    from parallel_heat_tpu.ops import pallas_stencil as ps

    def _pick() -> str:
        if site == "single_2d":
            kind, _ = ps.pick_single_2d(
                config.shape, config.dtype, float(config.cx),
                float(config.cy), accumulate=config.accumulate)
            return kind
        if site == "block_temporal_2d":
            from parallel_heat_tpu.parallel.mesh import AXIS_NAMES

            kind, _, _ = ps.pick_block_temporal_2d(config,
                                                   AXIS_NAMES[:2])
            return kind
        if site == "ensemble_2d":
            # The driver-level decision site — NOT pick_ensemble_2d
            # directly: ensemble_path gates on scheme/backend/ndim
            # before consulting the picker, so a pin the engine would
            # never see (e.g. kernel M on a jnp backend) probes
            # infeasible here instead of timing two identical paths.
            from parallel_heat_tpu.ensemble import engine

            return engine.ensemble_path(config)
        if site == "halo_overlap":
            from parallel_heat_tpu.parallel.temporal import (
                resolve_halo_overlap)
            from parallel_heat_tpu.solver import _resolve_backend

            return resolve_halo_overlap(config, _resolve_backend(config))
        raise ValueError(f"unknown tune site {site!r}")

    if choice is None:
        return _pick()
    with _quiet_force(site, choice):
        return _pick()


def _candidate_fn(site: str, config, choice: str, steps_per_call: int,
                  members: int = 4):
    """A zero-arg measured callable running ``choice``'s schedule
    through the production factories.

    Each candidate's program is built ONCE, under its own ``tune.force``
    pin, and snapshotted into the closure. The config-keyed runner memos
    (``solver._build_runner``; the ensemble engine's runner caches) are
    cleared BEFORE the build (so the pin cannot silently reuse the
    previous candidate's compiled schedule — the memo keys on config
    alone and every candidate shares the config) and AFTER it (so no
    forced runner leaks into production state). Compiles land in the
    snapshot's first call — the warm pass — never inside the timing
    bracket.

    ``single_2d`` times the multistep function directly (the quantity
    the picker prices); ``ensemble_2d`` times the engine's member-
    batched fixed runner over ``members`` members (the batched path is
    the ONLY consumer of ``pick_ensemble_2d`` — a plain solve never
    reaches it); the driver-level sites (``block_temporal_2d``,
    ``halo_overlap``) time the full compiled simulation program.
    Donating runners get a fresh ``jnp.copy`` of the prepared initial
    per call — identical overhead for every candidate."""
    import jax
    import jax.numpy as jnp

    if site == "single_2d":
        from parallel_heat_tpu.ops import pallas_stencil as ps

        with _quiet_force(site, choice):
            multi, _ = ps.single_grid_multistep(config)
        k = steps_per_call
        run = jax.jit(lambda u: multi(u, k))
        from parallel_heat_tpu.solver import make_initial_grid

        u0 = jnp.asarray(make_initial_grid(config))
        return lambda: run(u0)

    from parallel_heat_tpu import solver

    ocfg = solver._observer_free(config)

    if site == "ensemble_2d":
        from parallel_heat_tpu.ensemble import engine

        engine._build_fixed_runner.cache_clear()
        engine._batched_multistep.cache_clear()
        with _quiet_force(site, choice):
            run = engine._build_fixed_runner(ocfg, members,
                                             steps_per_call)
        engine._build_fixed_runner.cache_clear()
        engine._batched_multistep.cache_clear()
        u0 = solver._prepare_initial(ocfg, None)
        u0b = jax.block_until_ready(
            jnp.stack([u0] * members))
        return lambda: run(jnp.copy(u0b))

    solver._build_runner.cache_clear()
    with _quiet_force(site, choice):
        runner, _ = solver._build_runner(ocfg)
    solver._build_runner.cache_clear()
    u0 = solver._prepare_initial(ocfg, None)

    def fn():
        grid, _steps, _conv, _res = runner(jnp.copy(u0))
        return grid

    return fn


def search_site(config: HeatConfig, site: str = "single_2d", *,
                rounds: int = 3, steps_per_call: int = 16,
                members: int = 4, db=None, clock=None) -> Dict[str, Any]:
    """One measured search; returns the per-geometry report and (when
    ``db`` is given) persists a verified winner.

    The reference schedule is the analytic picker's choice on the same
    inputs; every candidate's output is bitwise-compared against it
    before timing, so the DB can only ever select among schedules
    proven interchangeable on THIS geometry.
    """
    config = config.validate()
    if site in ("block_temporal_2d", "halo_overlap"):
        # The driver-level sites decide on the RESOLVED config — the
        # concrete halo depth solver._resolved substitutes — so the
        # geometry key and the feasibility probes must see exactly
        # what the consult site will at pick time: an auto depth is
        # None here but concrete there, and a key built from the raw
        # config could never be consulted back (and the
        # block_temporal_2d probe would decline every kernel against
        # K=None). halo_overlap stays unresolved: an explicit
        # schedule short-circuits resolve_halo_overlap and would make
        # every pin but its own infeasible.
        from parallel_heat_tpu import solver

        mode = config.halo_overlap
        resolved, _, _ = solver._resolved(config)
        config = resolved.replace(halo_overlap=mode).validate()
    geometry = tune.geometry_for(site, config)
    topology = tune.current_topology()
    analytic = picked_kind(site, config)

    feasible: List[str] = []
    for choice in tune.SITE_CHOICES[site]:
        if picked_kind(site, config, choice) == choice:
            feasible.append(choice)

    fns = {c: _candidate_fn(site, config, c, steps_per_call,
                            members=members)
           for c in feasible}

    # Warm (compile + first dispatch) and capture each candidate's
    # output for the bitwise verify — timing a cold compile is the
    # classic garbage-rate bug.
    outputs = {}
    for c, fn in fns.items():
        outputs[c] = np.asarray(fn())
    reference = outputs[analytic]
    verified = {c: bool(np.array_equal(out, reference))
                for c, out in outputs.items()}

    walls = measure.interleaved_min_of_n(
        {c: fns[c] for c in feasible if verified[c]},
        rounds=rounds, clock=clock)

    candidates = []
    for c in tune.SITE_CHOICES[site]:
        candidates.append({
            "choice": c,
            "feasible": c in feasible,
            "bitwise_verified": verified.get(c, False),
            "min_wall_s": walls.get(c),
        })
    winner = min(walls, key=walls.get) if walls else analytic

    report = {
        "site": site,
        "geometry": geometry,
        "topology": topology,
        "analytic_choice": analytic,
        "winner": winner,
        "agrees_with_analytic": winner == analytic,
        "candidates": candidates,
        "protocol": {
            "timer": "interleaved_min_of_n",
            "rounds": rounds,
            "steps_per_call": (int(config.steps)
                               if site in ("block_temporal_2d",
                                           "halo_overlap")
                               else steps_per_call),
            "reference": f"analytic:{analytic}",
        },
    }
    if site == "ensemble_2d":
        report["protocol"]["members"] = int(members)
    if db is not None and walls:
        entry = db.put(site, topology, geometry, choice=winner,
                       verified=verified[winner],
                       candidates=candidates,
                       protocol=report["protocol"])
        report["db_key"] = entry["key"]
    return report


def _parse_geometry(text: str):
    nx, _, ny = text.partition("x")
    return int(nx), int(ny)


def main(argv=None) -> int:
    """``heat tune`` — drive measured searches and persist winners.

    CPU runs are DRYRUNS of the machinery (feasibility, bitwise
    verify, DB round-trip); their timings rank interpret-mode kernels,
    not hardware. Re-run the same command on the target TPU topology
    to produce shippable entries.
    """
    ap = argparse.ArgumentParser(
        prog="heat tune",
        description="measured schedule search -> tuning DB")
    ap.add_argument("--site", default="single_2d",
                    choices=sorted(tune.SITE_CHOICES))
    ap.add_argument("--geometry", action="append", default=[],
                    metavar="NXxNY",
                    help="grid geometry, repeatable (default 256x256)")
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--accumulate", default="storage",
                    choices=["storage", "f32chunk"])
    ap.add_argument("--backend", default="pallas")
    ap.add_argument("--mesh", default=None, metavar="DXxDY",
                    help="device mesh for the driver-level sites "
                         "(block_temporal_2d, halo_overlap)")
    ap.add_argument("--halo-depth", type=int, default=None)
    ap.add_argument("--steps", type=int, default=64,
                    help="solve steps for driver-level sites")
    ap.add_argument("--steps-per-call", type=int, default=16)
    ap.add_argument("--members", type=int, default=4,
                    help="member batch for the ensemble_2d site")
    ap.add_argument("--rounds", type=int, default=3,
                    help="interleaved min-of-N rounds")
    ap.add_argument("--db", default=None,
                    help="tuning-DB root to persist winners into "
                         "(omit for a report-only dry run)")
    ap.add_argument("--json", default=None,
                    help="write the full report to this path")
    args = ap.parse_args(argv)

    import jax

    geometries = [_parse_geometry(g) for g in args.geometry] or [(256,
                                                                  256)]
    db = tune.TuneDB(args.db) if args.db else None
    platform = jax.devices()[0].platform
    results = []
    try:
        for nx, ny in geometries:
            cfg = HeatConfig(nx=nx, ny=ny, steps=args.steps,
                             dtype=args.dtype,
                             accumulate=args.accumulate,
                             backend=args.backend,
                             mesh_shape=(_parse_geometry(args.mesh)
                                         if args.mesh else None),
                             halo_depth=args.halo_depth)
            rep = search_site(cfg, args.site, rounds=args.rounds,
                              steps_per_call=args.steps_per_call,
                              members=args.members, db=db)
            results.append(rep)
            mark = ("==" if rep["agrees_with_analytic"] else "!=")
            print(f"{nx}x{ny} {args.dtype}/{args.accumulate} "
                  f"[{args.site}]: winner {rep['winner']} "
                  f"{mark} analytic {rep['analytic_choice']}"
                  + (f" -> {rep.get('db_key', '')}" if db else ""))
    finally:
        if db is not None:
            db.close()

    doc = {
        "schema": "tune-search-v1",
        "site": args.site,
        "topology": tune.current_topology(),
        "results": results,
        "platform_note": (
            None if platform in ("tpu", "axon") else
            f"CPU DRYRUN ({platform}): validates feasibility, "
            f"bitwise-verify and DB round-trip; timings rank "
            f"interpret-mode kernels, not hardware."),
        "tpu_rerun_protocol": (
            "Re-run this exact command per target topology (the DB "
            "keys on platform/device_kind/n_devices, so CPU entries "
            "never shadow TPU ones); commit the DB root's index.jsonl "
            "+ records/ as fleet artifacts."),
    }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
