"""The tuning database — measured schedule winners under the cache's
journal discipline (SEMANTICS.md "Tuning soundness").

Layout of one DB root::

    <root>/index.jsonl          append-only fsynced index journal
    <root>/records/<key>.json   rename-committed measurement records

The index is the authority: a pure fold of its events
(:func:`reduce_tune_journal`, same fold law as
``service.cache.reduce_cache_journal``) yields the live entries. Each
entry names a rename-committed record file holding the full measurement
evidence (every candidate's bitwise-verify verdict and measured rate).
Commit ordering mirrors the result cache exactly:

- **put**: record file rename-commits BEFORE the index line — a crash
  between the two loses the ENTRY (the search re-runs), never serves a
  torn record;
- **invalidate**: the index line lands BEFORE the record delete — a
  crash between the two leaves an orphan record file (swept by
  :meth:`TuneDB.sweep_orphans`), never a live entry naming missing
  evidence;
- a SIGKILL mid-append leaves at most one torn tail line, which the
  tolerant replay (``service.store.read_journal_file``) skips.

Keys are content addresses over ``(site, topology, geometry)``
canonical JSON — the same ``_digest`` discipline as the result cache's
semantic keys, so byte-identical decision contexts share entries and
nothing else can collide with them. DB contents are ORCHESTRATION
state: they may only ever select among schedules the repo's parity
contracts already prove bitwise-identical, so no tune key, entry, or
enable/disable toggle may enter a config field, a cache key, or a
runner cache key (rule HL101's partition is the enforcement surface —
there is deliberately no ``HeatConfig`` field for the DB).
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

from parallel_heat_tpu.service.store import Journal, read_journal_file
from parallel_heat_tpu.utils.checkpoint import _fsync_replace

TUNE_SCHEMA_VERSION = 1

# The discrete choice vocabulary per decision site — exactly the kinds
# the analytic pickers can already return, so a DB entry can never
# introduce a schedule outside the proven-bitwise family. Admission is
# re-checked at consult time on top of this (a stale entry whose
# builder now declines falls back loudly; see tune.consult).
SITE_CHOICES: Dict[str, Tuple[str, ...]] = {
    "single_2d": ("A", "E", "E-uni", "I", "I-uni", "B", "C", "jnp"),
    "block_temporal_2d": ("G-uni", "G-fuse", "G-circ", "G", "jnp"),
    "halo_overlap": ("phase", "overlap", "pipeline"),
    "ensemble_2d": ("M", "vmap"),
    # Sharded implicit V-cycle spelling (ops/multigrid_sharded.py):
    # padded per-level shard_map blocks vs the replicated full-grid
    # program. The per-level agglomeration threshold inside the
    # partitioned spelling stays analytic (prof/model lanes) — the
    # site decides the spelling, the plan reports the depth.
    "mg_partition": ("replicated", "partitioned"),
}


def _digest(doc: dict) -> str:
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()[:40]


def tune_key(site: str, topology: dict, geometry: dict
             ) -> Tuple[str, dict]:
    """``(key, canonical_doc)`` for one decision context. The key is a
    content address: byte-identical canonical ``(site, topology,
    geometry)`` <=> equal keys."""
    if site not in SITE_CHOICES:
        raise ValueError(f"unknown tune site {site!r} "
                         f"(have: {sorted(SITE_CHOICES)})")
    canon = {"schema": TUNE_SCHEMA_VERSION, "site": site,
             "topology": dict(topology), "geometry": dict(geometry)}
    return _digest(canon), canon


# ---------------------------------------------------------------------------
# Index journal + pure fold
# ---------------------------------------------------------------------------

def reduce_tune_journal(events, state=None
                        ) -> Tuple[Dict[str, dict], List[str]]:
    """Pure fold of tune-index events -> ``(entries, anomalies)``.

    Entry lifecycle: ``tune_put`` creates/replaces, ``tune_invalidate``
    removes. Same fold law as ``cache.reduce_cache_journal``: pass a
    previous call's state to fold only appended events
    (``reduce(prefix) then reduce(suffix) == reduce(all)``). Unknown
    events/fields are ignored (forward compatibility); an invalidate of
    an unknown key is an anomaly — the index's double-terminal
    analogue."""
    entries: Dict[str, dict] = state[0] if state else {}
    anomalies: List[str] = state[1] if state else []
    for e in events:
        ev = e.get("event")
        key = e.get("key")
        if ev is None or not isinstance(key, str):
            continue
        if ev == "tune_put":
            entries[key] = {
                "key": key,
                "schema": e.get("db_schema"),
                "site": e.get("site"),
                "topology": e.get("topology"),
                "geometry": e.get("geometry"),
                "choice": e.get("choice"),
                # Builder-level detail of the winner (strip height,
                # tile shape, ...) — advisory: consult re-derives the
                # detail from the live pickers so a geometry change
                # can never resurrect a stale shape.
                "detail": e.get("detail"),
                # The soundness latch: True only when the winner's
                # candidate program was bitwise-equal to the reference
                # schedule before it was timed. Consult refuses
                # entries without it (measured-only-after-bitwise-
                # verify, SEMANTICS.md "Tuning soundness").
                "verified": bool(e.get("verified")),
                "record": e.get("record"),
                "n_candidates": e.get("n_candidates"),
                "put_t": e.get("t_wall"),
            }
        elif ev == "tune_invalidate":
            if entries.pop(key, None) is None:
                anomalies.append(
                    f"tune: invalidate of unknown entry {key}")
    return entries, anomalies


# ---------------------------------------------------------------------------
# The DB handle (journal writer + incremental fold)
# ---------------------------------------------------------------------------

class TuneDB:
    """One tuning-DB root: the index journal writer plus an incremental
    fold of it (the ``CacheIndex`` offset discipline — only whole lines
    are consumed, so a read racing an append re-reads the torn tail
    complete next pass). All writes go through this class so the commit
    ordering (record before index line; invalidate line before record
    delete) has one home."""

    def __init__(self, root: str):
        self.root = str(root)
        self.records_dir = os.path.join(self.root, "records")
        os.makedirs(self.records_dir, exist_ok=True)
        self.index_path = os.path.join(self.root, "index.jsonl")
        self._journal: Optional[Journal] = None
        self._offset = 0
        self._entries: Dict[str, dict] = {}
        self._anomalies: List[str] = []
        # Validated record evidence, key -> (choice, mtime_ns, size):
        # lookup() runs on the hot build path, so a record that already
        # passed the evidence check is re-verified by a stat (any
        # rewrite/doctor moves mtime or size and forces a re-read)
        # instead of an open+parse per pick.
        self._record_ok: Dict[str, Tuple[str, int, int]] = {}

    @property
    def journal(self) -> Journal:
        if self._journal is None:
            self._journal = Journal(self.index_path)
        return self._journal

    def close(self) -> None:
        if self._journal is not None:
            self._journal.close()
            self._journal = None

    def __enter__(self) -> "TuneDB":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def entries(self) -> Dict[str, dict]:
        """The folded index, O(appended bytes) per call."""
        try:
            with open(self.index_path, "rb") as f:
                f.seek(self._offset)
                data = f.read()
        except OSError:
            return self._entries
        end = data.rfind(b"\n")
        if end >= 0:
            self._offset += end + 1
            events = []
            for line in data[:end + 1].split(b"\n"):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "event" in rec:
                    events.append(rec)
            reduce_tune_journal(events,
                                state=(self._entries, self._anomalies))
        return self._entries

    def anomalies(self) -> List[str]:
        self.entries()
        return list(self._anomalies)

    # -- writes ----------------------------------------------------------

    def record_path(self, key: str) -> str:
        return os.path.join(self.records_dir, f"{key}.json")

    def put(self, site: str, topology: dict, geometry: dict, *,
            choice: str, detail=None, verified: bool,
            candidates: Optional[list] = None,
            protocol: Optional[dict] = None) -> dict:
        """Admit one measured winner; returns the live entry.

        The record file (full candidate table — per-candidate bitwise
        verdicts and measured rates, the audit evidence) rename-commits
        strictly BEFORE the index line: the crash window between them
        loses the entry (the search re-runs), never publishes an entry
        whose evidence is torn."""
        if choice not in SITE_CHOICES[site]:
            raise ValueError(
                f"choice {choice!r} is outside site {site!r}'s proven-"
                f"bitwise vocabulary {SITE_CHOICES[site]}")
        key, canon = tune_key(site, topology, geometry)
        rec_path = self.record_path(key)
        record_doc = {
            "schema": TUNE_SCHEMA_VERSION,
            "key": key,
            "canon": canon,
            "choice": choice,
            "detail": detail,
            "verified": bool(verified),
            "candidates": list(candidates or []),
            "protocol": dict(protocol or {}),
        }
        tmp = os.path.join(self.records_dir,
                           f".tmp-{os.getpid()}-{key}.json")
        with open(tmp, "w") as f:
            json.dump(record_doc, f, indent=1)
        _fsync_replace(tmp, rec_path)
        rec = self.journal.append(
            "tune_put", key=key, db_schema=TUNE_SCHEMA_VERSION,
            site=site, topology=canon["topology"],
            geometry=canon["geometry"], choice=choice, detail=detail,
            verified=bool(verified),
            n_candidates=len(candidates or []),
            record=os.path.basename(rec_path))
        self._consume([rec])
        self._record_ok.pop(key, None)  # fresh evidence, fresh check
        return self._entries[key]

    def invalidate(self, key: str) -> None:
        """Invalidate-line first, THEN delete the record: a crash
        between the two leaves an orphan record file (swept by
        :meth:`sweep_orphans`), never a live entry naming missing
        evidence."""
        rec = self.journal.append("tune_invalidate", key=key)
        self._consume([rec])
        self._record_ok.pop(key, None)
        try:
            os.unlink(self.record_path(key))
        except OSError:
            pass

    def sweep_orphans(self) -> int:
        """Remove record files no live entry references — the residue
        of crashes inside the two commit windows above. Returns the
        number removed."""
        live = {str(e.get("record") or "")
                for e in self.entries().values()}
        n = 0
        try:
            names = os.listdir(self.records_dir)
        except OSError:
            return 0
        for name in names:
            if name in live:
                continue
            try:
                os.unlink(os.path.join(self.records_dir, name))
                n += 1
            except OSError:
                pass
        return n

    def _consume(self, recs) -> None:
        """Fold freshly-appended records by hand and advance the offset
        past them (the append landed at the tail; the next
        :meth:`entries` read must not double-fold)."""
        try:
            self._offset = os.path.getsize(self.index_path)
        except OSError:
            pass
        reduce_tune_journal(recs,
                            state=(self._entries, self._anomalies))

    # -- lookup ----------------------------------------------------------

    def lookup(self, site: str, topology: dict, geometry: dict
               ) -> Tuple[Optional[dict], Optional[str]]:
        """``(entry, reject_reason)`` for one decision context.

        ``(None, None)`` is a clean miss. ``(None, reason)`` means an
        entry EXISTS but fails the soundness checks — schema drift, an
        unverified winner, a choice outside the site vocabulary, or
        doctored/missing record evidence — and callers must fall back
        loudly to the analytic model (never select an unverified
        schedule)."""
        key, _canon = tune_key(site, topology, geometry)
        e = self.entries().get(key)
        if e is None:
            return None, None
        if e.get("schema") != TUNE_SCHEMA_VERSION:
            return None, (f"entry {key}: schema {e.get('schema')!r} != "
                          f"{TUNE_SCHEMA_VERSION}")
        if not e.get("verified"):
            return None, (f"entry {key}: winner was not bitwise-"
                          f"verified against the reference schedule")
        choice = e.get("choice")
        if choice not in SITE_CHOICES.get(site, ()):
            return None, (f"entry {key}: choice {choice!r} outside "
                          f"site {site!r}'s vocabulary")
        stamp = self._record_stamp(key, choice)
        if stamp is not None and self._record_ok.get(key) == stamp:
            return e, None
        rec = self._read_record(key)
        if rec is None:
            self._record_ok.pop(key, None)
            return None, f"entry {key}: record file missing/torn"
        if rec.get("key") != key or rec.get("choice") != choice:
            self._record_ok.pop(key, None)
            return None, (f"entry {key}: record evidence disagrees "
                          f"with the index line (doctored or stale)")
        # Stamp taken BEFORE the read: a concurrent rewrite between
        # the two at worst re-validates on the next lookup.
        if stamp is not None:
            self._record_ok[key] = stamp
        return e, None

    def _record_stamp(self, key: str, choice: str
                      ) -> Optional[Tuple[str, int, int]]:
        try:
            st = os.stat(self.record_path(key))
        except OSError:
            return None
        return choice, int(st.st_mtime_ns), int(st.st_size)

    def _read_record(self, key: str) -> Optional[dict]:
        try:
            with open(self.record_path(key)) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else None
        except (OSError, ValueError):
            return None


def load_tune_db(root: str) -> Tuple[Dict[str, dict], List[str],
                                     int, bool]:
    """Cold read of one DB root ->
    ``(entries, anomalies, bad_lines, torn_tail)``."""
    path = os.path.join(str(root), "index.jsonl")
    events, bad, torn = read_journal_file(path)
    entries, anomalies = reduce_tune_journal(events)
    return entries, anomalies, bad, torn
