"""Measured autotuning — consult layer over the journal-backed tuning DB.

The pickers (``pick_single_2d``, ``pick_block_temporal_2d``,
``pick_ensemble_2d``, ``temporal.resolve_halo_overlap``) call
:func:`consult` before their analytic cost models. Resolution order:

1. :func:`force` override (the search harness and parity tests pin one
   candidate through the REAL picker/factory path);
2. the active tuning DB (:func:`set_active` / ``PHT_TUNE_DB``), whose
   entries are measured winners admitted only after a bitwise-verify
   against the reference schedule;
3. ``None`` — the analytic model decides, exactly as before.

A tuned choice is ADVISORY at the kind level: the picker re-derives the
builder-level detail itself and falls back loudly
(:func:`fallback_warning`) when the choice is no longer feasible for
the geometry, when the DB entry fails its soundness checks
(``TuneDB.lookup``'s reject reasons), or when the entry is stale.
Tuning can therefore never select an unverified schedule and never
change results — every choice it can return is one of the pickers'
already-proven-bitwise schedules (SEMANTICS.md "Tuning soundness").

DB state is ORCHESTRATION-only: activation is process-global (no
``HeatConfig`` field), so enabling/disabling the DB can never perturb
cache keys or ``_build_runner``'s memo key (HL101 partition).

:func:`record` captures which source decided each site for one region
of code; ``solver.explain`` wraps itself in a recorder and reports the
notes as ``decided_by``.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import os
import warnings
from typing import Any, Dict, List, Optional, Tuple

from parallel_heat_tpu.tune.db import (  # noqa: F401 — package API
    SITE_CHOICES, TUNE_SCHEMA_VERSION, TuneDB, load_tune_db,
    reduce_tune_journal, tune_key)

# ---------------------------------------------------------------------------
# Active DB (process-global orchestration state — never config state)
# ---------------------------------------------------------------------------

_ACTIVE_SENTINEL = object()
_active_db: Any = _ACTIVE_SENTINEL  # unresolved until first use


def set_active(root: Optional[str]) -> None:
    """Point the consult layer at a DB root (``None`` disables tuning
    and restores pure-analytic picking). Overrides ``PHT_TUNE_DB``."""
    global _active_db
    if _active_db not in (None, _ACTIVE_SENTINEL):
        _active_db.close()
    _active_db = TuneDB(root) if root else None


def active() -> Optional[TuneDB]:
    """The active :class:`TuneDB`, or ``None`` when tuning is off.
    First call resolves the ``PHT_TUNE_DB`` environment variable."""
    global _active_db
    if _active_db is _ACTIVE_SENTINEL:
        root = os.environ.get("PHT_TUNE_DB") or None
        _active_db = TuneDB(root) if root else None
    return _active_db


@functools.lru_cache(maxsize=1)
def _device_probe() -> Tuple[str, int]:
    """One jax.devices()/device_count() query per process — the device
    set cannot change after jax initializes, and :func:`consult` runs
    on the hot build path (inside lru-cached build probes), so the
    probe must not pay the device enumeration per pick. The device
    KIND is deliberately NOT memoized here: ``tpu_params.params()``
    honors ``PHT_TPU_KIND``/``set_override`` at call time."""
    import jax

    return str(jax.devices()[0].platform), int(jax.device_count())


def current_topology() -> Dict[str, Any]:
    """The topology half of a tune key: platform, device generation,
    device count. Canonical-JSON-stable (plain strs/ints only); a
    fresh dict per call (callers embed it in reports they may
    mutate)."""
    from parallel_heat_tpu.ops import tpu_params

    platform, n_devices = _device_probe()
    return {
        "platform": platform,
        "device_kind": tpu_params.params().kind,
        "n_devices": n_devices,
    }


# ---------------------------------------------------------------------------
# Geometry docs — ONE builder per site, shared by the picker hooks and
# the search harness so a searched key always matches the consulted one
# (the repo's one-decision-site rule applied to key construction).
# cx/cy are deliberately excluded: coefficients never change a schedule
# choice, and including them would fragment the DB per physics run.
# ---------------------------------------------------------------------------

def _dtype_name(dtype) -> str:
    import jax.numpy as jnp

    return str(jnp.dtype(dtype).name)


def geometry_single_2d(shape, dtype, accumulate="storage") -> dict:
    return {"shape": [int(n) for n in shape],
            "dtype": _dtype_name(dtype),
            "accumulate": str(accumulate)}


def geometry_block_temporal_2d(config) -> dict:
    return {"shape": [int(n) for n in config.shape],
            "dtype": _dtype_name(config.dtype),
            "block_shape": [int(b) for b in config.block_shape()],
            "halo_depth": int(config.halo_depth)}


def geometry_halo_overlap(config) -> dict:
    depth = config.halo_depth
    return {"shape": [int(n) for n in config.shape],
            "dtype": _dtype_name(config.dtype),
            "mesh_shape": [int(m) for m in config.mesh_or_unit()],
            "halo_depth": int(depth) if depth is not None else None}


def geometry_ensemble_2d(shape, dtype, accumulate="storage") -> dict:
    return {"shape": [int(n) for n in shape],
            "dtype": _dtype_name(dtype),
            "accumulate": str(accumulate)}


def geometry_mg_partition(config) -> dict:
    from parallel_heat_tpu.config import multigrid_level_shapes

    return {"shape": [int(n) for n in config.shape],
            "dtype": _dtype_name(config.dtype),
            "mesh_shape": [int(m) for m in config.mesh_or_unit()],
            "scheme": str(config.scheme),
            "mg_levels": len(multigrid_level_shapes(
                config.shape, config.mg_levels)),
            "mg_smooth": int(config.mg_smooth)}


def geometry_for(site: str, config) -> dict:
    """Dispatch to the site's geometry builder from a (validated)
    config — the search harness's entry point."""
    if site == "single_2d":
        return geometry_single_2d(config.shape, config.dtype,
                                  config.accumulate)
    if site == "block_temporal_2d":
        return geometry_block_temporal_2d(config)
    if site == "halo_overlap":
        return geometry_halo_overlap(config)
    if site == "ensemble_2d":
        return geometry_ensemble_2d(config.shape, config.dtype,
                                    config.accumulate)
    if site == "mg_partition":
        return geometry_mg_partition(config)
    raise ValueError(f"unknown tune site {site!r}")


# ---------------------------------------------------------------------------
# Force override (search harness / parity tests)
# ---------------------------------------------------------------------------

_force_var: contextvars.ContextVar[Optional[Dict[str, str]]] = \
    contextvars.ContextVar("pht_tune_force", default=None)


@contextlib.contextmanager
def force(site: str, choice: str):
    """Pin one site's decision for the dynamic extent of the block.

    The autotuner and the bitwise-parity sweep drive every candidate
    through the REAL picker/factory path with this, which is what makes
    "every candidate the DB can return is one of the already-proven-
    bitwise schedules" true by construction. The pinned choice is still
    feasibility-checked by the picker — an infeasible pin falls back
    loudly just like a stale DB entry."""
    if choice not in SITE_CHOICES[site]:
        raise ValueError(f"choice {choice!r} outside site {site!r}'s "
                         f"vocabulary {SITE_CHOICES[site]}")
    prev = _force_var.get()
    nxt = dict(prev or {})
    nxt[site] = choice
    token = _force_var.set(nxt)
    try:
        yield
    finally:
        _force_var.reset(token)


# ---------------------------------------------------------------------------
# Decision recorder (solver.explain's decided_by feed)
# ---------------------------------------------------------------------------

_record_var: contextvars.ContextVar[Optional[List[dict]]] = \
    contextvars.ContextVar("pht_tune_record", default=None)


@contextlib.contextmanager
def record():
    """Collect per-site decision notes for the dynamic extent of the
    block; yields the (mutable) list of notes. ``solver.explain`` wraps
    its resolution pass in this and attaches the notes as
    ``decided_by``."""
    notes: List[dict] = []
    token = _record_var.set(notes)
    try:
        yield notes
    finally:
        _record_var.reset(token)


def note(site: str, source: str, choice: Any, *,
         entry: Optional[str] = None,
         reason: Optional[str] = None) -> None:
    """Record one decision: ``source`` is ``"tuned-db"``,
    ``"analytic-model"``, or ``"forced"``. No-op outside
    :func:`record`."""
    notes = _record_var.get()
    if notes is None:
        return
    d: Dict[str, Any] = {"site": site, "source": source,
                         "choice": choice}
    if entry:
        d["entry"] = entry
    if reason:
        d["reason"] = reason
    notes.append(d)


# ---------------------------------------------------------------------------
# Consult (the picker hook)
# ---------------------------------------------------------------------------

def consult(site: str, geometry: Dict[str, Any]
            ) -> Tuple[Optional[str], Optional[str], Optional[str]]:
    """``(choice, source, entry_key)`` for one decision context.

    ``(None, None, None)`` means no override and no usable entry — the
    analytic model decides. A DB entry that exists but fails its
    soundness checks triggers :func:`fallback_warning` here (the loud
    fallback: never silently run on rejected evidence) and also returns
    the analytic triple."""
    forced = _force_var.get()
    if forced and site in forced:
        return forced[site], "forced", None
    db = active()
    if db is None:
        return None, None, None
    try:
        entry, reason = db.lookup(site, current_topology(), geometry)
    except Exception as e:  # noqa: BLE001 — a broken DB must not break solves
        fallback_warning(site, f"tuning-DB lookup failed: {e!r}")
        return None, None, None
    if entry is not None:
        return entry["choice"], "tuned-db", entry["key"]
    if reason is not None:
        fallback_warning(site, reason)
    return None, None, None


def fallback_warning(site: str, reason: str) -> None:
    """The LOUD analytic fallback (SEMANTICS.md "Tuning soundness"):
    a rejected/corrupt/stale/infeasible tuned entry warns before the
    analytic model takes over, so fleet logs show the DB rotting
    instead of silently losing measured speed."""
    warnings.warn(f"tune[{site}]: falling back to analytic model: "
                  f"{reason}", RuntimeWarning, stacklevel=3)
