"""Layer 2: AST-level custom lint over the package source (``HL2xx``).

Pure ``ast`` — no jax import, no tracing — so this layer runs in
milliseconds and can ride the smoke-target chains. Each rule is a
function ``rule(tree, src_lines, path) -> [Finding]``; the registry
``AST_RULES`` maps rule id -> (severity, summary, fn).

Rules:

- **HL201 blocking-in-dispatch** — no blocking host syncs
  (``block_until_ready``, ``device_get``, ``np.asarray``, ``.item()``,
  ``sync(...)``, ``time.sleep``, ``float()/int()/bool()`` on
  non-literals) inside *dispatch regions*: the async dispatch path of
  the pipelined stream and the timed loops of the A/B harnesses. A
  region is a function whose ``def`` line (or the line above it)
  carries ``# heatlint: dispatch-region``, or the lines between
  ``# heatlint: begin dispatch-region`` / ``# heatlint: end
  dispatch-region`` markers.
- **HL202 wallclock-in-traced** — no wall-clock or host-RNG calls
  (``time.*``, ``datetime.now``, ``random.*``, ``np.random.*``,
  ``uuid``, ``secrets``, ``os.urandom``) inside traced code: functions
  decorated with / passed to ``jax.jit``, bodies handed to ``lax``
  control flow (``fori_loop``/``while_loop``/``scan``/``cond``/
  ``switch``), Pallas kernels (first argument of ``pallas_call``), and
  functions passed to ``shard_map``. Such a call traces to a constant:
  the program bakes in one arbitrary clock/RNG sample and silently
  reuses it forever. (``jax.random`` is traced and deterministic —
  not flagged.)
- **HL203 pallas-name** — every ``pallas_call`` carries
  ``name="heat_*"`` as a string literal: the profiler-trace contract
  from PR 3 (SEMANTICS.md), previously maintained by hand across 17
  call sites.
- **HL204 lock-discipline** — in classes holding a ``threading.Lock``/
  ``RLock`` attribute, any attribute the class mutates under ``with
  self.<lock>`` somewhere is *lock-guarded*; mutating it anywhere else
  (outside ``__init__``, where the object is not yet shared) is a
  race. The guarded set is inferred, not declared: the code's own
  locking IS the declaration.
- **HL205 unused-import** — import hygiene: a module-level import
  never referenced (by name, in ``__all__``, or via a ``# noqa``
  waiver) in the module. ``__init__.py`` re-export surfaces are
  skipped.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional

from parallel_heat_tpu.analysis.findings import Finding

_PRAGMA_FUNC = "heatlint: dispatch-region"
_PRAGMA_BEGIN = "heatlint: begin dispatch-region"
_PRAGMA_END = "heatlint: end dispatch-region"

# Repo root, derived from this file's location — the default scan
# scope must NOT depend on the invoker's cwd: a gate run from any
# other directory would otherwise scan zero files and report clean.
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Default AST-layer scan scope, relative to the repo root.
DEFAULT_PATHS = ("parallel_heat_tpu", "tools", "bench.py")


def default_scan_paths():
    """The default scope resolved against the repo root; raises when
    nothing resolves (a silently-empty scan set would un-gate CI)."""
    paths = [os.path.join(REPO_ROOT, p) for p in DEFAULT_PATHS]
    paths = [p for p in paths if os.path.exists(p)]
    if not paths:
        raise RuntimeError(
            f"heatlint: none of the default scan paths {DEFAULT_PATHS} "
            f"exist under {REPO_ROOT!r} — refusing to report a clean "
            f"result for an empty scan")
    return paths


def _iter_py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", "build")]
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def _qual_name(node) -> Optional[str]:
    """Dotted name of a call target: ``jax.block_until_ready`` ->
    'jax.block_until_ready', bare ``sync`` -> 'sync'."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _enclosing_symbol(stack) -> str:
    names = [n.name for n in stack
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef))]
    return ".".join(names) if names else "<module>"


class _Walker(ast.NodeVisitor):
    """Generic visitor that tracks the def/class stack."""

    def __init__(self):
        self.stack: list = []

    def generic_visit(self, node):
        push = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef))
        if push:
            self.stack.append(node)
        super().generic_visit(node)
        if push:
            self.stack.pop()

    visit_FunctionDef = generic_visit
    visit_AsyncFunctionDef = generic_visit
    visit_ClassDef = generic_visit


# ---------------------------------------------------------------------------
# HL201 blocking-in-dispatch
# ---------------------------------------------------------------------------

_BLOCKING_TAILS = ("block_until_ready", "device_get", "item")
_BLOCKING_CALLS = ("sync", "time.sleep")
_BLOCKING_ASARRAY = ("np.asarray", "numpy.asarray", "onp.asarray")
_SCALAR_CASTS = ("float", "int", "bool")


def _string_lines(tree):
    """Lines covered by string literals (docstrings included) — a
    marker mentioned in documentation is not a marker."""
    lines = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Constant, ast.JoinedStr)) and (
                isinstance(node, ast.JoinedStr)
                or isinstance(node.value, str)):
            lines.update(range(node.lineno, (node.end_lineno or
                                             node.lineno) + 1))
    return lines


def _dispatch_regions(tree, src_lines, path):
    """``(line ranges covered by a dispatch-region pragma, marker
    findings)``. An unterminated ``begin`` marker still covers
    begin..EOF (conservative) but is reported — a deleted ``end`` line
    must never silently disable the rule."""
    regions = []
    findings = []
    in_string = _string_lines(tree)
    # Function-level pragma: on the def line or the line above it.
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cand = [src_lines[node.lineno - 1]]
        if node.lineno >= 2:
            cand.append(src_lines[node.lineno - 2])
        if any(_PRAGMA_FUNC in c and _PRAGMA_BEGIN not in c
               for c in cand):
            regions.append((node.lineno, node.end_lineno))
    # Block markers.
    begin = None
    for i, line in enumerate(src_lines, start=1):
        if i in in_string:
            continue
        if _PRAGMA_BEGIN in line:
            if begin is not None:
                findings.append(Finding(
                    "HL201", "error", path, begin, "<module>",
                    f"'# {_PRAGMA_BEGIN}' marker at line {begin} has "
                    f"no matching end before the next begin at line "
                    f"{i} — add '# {_PRAGMA_END}'"))
            begin = i
        elif _PRAGMA_END in line and begin is not None:
            regions.append((begin, i))
            begin = None
    if begin is not None:
        findings.append(Finding(
            "HL201", "error", path, begin, "<module>",
            f"unterminated '# {_PRAGMA_BEGIN}' marker — no matching "
            f"'# {_PRAGMA_END}' before end of file (scanning "
            f"begin..EOF conservatively; terminate the region)"))
        regions.append((begin, len(src_lines)))
    return regions, findings


def rule_hl201(tree, src_lines, path) -> List[Finding]:
    regions, out0 = _dispatch_regions(tree, src_lines, path)
    if not regions:
        return out0

    def in_region(lineno):
        return any(lo <= lineno <= hi for lo, hi in regions)

    out = out0

    class V(_Walker):
        def visit_Call(self, node):
            if in_region(node.lineno):
                why = None
                q = _qual_name(node.func)
                if q is not None:
                    tail = q.rsplit(".", 1)[-1]
                    if tail in _BLOCKING_TAILS:
                        why = f"{q}() synchronizes with the device"
                    elif q in _BLOCKING_CALLS:
                        why = f"{q}() blocks the dispatch path"
                    elif q in _BLOCKING_ASARRAY or q.endswith(".asarray") \
                            and not q.startswith(("jnp", "jax")):
                        why = (f"{q}() gathers the array to host "
                               f"(a full device sync + transfer)")
                    elif q in _SCALAR_CASTS and node.args and not \
                            isinstance(node.args[0], ast.Constant):
                        why = (f"{q}() on a possible device value reads "
                               f"it to host (blocks on the program)")
                if why is not None:
                    out.append(Finding(
                        "HL201", "error", path, node.lineno,
                        _enclosing_symbol(self.stack),
                        f"blocking call inside a dispatch region: {why} "
                        f"— drain observers outside the region or use a "
                        f"non-blocking copy (copy_to_host_async)"))
            self.generic_visit(node)

    V().visit(tree)
    return out


# ---------------------------------------------------------------------------
# HL202 wallclock-in-traced
# ---------------------------------------------------------------------------

_TRACE_ENTRY_CALLS = {
    "fori_loop", "while_loop", "scan", "cond", "switch", "pallas_call",
    "shard_map", "_shard_map", "jit", "named_call", "checkpoint",
    "remat", "vmap", "pmap", "grad", "value_and_grad",
}
_HOST_CLOCK_RNG_PREFIXES = (
    "time.", "datetime.", "random.", "np.random.", "numpy.random.",
    "uuid.", "secrets.",
)
_HOST_CLOCK_RNG_EXACT = ("os.urandom",)


def _is_jit_decorator(dec) -> bool:
    q = _qual_name(dec) or ""
    if q.endswith("jit"):
        return True
    if isinstance(dec, ast.Call):
        # functools.partial(jax.jit, ...) or jax.jit(static_argnums=...)
        fq = _qual_name(dec.func) or ""
        if fq.endswith("jit"):
            return True
        if fq.endswith("partial") and dec.args:
            aq = _qual_name(dec.args[0]) or ""
            if aq.endswith("jit"):
                return True
    return False


def rule_hl202(tree, src_lines, path) -> List[Finding]:
    # Pass 1: collect traced roots — decorated defs, and defs/lambdas
    # passed (by name or inline) to trace-entry calls.
    module_defs = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            module_defs.setdefault(node.name, node)
    traced_nodes = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                traced_nodes.append(node)
        elif isinstance(node, ast.Call):
            q = _qual_name(node.func) or ""
            if q.rsplit(".", 1)[-1] not in _TRACE_ENTRY_CALLS:
                continue
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Lambda):
                    traced_nodes.append(arg)
                elif isinstance(arg, ast.Name) and arg.id in module_defs:
                    traced_nodes.append(module_defs[arg.id])
    if not traced_nodes:
        return []
    spans = sorted({(n.lineno, n.end_lineno) for n in traced_nodes})

    def in_traced(lineno):
        return any(lo <= lineno <= hi for lo, hi in spans)

    out = []

    class V(_Walker):
        def visit_Call(self, node):
            if in_traced(node.lineno):
                q = _qual_name(node.func) or ""
                if (q in _HOST_CLOCK_RNG_EXACT
                        or any(q.startswith(p)
                               for p in _HOST_CLOCK_RNG_PREFIXES)):
                    out.append(Finding(
                        "HL202", "error", path, node.lineno,
                        _enclosing_symbol(self.stack),
                        f"host wall-clock/RNG call {q}() inside traced "
                        f"code: it evaluates ONCE at trace time and is "
                        f"baked into the compiled program as a constant "
                        f"— hoist it to the host side, or use "
                        f"jax.random for in-program randomness"))
            self.generic_visit(node)

    V().visit(tree)
    return out


# ---------------------------------------------------------------------------
# HL203 pallas-name
# ---------------------------------------------------------------------------

def rule_hl203(tree, src_lines, path) -> List[Finding]:
    out = []

    class V(_Walker):
        def visit_Call(self, node):
            q = _qual_name(node.func) or ""
            if q.rsplit(".", 1)[-1] == "pallas_call":
                name_kw = next((k.value for k in node.keywords
                                if k.arg == "name"), None)
                sym = _enclosing_symbol(self.stack)
                if name_kw is None:
                    out.append(Finding(
                        "HL203", "error", path, node.lineno, sym,
                        "pallas_call without a name= — every kernel "
                        "must carry a literal name=\"heat_*\" so "
                        "profiler traces attribute device time to the "
                        "kernel family (SEMANTICS.md annotations "
                        "contract)"))
                elif not (isinstance(name_kw, ast.Constant)
                          and isinstance(name_kw.value, str)
                          and name_kw.value.startswith("heat_")):
                    out.append(Finding(
                        "HL203", "error", path, node.lineno, sym,
                        "pallas_call name= must be a string literal "
                        "starting with 'heat_' (got "
                        f"{ast.dump(name_kw)[:60]})"))
            self.generic_visit(node)

    V().visit(tree)
    return out


# ---------------------------------------------------------------------------
# HL204 lock-discipline
# ---------------------------------------------------------------------------

_MUTATOR_METHODS = ("append", "extend", "insert", "add", "update",
                    "pop", "popleft", "remove", "clear", "discard",
                    "appendleft", "setdefault", "put", "put_nowait")


def _self_attr(node) -> Optional[str]:
    """'x' for ``self.x``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_attrs(cls) -> set:
    """Attributes assigned a threading.Lock()/RLock() anywhere in the
    class."""
    locks = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            q = _qual_name(node.value.func) or ""
            if q.rsplit(".", 1)[-1] in ("Lock", "RLock"):
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        locks.add(attr)
    return locks


def _attr_mutations(node):
    """Yield (attr_name, lineno) for ``self.X = ...``, ``self.X += ...``
    and ``self.X.append(...)``-style mutations inside ``node``."""
    for n in ast.walk(node):
        if isinstance(n, (ast.Assign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    yield attr, n.lineno
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr in _MUTATOR_METHODS:
                attr = _self_attr(n.func.value)
                if attr is not None:
                    yield attr, n.lineno


def rule_hl204(tree, src_lines, path) -> List[Finding]:
    out = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if not locks:
            continue
        methods = [m for m in cls.body
                   if isinstance(m, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        # Line spans inside `with self.<lock>:` blocks, per method.
        locked_spans = []
        for m in methods:
            for n in ast.walk(m):
                if isinstance(n, ast.With):
                    for item in n.items:
                        expr = item.context_expr
                        # with self._lock:  /  with self._lock, other:
                        attr = _self_attr(expr)
                        if attr is None and isinstance(expr, ast.Call):
                            attr = _self_attr(expr.func)
                        if attr in locks:
                            locked_spans.append((n.lineno, n.end_lineno))
                            break

        def under_lock(lineno):
            return any(lo <= lineno <= hi for lo, hi in locked_spans)

        # Infer the guarded set: attrs mutated under a lock anywhere
        # outside __init__.
        guarded = set()
        for m in methods:
            if m.name == "__init__":
                continue
            for attr, lineno in _attr_mutations(m):
                if under_lock(lineno) and attr not in locks:
                    guarded.add(attr)
        if not guarded:
            continue
        for m in methods:
            if m.name == "__init__":
                continue
            for attr, lineno in _attr_mutations(m):
                if attr in guarded and not under_lock(lineno):
                    out.append(Finding(
                        "HL204", "error", path, lineno,
                        f"{cls.name}.{m.name}",
                        f"thread-shared attribute self.{attr} is "
                        f"mutated without holding the class lock — "
                        f"elsewhere in {cls.name} it is only written "
                        f"under `with self.{'/'.join(sorted(locks))}`; "
                        f"an unlocked write races those critical "
                        f"sections"))
    return out


# ---------------------------------------------------------------------------
# HL205 unused-import
# ---------------------------------------------------------------------------

def rule_hl205(tree, src_lines, path) -> List[Finding]:
    if os.path.basename(path) == "__init__.py":
        return []  # re-export surface: unused-by-design
    imports = {}  # binding name -> (lineno, display)
    for node in tree.body:
        if isinstance(node, ast.Import):
            for alias in node.names:
                binding = alias.asname or alias.name.split(".")[0]
                imports[binding] = (node.lineno, alias.name)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                if alias.name == "*":
                    continue
                binding = alias.asname or alias.name
                imports[binding] = (
                    node.lineno,
                    f"{'.' * node.level}{node.module or ''}.{alias.name}")
    if not imports:
        return []
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value,
                                                           str):
            # __all__ entries / docstring references by exact name are
            # counted as use only for __all__-style short strings.
            if node.value.isidentifier():
                used.add(node.value)
    out = []
    for binding, (lineno, display) in imports.items():
        if binding in used:
            continue
        if "noqa" in src_lines[lineno - 1]:
            continue
        out.append(Finding(
            "HL205", "error", path, lineno, "<module>",
            f"unused import: {display!r} (bound as {binding!r}) is "
            f"never referenced in this module"))
    return out


# ---------------------------------------------------------------------------
# registry / driver
# ---------------------------------------------------------------------------

AST_RULES = {
    "HL201": ("error", "blocking host sync inside a dispatch region",
              rule_hl201),
    "HL202": ("error", "wall-clock/RNG call inside traced code",
              rule_hl202),
    "HL203": ("error", "pallas_call without a literal heat_* name",
              rule_hl203),
    "HL204": ("error", "lock-guarded attribute mutated without the lock",
              rule_hl204),
    "HL205": ("error", "unused module-level import", rule_hl205),
}


def lint_file(path, rules=None) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [Finding("HL200", "error", path, e.lineno or 0,
                        "<module>", f"syntax error: {e.msg}")]
    src_lines = src.splitlines() or [""]
    out = []
    for rule_id, (_sev, _summary, fn) in AST_RULES.items():
        if rules is not None and rule_id not in rules:
            continue
        out.extend(fn(tree, src_lines, path))
    return out


def lint_paths(paths=None, rules=None) -> List[Finding]:
    """Run the AST rules over ``paths`` (files or directories;
    defaults to the package + tools + bench.py, anchored to the repo
    root so the gate works from any cwd)."""
    if paths is None:
        paths = default_scan_paths()
    out = []
    for f in _iter_py_files(paths):
        out.extend(lint_file(f, rules=rules))
    return out
