"""Layer 3: SPMD/collective protocol verifiers (``HL3xx``).

The halo-exchange protocol is the layer of this solver where the
paper's correctness actually lives: four (2D) / six (3D) ``ppermute``
shifts per exchange round, a ``pmax`` convergence vote, and host
control flow steered by reduced scalars. All of it runs under
``shard_map``, and on pre-vma jax the compat shim
(``utils/compat.py``) runs with ``check_rep=False`` — nothing checks
replication dynamically. These rules supply the missing *static*
proofs by tracing the real sharded programs (``solver._build_runner``)
on a simulated multi-device mesh — abstract evaluation only, nothing
executes — and walking the jaxprs:

- **HL301 halo-permutation-protocol** — every ``ppermute`` permutation
  table is a complete one-hop shift consistent with the ``mesh.py``
  topology: pairs are ``(i, i±1)`` along exactly one named axis, no
  source or destination appears twice (a partial bijection — the
  static analogue of matched MPI send/recv), and the table covers
  every device that HAS the neighbor (an incomplete table silently
  drops halo data). Within each exchange phase, shift directions come
  in ``+1``/``-1`` pairs — the deadlock-freedom symmetry of the
  reference's paired ``MPI_Isend``/``MPI_Irecv``
  (``mpi/...stat.c:130-155``).
- **HL302 collective-divergence** — collective sequences are identical
  on both sides of every ``lax.cond`` and stable across loop exits,
  *unless* the branch predicate is provably replicated (then every
  device takes the same side and divergence is impossible — the
  converge tail ``lax.cond`` is legal exactly because its predicate
  comes out of a ``pmax``). A ``lax.while_loop`` whose body performs
  collectives must likewise have a replicated predicate, or some
  devices exit the loop while their neighbors still wait in a
  collective: an SPMD hang at scale. Across the fixed / converge /
  f32chunk program variants of one geometry, the set of exchange
  tables must be identical — a variant that exchanges differently
  would deadlock against the others' compiled expectations.
- **HL303 replication-proof** — a forward varying-axes dataflow over
  each ``shard_map`` body (the vma system re-implemented as a static
  analysis, since the compat shim disables the dynamic checker on
  pre-0.5 jax): every output the ``out_specs`` declare replicated
  (``P()`` — the convergence residual, step counts, guard verdicts
  that feed host control flow) must be *provably* invariant across
  the mesh, i.e. its varying set — seeded by input shardings and
  ``axis_index``, grown by ``ppermute``, erased only by all-axes
  reductions (``pmax``/``psum``/``pmin``) — is empty. An unreplicated
  scalar fed to host control flow desynchronizes the SPMD programs.

All audits accept injected targets so the test fixtures can seed
violations without touching the real solver.
"""

from __future__ import annotations

import functools
from typing import List

from parallel_heat_tpu.analysis.findings import Finding

# The 2D mesh shapes the audit proves the exchange protocol over —
# a superset of tests/test_sharded.py's MESHES (pinned by
# tests/test_analysis.py::test_audit_meshes_cover_test_sharded), so
# the static proof covers every topology the dynamic parity suite
# exercises.
AUDIT_MESHES_2D = ((1, 1), (2, 1), (1, 2), (2, 2), (2, 4), (4, 2),
                   (8, 1), (1, 8))
AUDIT_MESHES_3D = ((2, 2, 2), (2, 1, 2), (1, 2, 4))

_LOC = "parallel_heat_tpu/parallel/halo.py"

# Collectives that erase variance over their named axes.
_REDUCING = {"pmax", "pmin", "psum", "all_gather"}
# Call-like primitives whose single sub-jaxpr consumes the eqn invars
# 1:1 (after the closed jaxpr's consts).
_CALL_PRIMS = {"pjit", "closed_call", "core_call", "remat", "remat2",
               "checkpoint", "custom_jvp_call", "custom_vjp_call",
               "custom_vjp_call_jaxpr", "named_call"}


def _axes_tuple(x):
    if isinstance(x, (tuple, list)):
        return tuple(x)
    return (x,)


def _inner(j):
    """The open Jaxpr of a possibly-closed jaxpr."""
    return getattr(j, "jaxpr", j)


def _consts_of(j):
    return getattr(j, "consts", ())


def _sub_jaxprs_of(eqn):
    from parallel_heat_tpu.analysis.contracts import _sub_jaxprs

    return list(_sub_jaxprs(eqn.params))


# ---------------------------------------------------------------------------
# Target matrix
# ---------------------------------------------------------------------------

class SpmdTarget:
    """One traceable program: ``fn(sds)`` is traced with
    ``jax.make_jaxpr``. ``family`` groups the fixed/converge/f32chunk
    variants whose exchange tables HL302 requires identical."""

    def __init__(self, label, family, variant, fn, sds):
        self.label = label
        self.family = family
        self.variant = variant
        self.fn = fn
        self.sds = sds


def _runner_target(cfg, family, variant):
    import jax

    from parallel_heat_tpu.solver import _build_runner, _observer_free

    runner, _mesh = _build_runner(_observer_free(cfg))
    sds = jax.ShapeDtypeStruct(cfg.shape, cfg.dtype)
    return SpmdTarget(f"{family}/{variant}", family, variant, runner, sds)


def default_spmd_targets():
    """``(targets, skip_findings)`` — the real solver programs over the
    audit mesh matrix, filtered to the devices this process has (the
    heatlint CLI requests 8 virtual CPU devices up front; an embedder
    with fewer gets a loud warning per skipped mesh, never a silently
    shrunken proof)."""
    import jax

    from parallel_heat_tpu.config import HeatConfig

    n_dev = len(jax.devices())
    targets, skips = [], []

    def mesh_ok(mesh):
        n = 1
        for d in mesh:
            n *= d
        return n <= n_dev

    def skip(mesh, what):
        skips.append(Finding(
            "HL301", "warning", _LOC, 0, "default_spmd_targets",
            f"mesh {mesh} ({what}) skipped: needs more devices than "
            f"the {n_dev} this process has — the exchange protocol "
            f"for that topology is UNPROVEN here (run via "
            f"tools/heatlint.py, which requests 8 virtual CPU "
            f"devices)", soundness=True))

    for mesh in AUDIT_MESHES_2D:
        if not mesh_ok(mesh):
            skip(mesh, "2D")
            continue
        fam = f"jnp-2d-{mesh[0]}x{mesh[1]}"
        base = dict(nx=16, ny=16, backend="jnp", mesh_shape=mesh)
        targets.append(_runner_target(
            HeatConfig(steps=4, **base), fam, "fixed"))
        targets.append(_runner_target(
            HeatConfig(steps=40, converge=True, check_interval=20,
                       **base), fam, "converge"))
    for mesh in AUDIT_MESHES_3D:
        if not mesh_ok(mesh):
            skip(mesh, "3D")
            continue
        fam = f"jnp-3d-{'x'.join(map(str, mesh))}"
        base = dict(nx=8, ny=8, nz=8, backend="jnp", mesh_shape=mesh)
        targets.append(_runner_target(
            HeatConfig(steps=4, **base), fam, "fixed"))
        targets.append(_runner_target(
            HeatConfig(steps=24, converge=True, check_interval=8,
                       **base), fam, "converge"))
    # K-deep temporal exchange rounds (parallel/temporal.py), jnp and
    # Mosaic (kernel G + deferred band; interpret mode traces the same
    # program structure hardware runs).
    if mesh_ok((2, 2)):
        base = dict(nx=32, ny=32, backend="jnp", mesh_shape=(2, 2),
                    halo_depth=4)
        targets.append(_runner_target(
            HeatConfig(steps=8, **base), "jnp-2d-temporal", "fixed"))
        targets.append(_runner_target(
            HeatConfig(steps=40, converge=True, check_interval=8,
                       **base), "jnp-2d-temporal", "converge"))
        # Exchange-schedule variants (SEMANTICS.md "Overlapped
        # exchange"): the overlapped/deferred and phase-separated
        # schedules of one geometry MUST exchange identical halo
        # tables — HL302's cross-variant rule proves it statically for
        # every family that spells both out. The default targets above
        # resolve halo_overlap=auto (the overlapped schedule), so
        # adding the "phase" spelling pins the pair.
        targets.append(_runner_target(
            HeatConfig(steps=8, halo_overlap="phase", **base),
            "jnp-2d-temporal", "fixed-phase"))
        basep = dict(nx=32, ny=32, backend="pallas", mesh_shape=(2, 2),
                     halo_depth=8)
        targets.append(_runner_target(
            HeatConfig(steps=16, **basep), "pallas-2d-temporal",
            "fixed"))
        targets.append(_runner_target(
            HeatConfig(steps=32, converge=True, check_interval=8,
                       **basep), "pallas-2d-temporal", "converge"))
        # The kernel-G schedule triple: auto resolves to the pipelined
        # round here, so "fixed" above already audits the
        # double-buffered tables; these pin the deferred and
        # phase-separated spellings into the same family.
        targets.append(_runner_target(
            HeatConfig(steps=16, halo_overlap="overlap", **basep),
            "pallas-2d-temporal", "fixed-overlap"))
        targets.append(_runner_target(
            HeatConfig(steps=16, halo_overlap="phase", **basep),
            "pallas-2d-temporal", "fixed-phase"))
    if mesh_ok((2, 2, 2)):
        # 3D deferred rounds (the x phase overlapped) vs
        # phase-separated: same cross-schedule table pin as 2D.
        base3t = dict(nx=8, ny=8, nz=8, backend="jnp",
                      mesh_shape=(2, 2, 2), halo_depth=2)
        targets.append(_runner_target(
            HeatConfig(steps=4, **base3t), "jnp-3d-temporal", "fixed"))
        targets.append(_runner_target(
            HeatConfig(steps=4, halo_overlap="phase", **base3t),
            "jnp-3d-temporal", "fixed-phase"))
        # Per-step pallas block path (kernel B/C sharded or the jnp
        # fallback — whatever pick_block_2d routes; the exchange
        # protocol must be identical either way).
        basebs = dict(nx=32, ny=32, backend="pallas", mesh_shape=(2, 2),
                      halo_depth=1)
        targets.append(_runner_target(
            HeatConfig(steps=4, **basebs), "pallas-2d-perstep",
            "fixed"))
        targets.append(_runner_target(
            HeatConfig(steps=40, converge=True, check_interval=20,
                       **basebs), "pallas-2d-perstep", "converge"))
    # Partitioned multigrid V-cycle (ops/multigrid_sharded.py): the
    # per-sweep halo exchanges, the restrict/prolong seam shifts (the
    # one-sided north+west / south+east pairs — HL301's per-jaxpr
    # direction symmetry holds because every restriction seam has its
    # prolongation transpose in the same unrolled cycle body) and the
    # agglomeration all_gather/dynamic_slice dataflow. fixed, converge
    # and the Crank-Nicolson RHS must exchange IDENTICAL tables
    # (HL302's cross-variant rule); the P() convergence scalars must
    # prove replicated through the pmax verdicts (HL303).
    if mesh_ok((2, 4)):
        basem = dict(nx=16, ny=16, cx=6.5, cy=6.5, backend="jnp",
                     mesh_shape=(2, 4), scheme="backward_euler",
                     mg_partition="partitioned")
        targets.append(_runner_target(
            HeatConfig(steps=4, **basem), "jnp-2d-mgpart", "fixed"))
        targets.append(_runner_target(
            HeatConfig(steps=40, converge=True, check_interval=4,
                       **basem), "jnp-2d-mgpart", "converge"))
        targets.append(_runner_target(
            HeatConfig(steps=4, **dict(basem,
                                       scheme="crank_nicolson")),
            "jnp-2d-mgpart", "fixed-cn"))
        # Deep partitioned chain: at 4096^2 the analytic plan keeps
        # TWO levels partitioned, so the partitioned->partitioned
        # restriction/prolongation tables (not just the agglomeration
        # transition) enter the proof. Tracing only — the audit never
        # executes, so the grid size costs nothing.
        targets.append(_runner_target(
            HeatConfig(nx=4096, ny=4096, cx=1400.0, cy=1400.0,
                       steps=2, backend="jnp", mesh_shape=(2, 4),
                       scheme="backward_euler",
                       mg_partition="partitioned"),
            "jnp-2d-mgpart-deep", "fixed"))
    # f32chunk variants are single-device by contract
    # (config.validate()); their collective signature must be EMPTY —
    # a collective appearing here would be an SPMD call outside any
    # mesh.
    basef = dict(nx=32, ny=32, dtype="bfloat16", accumulate="f32chunk",
                 backend="jnp")
    targets.append(_runner_target(
        HeatConfig(steps=32, **basef), "f32chunk-2d", "fixed"))
    targets.append(_runner_target(
        HeatConfig(steps=64, converge=True, check_interval=16, **basef),
        "f32chunk-2d", "converge"))
    return targets, skips


@functools.lru_cache(maxsize=1)
def _traced_default():
    """Trace the default target matrix once per process; the three
    rules share it (tracing is the expensive part)."""
    import jax

    targets, skips = default_spmd_targets()
    traced = []
    for t in targets:
        traced.append((t, jax.make_jaxpr(t.fn)(t.sds)))
    return traced, skips


def _traced(targets):
    if targets is None:
        return _traced_default()
    import jax

    return [(t, jax.make_jaxpr(t.fn)(t.sds)) for t in targets], []


# ---------------------------------------------------------------------------
# shard_map discovery
# ---------------------------------------------------------------------------

def _find_shard_maps(closed):
    """Yield every ``shard_map`` eqn reachable from ``closed``."""
    stack = [closed]
    seen = set()
    while stack:
        j = _inner(stack.pop())
        if id(j) in seen:
            continue
        seen.add(id(j))
        for eqn in j.eqns:
            if eqn.primitive.name == "shard_map":
                yield eqn
            stack.extend(_sub_jaxprs_of(eqn))


def _mesh_info(eqn):
    """(axis_names tuple, {axis: size}) from a shard_map eqn."""
    mesh = eqn.params["mesh"]
    names = tuple(mesh.axis_names)
    sizes = dict(mesh.shape)
    return names, sizes


def _names_axes(names_entry) -> frozenset:
    """Axes mentioned by one in_names/out_names dict entry."""
    out = set()
    for axes in names_entry.values():
        out.update(_axes_tuple(axes))
    return frozenset(out)


# ---------------------------------------------------------------------------
# HL301 halo permutation protocol
# ---------------------------------------------------------------------------

def _check_ppermute(eqn, sizes, report, where):
    axes = _axes_tuple(eqn.params["axis_name"])
    perm = tuple(tuple(p) for p in eqn.params["perm"])
    if len(axes) != 1:
        report(f"{where}: ppermute over multiple axes {axes} — the "
               f"halo protocol uses single-axis shifts; a multi-axis "
               f"table cannot be checked against the mesh topology")
        return None
    axis = axes[0]
    n = sizes.get(axis)
    if n is None:
        report(f"{where}: ppermute over unknown mesh axis {axis!r}")
        return None
    srcs = [s for s, _ in perm]
    dsts = [d for _, d in perm]
    bad = [i for i in srcs + dsts if not (0 <= i < n)]
    if bad:
        report(f"{where}: ppermute index {bad[0]} out of range for "
               f"axis {axis!r} of size {n}")
        return None
    if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
        report(f"{where}: ppermute table {perm} is not a partial "
               f"bijection on axis {axis!r} — a repeated source or "
               f"destination means one device sends twice or receives "
               f"twice in one collective (the MPI analogue: mismatched "
               f"send/recv counts = deadlock)")
        return None
    down = {(i, i + 1) for i in range(n - 1)}
    up = {(i + 1, i) for i in range(n - 1)}
    got = set(perm)
    if not got:
        # A size-1 axis has no neighbor edges; an empty table is a
        # correct no-op exchange, not a shift in either direction
        # (matching both reference sets would skew the pairing count).
        return None
    if got == down:
        return (axis, +1)
    if got == up:
        return (axis, -1)
    if any(abs(s - d) != 1 for s, d in got):
        hop = next((s, d) for s, d in got if abs(s - d) != 1)
        report(f"{where}: ppermute pair {hop} on axis {axis!r} is not "
               f"a one-hop neighbor shift — the mesh.py topology only "
               f"defines ±1 neighbors (MPI_Cart_shift), so this edge "
               f"has no ICI route the exchange protocol covers")
        return None
    report(f"{where}: ppermute table {sorted(got)} on axis {axis!r} "
           f"(size {n}) is an INCOMPLETE shift — a complete "
           f"non-periodic ±1 shift has {n - 1} pairs covering every "
           f"neighbor edge; devices missing from the table silently "
           f"exchange zeros where real halo data is required")
    return None


def _audit_ppermutes_under(body, sizes, report):
    """Walk ``body``; check every ppermute and the per-jaxpr direction
    pairing. Returns the set of (axis, frozenset(perm)) tables seen."""
    tables = set()
    stack = [body]
    seen = set()
    while stack:
        j = _inner(stack.pop())
        if id(j) in seen:
            continue
        seen.add(id(j))
        directions = []
        for eqn in j.eqns:
            if eqn.primitive.name == "ppermute":
                axes = _axes_tuple(eqn.params["axis_name"])
                perm = frozenset(tuple(p) for p in eqn.params["perm"])
                tables.add((axes, perm))
                d = _check_ppermute(eqn, sizes, report,
                                    f"ppermute(axis={axes})")
                if d is not None:
                    directions.append(d)
            stack.extend(_sub_jaxprs_of(eqn))
        # Direction symmetry within one jaxpr (one exchange phase
        # lives in one jaxpr): +1 and -1 shift counts must match per
        # axis — the paired-send/recv deadlock-freedom argument.
        for axis in {a for a, _ in directions}:
            n_down = sum(1 for a, d in directions
                         if a == axis and d == +1)
            n_up = sum(1 for a, d in directions
                       if a == axis and d == -1)
            if n_down != n_up:
                report(
                    f"unpaired shift direction on axis {axis!r}: "
                    f"{n_down} down-shift vs {n_up} up-shift ppermute "
                    f"tables in one exchange phase — every neighbor "
                    f"send needs the symmetric receive "
                    f"(mpi/...stat.c:130-155 pairs all four "
                    f"directions)")
    return tables


# ---------------------------------------------------------------------------
# Varying-axes dataflow (HL302 / HL303)
# ---------------------------------------------------------------------------

def _collective_signature(j):
    """Deep, ordered collective signature of a jaxpr."""
    sig = []
    for eqn in _inner(j).eqns:
        name = eqn.primitive.name
        if name == "ppermute":
            sig.append(("ppermute",
                        _axes_tuple(eqn.params["axis_name"]),
                        tuple(sorted(tuple(p)
                                     for p in eqn.params["perm"]))))
        elif name in _REDUCING:
            axes = eqn.params.get("axes",
                                  eqn.params.get("axis_name", ()))
            sig.append((name, _axes_tuple(axes)))
        else:
            for s in _sub_jaxprs_of(eqn):
                sig.extend(_collective_signature(s))
    return tuple(sig)


class _Dataflow:
    """Forward varying-axes analysis over one shard_map body."""

    def __init__(self, mesh_axes, report302):
        self.mesh_axes = frozenset(mesh_axes)
        self.report302 = report302

    def run(self, j, in_varying):
        """Analyze open-or-closed jaxpr ``j`` whose invars carry
        ``in_varying``; returns the outvars' varying sets."""
        import jax.core as jcore

        jaxpr = _inner(j)
        env = {}

        def V(atom):
            if isinstance(atom, jcore.Literal):
                return frozenset()
            return env.get(id(atom), frozenset())

        def setv(var, v):
            env[id(var)] = frozenset(v)

        for var in getattr(jaxpr, "constvars", ()):
            setv(var, frozenset())
        for var, v in zip(jaxpr.invars, in_varying):
            setv(var, v)

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            invs = [V(v) for v in eqn.invars]
            union = frozenset().union(*invs) if invs else frozenset()
            if name == "axis_index":
                outs = [frozenset(
                    _axes_tuple(eqn.params["axis_name"]))]
            elif name == "ppermute":
                outs = [union | frozenset(
                    _axes_tuple(eqn.params["axis_name"]))]
            elif name in _REDUCING:
                axes = eqn.params.get("axes",
                                      eqn.params.get("axis_name", ()))
                named = frozenset(a for a in _axes_tuple(axes)
                                  if isinstance(a, str))
                if eqn.params.get("axis_index_groups") is not None:
                    outs = [union]  # grouped reduce: stay conservative
                else:
                    outs = [union - named]
            elif name == "cond":
                outs = self._cond(eqn, invs)
            elif name == "while":
                outs = self._while(eqn, invs)
            elif name == "scan":
                outs = self._scan(eqn, invs)
            elif name in _CALL_PRIMS:
                outs = self._call(eqn, invs, union)
            else:
                # First-order primitives and unknown higher-order ones
                # (pallas_call, custom lowerings) alike: conservative —
                # outputs vary wherever any input does.
                outs = [union] * len(eqn.outvars)
            for var, v in zip(eqn.outvars, outs):
                setv(var, v)
        return [V(v) for v in jaxpr.outvars]

    # -- higher-order primitives ------------------------------------

    def _call(self, eqn, invs, union):
        subs = _sub_jaxprs_of(eqn)
        if len(subs) == 1:
            body = subs[0]
            jaxpr = _inner(body)
            nconsts = len(jaxpr.invars) - len(eqn.invars)
            if nconsts == 0:
                return self.run(body, invs)
            if nconsts > 0 and len(_consts_of(body)) == nconsts:
                consts = [frozenset()] * nconsts
                return self.run(body, consts + invs)
        return [union] * len(eqn.outvars)

    def _cond(self, eqn, invs):
        pred_v = invs[0]
        ops = invs[1:]
        branches = eqn.params["branches"]
        sigs = [_collective_signature(b) for b in branches]
        if len(set(sigs)) > 1 and pred_v:
            self.report302(
                f"lax.cond branches perform DIFFERENT collective "
                f"sequences ({[len(s) for s in sigs]} collectives per "
                f"branch) and the predicate varies across mesh axes "
                f"{sorted(pred_v)} — devices would take different "
                f"branches and the collectives inside would wait on "
                f"peers that never arrive (SPMD hang); reduce the "
                f"predicate (pmax/psum over all axes) before "
                f"branching, or make the branches' collectives "
                f"identical")
        outs = None
        for b in branches:
            ov = self.run(b, ops)
            outs = (ov if outs is None
                    else [a | c for a, c in zip(outs, ov)])
        return [o | pred_v for o in outs]

    def _while(self, eqn, invs):
        cond_j = eqn.params["cond_jaxpr"]
        body_j = eqn.params["body_jaxpr"]
        ncc = eqn.params["cond_nconsts"]
        nbc = eqn.params["body_nconsts"]
        cond_c = invs[:ncc]
        body_c = invs[ncc:ncc + nbc]
        carry = list(invs[ncc + nbc:])
        # Iterate to a fixpoint: variance can flow through a CHAIN of
        # carries (a <- axis_index, b <- a, c <- b needs one pass per
        # link), so any iteration cap under-approximates. Union on the
        # finite axis lattice is monotone, so this terminates.
        while True:
            new = self.run(body_j, body_c + carry)
            merged = [a | b for a, b in zip(carry, new)]
            if merged == carry:
                break
            carry = merged
        pred_v = self.run(cond_j, cond_c + carry)[0]
        body_sig = _collective_signature(body_j)
        if body_sig and pred_v:
            self.report302(
                f"lax.while_loop body performs {len(body_sig)} "
                f"collective(s) but its predicate varies across mesh "
                f"axes {sorted(pred_v)} — devices would exit the loop "
                f"at different iterations while neighbors still wait "
                f"in the body's collectives (the converge loop avoids "
                f"this by pmax-reducing the residual before the "
                f"check)")
        return [c | pred_v for c in carry]

    def _scan(self, eqn, invs):
        body = eqn.params["jaxpr"]
        nc = eqn.params["num_consts"]
        ncar = eqn.params["num_carry"]
        consts = invs[:nc]
        carry = list(invs[nc:nc + ncar])
        xs = invs[nc + ncar:]
        n_out = len(eqn.outvars)
        ys = [frozenset()] * (n_out - ncar)
        while True:
            out = self.run(body, consts + carry + xs)
            new_carry = [a | b for a, b in zip(carry, out[:ncar])]
            ys = [a | b for a, b in zip(ys, out[ncar:])]
            if new_carry == carry:
                break
            carry = new_carry
        return carry + ys


# ---------------------------------------------------------------------------
# audit drivers
# ---------------------------------------------------------------------------

def _audit_traced(traced, skips) -> List[Finding]:
    out = list(skips)
    seen = set()

    def report(rule, label, message, severity="error"):
        key = (rule, label, message)
        if key not in seen:
            seen.add(key)
            out.append(Finding(rule, severity, _LOC, 0, label, message))

    families = {}
    n_shard_maps = 0
    n_ppermutes = 0
    for target, closed in traced:
        tables_all = set()
        for sm in _find_shard_maps(closed):
            n_shard_maps += 1
            names, sizes = _mesh_info(sm)
            body = sm.params["jaxpr"]
            # HL301 over every ppermute under this shard_map.
            tables = _audit_ppermutes_under(
                body, sizes,
                lambda m, lb=target.label: report("HL301", lb, m))
            n_ppermutes += len(tables)
            tables_all |= {(a, p) for a, p in tables}
            # HL302/HL303 via the varying-axes dataflow.
            in_names = sm.params["in_names"]
            out_names = sm.params["out_names"]
            flow = _Dataflow(
                names,
                lambda m, lb=target.label: report("HL302", lb, m))
            in_varying = [_names_axes(e) for e in in_names]
            jaxpr = _inner(body)
            if len(in_varying) != len(jaxpr.invars):
                report("HL303", target.label,
                       f"shard_map body arity mismatch "
                       f"({len(in_varying)} specs vs "
                       f"{len(jaxpr.invars)} invars) — replication "
                       f"unprovable")
                continue
            out_varying = flow.run(body, in_varying)
            for k, (spec, v) in enumerate(zip(out_names, out_varying)):
                allowed = _names_axes(spec)
                extra = v - allowed
                if extra:
                    report(
                        "HL303", target.label,
                        f"shard_map output {k} is declared "
                        f"{'replicated' if not allowed else f'sharded only over {sorted(allowed)}'} "
                        f"by its out_spec but provably varies over "
                        f"mesh axes {sorted(extra)} — the value "
                        f"feeds host control flow / GSPMD as if "
                        f"identical on every device, so programs "
                        f"desynchronize; reduce it (pmax/psum over "
                        f"{sorted(extra)}) inside the shard_map body "
                        f"(utils/compat.py runs check_rep=False on "
                        f"pre-vma jax, so ONLY this static proof "
                        f"checks it)")
        families.setdefault(target.family, {})[target.variant] = (
            target.label, frozenset(tables_all))

    # HL302 cross-variant: the exchange-table set is a function of the
    # geometry family, not of the stepping mode.
    for family, variants in families.items():
        if len(variants) < 2:
            continue
        ref_variant, (ref_label, ref_tables) = next(
            iter(sorted(variants.items())))
        for variant, (label, tables) in sorted(variants.items()):
            if tables != ref_tables:
                only_a = {f"{a}:{sorted(p)}" for a, p in
                          (ref_tables - tables)}
                only_b = {f"{a}:{sorted(p)}" for a, p in
                          (tables - ref_tables)}
                report(
                    "HL302", label,
                    f"program variant {variant!r} exchanges different "
                    f"halo tables than variant {ref_variant!r} of the "
                    f"same geometry family {family!r} (only in "
                    f"{ref_variant}: {sorted(only_a) or '{}'}; only "
                    f"in {variant}: {sorted(only_b) or '{}'}) — "
                    f"variants must share one exchange protocol or a "
                    f"mixed deployment hangs")
    return out, n_shard_maps, n_ppermutes


def audit_spmd(targets=None) -> List[Finding]:
    """Run HL301+HL302+HL303 over ``targets`` (default: the real
    solver programs across the audit mesh matrix). One traversal
    serves all three rules."""
    traced, skips = _traced(targets)
    out, n_sm, n_pp = _audit_traced(traced, skips)
    if targets is None and (n_sm == 0 or n_pp == 0):
        out.append(Finding(
            "HL301", "error", _LOC, 0, "audit_spmd",
            f"vacuous audit: found {n_sm} shard_map(s) and {n_pp} "
            f"ppermute table(s) in the default target matrix — the "
            f"solver's sharded programs no longer trace the way the "
            f"audit expects; fix the target matrix before trusting a "
            f"clean result", soundness=True))
    return out


def _rule_runner(rule_id):
    def run():
        return run_spmd({rule_id})

    return run


SPMD_RULES = {
    "HL301": ("error", "halo ppermute table breaks the exchange protocol",
              _rule_runner("HL301")),
    "HL302": ("error", "collective sequences diverge across branches/variants",
              _rule_runner("HL302")),
    "HL303": ("error", "shard_map output not provably replicated",
              _rule_runner("HL303")),
}


def run_spmd(rules=None) -> List[Finding]:
    """Run the SPMD-layer audits against the installed package.

    Unlike ``run_contracts``, the three rules share one traced target
    set, so this runs the audit once and filters. Soundness sentinels
    (skipped meshes, a vacuous target matrix) survive any rule filter —
    they mean the proof did not actually run."""
    wanted = set(SPMD_RULES) if rules is None else set(rules)
    return [f for f in audit_spmd() if f.rule in wanted or f.soundness]
