"""Layer 4: Pallas kernel-safety verifiers (``HL4xx``).

The kernel family (A/B/C/D/E/F/G/H/I + -uni/-fuse/-band variants, 17
``pallas_call`` sites in ``ops/pallas_stencil.py``) hand-manages DMA
windows, VMEM scratch, and double-buffer semaphores. Until now the only
enforcement was dynamic: hw_validate parity runs on real hardware.
These rules verify the same discipline *statically*: each builder is
instantiated at a representative geometry, traced with
``jax.make_jaxpr`` (abstract evaluation — no kernel executes), and the
``pallas_call`` eqn's ``grid_mapping``, block specs and kernel jaxpr
are analyzed directly:

- **HL401 dma-in-bounds** — every async-copy window is proven inside
  its source and destination refs. The kernel jaxpr's scalar index
  arithmetic (``program_id``, clamps, ``pl.multiple_of``, prefetched
  offsets) is evaluated concretely for EVERY grid instance, so the
  clamped edge windows and the steady-state prefetch windows are both
  checked exactly — including the E-uni/I-uni fixed-shape gather
  bands, whose conditional edge branches resolve per instance. A
  window whose start the evaluator cannot resolve is reported as
  unprovable (the contract demands provability, not plausibility).
- **HL402 vmem-budget** — the kernel's static VMEM footprint (grid-
  mapped VMEM blocks double-buffered by the Mosaic pipeline, plus all
  VMEM scratch) must fit ``TpuParams.vmem_limit_bytes``, so a
  geometry the pickers admit can never be one XLA rejects at run
  time with a scoped-vmem OOM.
- **HL403 dma-discipline** — the per-instance DMA schedule (the TPU
  grid is sequential) is simulated over counting semaphores: a wait
  with no outstanding copy (a hang), a copy started but never waited
  (a leak past the kernel's end), and a copy started into a window
  overlapping an outstanding copy's destination (double-buffer slot
  reuse while in flight) are all errors.
- **HL404 grid-coverage** — for every grid-blocked ref, the block
  shape divides the array shape (the same exact-tiling discipline
  ``config.divisible_factorizations`` pins at the mesh level), the
  index map stays in range for every grid instance, and each OUTPUT
  ref's blocks are fully covered — an uncovered output block is
  silently-uninitialized VMEM leaving the kernel.

The default target matrix instantiates every builder; the audit then
cross-checks coverage against the ``name="heat_*"`` literals in
``ops/pallas_stencil.py`` (the same literals rule HL203 enforces), so
an 18th kernel site cannot land without either an audit target or a
justified baseline entry. All audits accept injected targets so test
fixtures can seed violations.
"""

from __future__ import annotations

import functools
import itertools
from typing import List, Optional

from parallel_heat_tpu.analysis.findings import Finding

_LOC = "parallel_heat_tpu/ops/pallas_stencil.py"

# Refuse to "prove" anything by exhaustion past this many grid
# instances — the audit geometries are chosen small; a blow-up here
# means the target matrix regressed, not that the kernel is fine.
_MAX_INSTANCES = 4096


class _Unknown:
    __slots__ = ()

    def __repr__(self):
        return "<unknown>"


UNKNOWN = _Unknown()


class _Ref:
    """Concrete handle for a memref kernel operand."""

    __slots__ = ("rid", "shape", "space", "itemsize", "value")

    def __init__(self, rid, aval, value=None):
        import numpy as np

        self.rid = rid
        self.shape = tuple(aval.shape)
        self.space = str(getattr(aval, "memory_space", "vmem"))
        try:
            self.itemsize = np.dtype(aval.dtype).itemsize
        except TypeError:
            # Extended dtypes (semaphore refs trace as 'dma_sem' when
            # the builder bypasses the prefetch grid-spec path) carry
            # no numpy itemsize; they never participate in byte math.
            self.itemsize = getattr(aval.dtype, "itemsize", 0) or 0
        self.value = value  # concrete np array for prefetch operands


class KernelTarget:
    """One traceable kernel invocation: ``fn(*args_sds)`` traced with
    ``make_jaxpr``; ``prefetch`` supplies concrete values for the
    pallas scalar-prefetch operands (audit-chosen offsets — the DMA
    schedule must not depend on them, and the evaluator reports any
    window that does as unprovable unless it resolves)."""

    def __init__(self, label, fn, args_sds, prefetch=None):
        self.label = label
        self.fn = fn
        self.args_sds = args_sds
        self.prefetch = prefetch


# ---------------------------------------------------------------------------
# Target matrix: every builder at a representative geometry
# ---------------------------------------------------------------------------

def default_kernel_targets() -> List[KernelTarget]:
    import jax
    import numpy as np

    from parallel_heat_tpu.ops import pallas_stencil as ps

    f32 = "float32"

    def sds(shape, dt=f32):
        return jax.ShapeDtypeStruct(shape, dt)

    offs2 = np.zeros(2, np.int32)
    offs3 = np.zeros(3, np.int32)
    T: List[KernelTarget] = []

    def add(label, fn, args, prefetch=None):
        if fn is None:
            raise RuntimeError(
                f"kernel audit target {label!r} declined to build — "
                f"the representative geometry regressed; fix "
                f"default_kernel_targets before trusting the audit")
        T.append(KernelTarget(label, fn, args, prefetch))

    # Kernel A — VMEM-resident multi-step (no DMA engine use).
    add("A", ps._build_vmem_multistep((24, 36), f32, 0.1, 0.1, 4),
        [sds((24, 36))])

    # Kernel M — member-batched VMEM-resident multi-step (the ensemble
    # engine's hot path; grid iterates the member axis).
    from parallel_heat_tpu.ops import batched as bt

    add("M", bt._build_ensemble_vmem_multistep(3, (24, 36), f32,
                                               0.1, 0.1, 4),
        [sds((3, 24, 36))])

    # Kernel B — streaming strip, unsharded (clamped windows) and
    # sharded (extended input rows).
    fnB, subB = ps._build_strip_kernel((64, 64), f32, 0.1, 0.1,
                                       (64, 64), False)
    add("B", lambda u, f=fnB: f(u, 0, 0), [sds((64, 64))], offs2)
    fnBs, subBs = ps._build_strip_kernel((32, 64), f32, 0.1, 0.1,
                                         (64, 128), True)
    add("B-sharded", lambda u, f=fnBs: f(u, 0, 0),
        [sds((32 + 2 * subBs, 64))], offs2)

    # Kernel C — 2D-tiled streaming (both axes windowed).
    fnC, _ = ps._build_tiled_kernel((32, 2048), f32, 0.1, 0.1,
                                    (32, 2048), False)
    add("C", lambda u, f=fnC: f(u, 0, 0), [sds((32, 2048))], offs2)

    # Kernel E — temporal strip, storage and f32chunk accumulation.
    add("E", ps._build_temporal_strip((64, 64), f32, 0.1, 0.1, 8),
        [sds((64, 64))])
    add("E-acc", ps._build_temporal_strip((64, 64), "bfloat16",
                                          0.1, 0.1, 16, acc_f32=True),
        [sds((64, 64), "bfloat16")])
    # Kernel E-uni — uniform-window gather (>= 3 strips).
    add("E-uni", ps._build_temporal_strip_uniform((64, 64), f32,
                                                  0.1, 0.1, 8),
        [sds((64, 64))])

    # Kernel I / I-uni — 2D-tiled temporal.
    add("I", ps._build_tile_temporal_2d((64, 256), f32, 0.1, 0.1, 8),
        [sds((64, 256))])
    add("I-uni", ps._build_tile_temporal_2d_uniform((64, 256), f32,
                                                    0.1, 0.1, 8),
        [sds((64, 256))])

    # Kernel G family — shard-block temporal; shapes follow
    # parallel/temporal.py's exchange assembly exactly.
    bx, by, K = 16, 16, 8
    gargs = ((bx, by), f32, 0.1, 0.1, (32, 32), K)
    g = ps._build_temporal_block(*gargs)
    add("G", lambda ext, f=g: f(ext, 0, 0),
        [sds((bx + 2 * K, g.padded_width))], offs2)
    gc = ps._build_temporal_block_circular(*gargs)
    add("G-circ", lambda ext, f=gc: f(ext, 0, 0),
        [sds((bx + 2 * K, by + gc.tail))], offs2)
    gf = ps._build_temporal_block_fused(*gargs)
    fuse_args = [sds((bx, by)), sds((bx, gf.tail)),
                 sds((K, by + gf.tail)), sds((K, by + gf.tail))]
    add("G-fuse", lambda u, t, hn, hs, f=gf: f(u, t, hn, hs, 0, 0),
        fuse_args, offs2)
    gu = ps._build_temporal_block_uniform(*gargs)
    add("G-uni", lambda u, t, hn, hs, f=gu: f(u, t, hn, hs, 0, 0),
        fuse_args, offs2)
    gud = ps._build_temporal_block_uniform(*gargs, defer_ns=True)
    add("G-uni-defer", lambda u, t, f=gud: f(u, t, 0, 0),
        fuse_args[:2], offs2)
    gb = ps._build_band_fix_2d(*gargs, ("x", "y"))
    add("G-band", lambda u, t, hn, hs, f=gb: f(u, t, hn, hs, 0, 0),
        fuse_args, offs2)

    # Multigrid transfer kernels — whole-array VMEM restriction /
    # prolongation of the implicit V-cycle (ops/multigrid.py). The
    # geometry is one real hierarchy edge: fine (34, 34) -> coarse
    # (18, 18) (config.multigrid_level_shapes((34, 34))[1]).
    from parallel_heat_tpu.ops import multigrid as mgrid

    add("MG-restrict", mgrid._build_restrict_kernel((34, 34), (18, 18)),
        [sds((34, 34))])
    add("MG-prolong", mgrid._build_prolong_kernel((18, 18), (34, 34)),
        [sds((18, 18))])

    # Kernel D — XY-tiled 3D slab.
    add("D", ps._build_slab_kernel_3d((16, 32, 128), f32,
                                      0.1, 0.1, 0.1),
        [sds((16, 32, 128))])
    # Kernel F — X-slab temporal 3D.
    add("F", ps._build_xslab_3d((32, 16, 128), f32, 0.1, 0.1, 0.1,
                                8, 3),
        [sds((32, 16, 128))])

    # Kernel H family — 3D shard-block temporal; shapes follow
    # temporal.exchange_halos_{circular,fused}_3d.
    blocks, K3, halos = (8, 8, 8), 2, (2, 2, 2)
    hargs = (blocks, f32, 0.1, 0.1, 0.1, (16, 16, 16), K3, halos,
             ("x", "y", "z"))
    h = ps._build_temporal_block_3d(*hargs)
    bx3, by3, bz3 = blocks
    ext3 = (bx3 + 2 * K3, by3 + h.tail_y, bz3 + h.tail_z)
    add("H", lambda ext, f=h: f(ext, 0, 0, 0), [sds(ext3)], offs3)
    hf = ps._build_temporal_block_3d_fused(*hargs)
    ze, ye = bz3 + hf.tail_z, by3 + hf.tail_y
    h_ops = [sds(blocks), sds((bx3, by3, hf.tail_z)),
             sds((bx3, hf.tail_y, ze)), sds((K3, ye, ze)),
             sds((K3, ye, ze))]
    add("H-fuse",
        lambda u, zt, yt, xl, xh, f=hf: f(u, zt, yt, xl, xh, 0, 0, 0),
        h_ops, offs3)
    hb = ps._build_band_fix_3d(*hargs)
    add("H-band",
        lambda u, zt, yt, xl, xh, f=hb: f(u, zt, yt, xl, xh, 0, 0, 0),
        h_ops, offs3)
    return T


@functools.lru_cache(maxsize=1)
def _traced_default():
    return _trace_targets(tuple(default_kernel_targets()))


def _trace_targets(targets):
    import jax

    traced = []
    for t in targets:
        closed = jax.make_jaxpr(t.fn)(*t.args_sds)
        for eqn in _find_pallas_calls(closed):
            traced.append((t, eqn))
    return traced


def _traced(targets):
    if targets is None:
        return _traced_default()
    return _trace_targets(tuple(targets))


def _find_pallas_calls(closed):
    from parallel_heat_tpu.analysis.contracts import _sub_jaxprs

    stack = [closed]
    seen = set()
    while stack:
        item = stack.pop()
        j = getattr(item, "jaxpr", item)
        for eqn in j.eqns:
            if eqn.primitive.name == "pallas_call":
                if id(eqn) not in seen:
                    seen.add(id(eqn))
                    yield eqn
            for s in _sub_jaxprs(eqn.params):
                stack.append(s)


def _call_name(eqn) -> str:
    nsi = eqn.params.get("name_and_src_info")
    return getattr(nsi, "name", None) or str(nsi)


def _space_of(aval) -> str:
    """Lowercased memory-space tag of a block/scratch aval. An
    unspecified space is Mosaic's default — a grid-blocked VMEM
    buffer — so it must count as ``vmem`` (skipping it silently
    exempted default-space blocks from the budget and coverage
    audits)."""
    sp = getattr(aval, "memory_space", None)
    if sp is None:
        return "vmem"
    return str(sp).lower()


# ---------------------------------------------------------------------------
# Concrete per-instance evaluator
# ---------------------------------------------------------------------------

def _is_ndindexer(obj) -> bool:
    return (hasattr(obj, "indices") and hasattr(obj, "shape")
            and type(obj).__name__ == "NDIndexer")


def _is_slice(obj) -> bool:
    return (hasattr(obj, "start") and hasattr(obj, "size")
            and type(obj).__name__ == "Slice")


class _DmaEvent:
    __slots__ = ("kind", "sem_key", "src", "src_win", "dst", "dst_win",
                 "where")

    def __init__(self, kind, sem_key, src, src_win, dst, dst_win,
                 where):
        self.kind = kind
        self.sem_key = sem_key
        self.src = src
        self.src_win = src_win
        self.dst = dst
        self.dst_win = dst_win
        self.where = where

    def descriptor(self):
        return (self.src.rid if self.src else None, self.src_win,
                self.dst.rid if self.dst else None, self.dst_win)


def _has_dma(j) -> bool:
    from parallel_heat_tpu.analysis.contracts import _sub_jaxprs

    jaxpr = getattr(j, "jaxpr", j)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in ("dma_start", "dma_wait"):
            return True
        for s in _sub_jaxprs(eqn.params):
            if _has_dma(s):
                return True
    return False


class _KernelEval:
    """Concretely evaluate one kernel jaxpr's scalar/index slice for a
    single grid instance, recording DMA events."""

    def __init__(self, grid, instance, report, events):
        self.grid = tuple(grid)
        self.instance = tuple(instance)
        self.report = report
        self.events = events

    # -- value resolution ------------------------------------------

    def _val(self, env, atom):
        import jax.core as jcore

        if isinstance(atom, jcore.Literal):
            return atom.val
        return env.get(id(atom), UNKNOWN)

    def _resolve_index(self, env, x):
        """An indexer leaf to a concrete int, or UNKNOWN."""
        import numpy as np

        if isinstance(x, (int, np.integer)):
            return int(x)
        v = self._val(env, x) if hasattr(x, "aval") else UNKNOWN
        if isinstance(v, _Unknown):
            return UNKNOWN
        try:
            return int(v)
        except (TypeError, ValueError):
            return UNKNOWN

    def _resolve_indexer(self, env, nd):
        """NDIndexer -> list of (start, size, stride) / int entries,
        or UNKNOWN."""
        out = []
        for idx in nd.indices:
            if _is_slice(idx):
                start = self._resolve_index(env, idx.start)
                if isinstance(start, _Unknown):
                    return UNKNOWN
                out.append((start, int(idx.size), int(idx.stride)))
            else:
                i = self._resolve_index(env, idx)
                if isinstance(i, _Unknown):
                    return UNKNOWN
                out.append(i)
        return out

    # -- the interpreter -------------------------------------------

    def run(self, j, args):
        """Evaluate open-or-closed jaxpr ``j`` with ``args`` (values,
        _Refs, or UNKNOWN); returns outvar values."""
        import numpy as np
        import jax.core as jcore

        jaxpr = getattr(j, "jaxpr", j)
        env = {}
        consts = getattr(j, "consts", ())
        for var, c in zip(getattr(jaxpr, "constvars", ()), consts):
            env[id(var)] = c if np.ndim(c) == 0 else UNKNOWN
        for var in getattr(jaxpr, "constvars", ())[len(consts):]:
            env[id(var)] = UNKNOWN
        if len(args) != len(jaxpr.invars):
            return [UNKNOWN] * len(jaxpr.outvars)
        for var, a in zip(jaxpr.invars, args):
            env[id(var)] = a

        for eqn in jaxpr.eqns:
            outs = self._eval_eqn(env, eqn)
            for var, v in zip(eqn.outvars, outs):
                env[id(var)] = v
        return [self._val(env, v) for v in jaxpr.outvars]

    def _eval_eqn(self, env, eqn):
        import numpy as np

        name = eqn.primitive.name
        n_out = len(eqn.outvars)
        unk = [UNKNOWN] * n_out
        vals = [self._val(env, v) for v in eqn.invars]

        def scalars():
            out = []
            for v in vals:
                if isinstance(v, (_Unknown, _Ref)):
                    return None
                if np.ndim(v) != 0:
                    return None
                out.append(v)
            return out

        if name == "program_id":
            return [self.instance[eqn.params["axis"]]]
        if name == "num_programs":
            return [self.grid[eqn.params["axis"]]]
        if name == "multiple_of":
            return [vals[0]]
        if name in ("dma_start", "dma_wait"):
            self._dma(env, eqn, name)
            return unk
        if name == "get":
            return [self._get(env, eqn, vals)]
        if name == "cond":
            return self._cond(env, eqn, vals)
        if name in ("pjit", "closed_call", "core_call", "named_call",
                    "custom_jvp_call", "custom_vjp_call", "remat",
                    "remat2", "checkpoint"):
            return self._call(env, eqn, vals)
        if name in ("scan", "while"):
            from parallel_heat_tpu.analysis.contracts import _sub_jaxprs

            for s in _sub_jaxprs(eqn.params):
                if _has_dma(s):
                    self.report(
                        "HL403",
                        f"async copy inside a {name} loop — the DMA "
                        f"schedule is not statically enumerable (the "
                        f"kernel family keeps copies in straight-line "
                        f"per-instance code; extend the audit before "
                        f"shipping a looped schedule)",
                        soundness=True)
            return unk
        sc = scalars()
        if sc is None:
            return unk
        return self._scalar_prim(name, eqn, sc, unk)

    def _scalar_prim(self, name, eqn, sc, unk):
        import numpy as np

        try:
            if name == "add":
                return [sc[0] + sc[1]]
            if name == "sub":
                return [sc[0] - sc[1]]
            if name == "mul":
                return [sc[0] * sc[1]]
            if name == "div":
                a, b = sc
                if isinstance(a, (int, np.integer)) and isinstance(
                        b, (int, np.integer)):
                    q = abs(int(a)) // abs(int(b))
                    return [q if (a >= 0) == (b >= 0) else -q]
                return [a / b]
            if name == "rem":
                a, b = int(sc[0]), int(sc[1])
                r = abs(a) % abs(b)
                return [r if a >= 0 else -r]
            if name == "max":
                return [max(sc[0], sc[1])]
            if name == "min":
                return [min(sc[0], sc[1])]
            if name == "clamp":
                lo, x, hi = sc
                return [min(max(x, lo), hi)]
            if name == "neg":
                return [-sc[0]]
            if name == "sign":
                return [(sc[0] > 0) - (sc[0] < 0)]
            if name == "abs":
                return [abs(sc[0])]
            if name in ("eq", "ne", "lt", "le", "gt", "ge"):
                a, b = sc
                return [{"eq": a == b, "ne": a != b, "lt": a < b,
                         "le": a <= b, "gt": a > b, "ge": a >= b}[name]]
            if name in ("and", "or", "xor"):
                # lax's and/or/xor are BITWISE; boolean shortcutting
                # over ints would e.g. turn 2 & 1 == 0 into True and
                # "prove" a DMA window at the wrong offset.
                a, b = sc
                if isinstance(a, (bool, np.bool_)) and isinstance(
                        b, (bool, np.bool_)):
                    return [{"and": a and b, "or": a or b,
                             "xor": bool(a) != bool(b)}[name]]
                if isinstance(a, (int, np.integer)) and isinstance(
                        b, (int, np.integer)):
                    return [{"and": int(a) & int(b),
                             "or": int(a) | int(b),
                             "xor": int(a) ^ int(b)}[name]]
                return unk
            if name == "not":
                x = sc[0]
                if isinstance(x, (bool, np.bool_)):
                    return [not x]
                if isinstance(x, (int, np.integer)):
                    return [~int(x)]  # lax.not_ on ints is bitwise
                return unk
            if name == "select_n":
                idx = int(sc[0])
                return [sc[1 + idx]]
            if name == "convert_element_type":
                dt = np.dtype(eqn.params["new_dtype"])
                if dt.kind in "iu":
                    return [int(sc[0])]
                if dt.kind == "b":
                    return [bool(sc[0])]
                if dt.kind == "f":
                    return [float(sc[0])]
            if name in ("broadcast_in_dim", "reshape", "squeeze",
                        "stop_gradient", "copy"):
                # Value-preserving only while the result stays a
                # single element — a real broadcast is an array the
                # scalar evaluator must not impersonate.
                shape = eqn.params.get("shape",
                                       eqn.params.get("new_sizes", ()))
                n = 1
                for d in shape or ():
                    n *= int(d)
                if n == 1:
                    return [sc[0]]
                return unk
        except (TypeError, ValueError, ZeroDivisionError,
                OverflowError):
            return unk
        return unk

    def _get(self, env, eqn, vals):
        import numpy as np

        ref = vals[0]
        if not isinstance(ref, _Ref) or ref.value is None:
            return UNKNOWN
        tree = eqn.params.get("tree")
        if tree is None:
            return UNKNOWN
        from jax import tree_util

        # get invars = [ref] + dynamic indexer leaves; the tree covers
        # only the transforms.
        transforms = tree_util.tree_unflatten(tree, eqn.invars[1:])
        # transforms: a tuple of NDIndexer chains; apply to the value.
        try:
            val = np.asarray(ref.value)
            for nd in transforms:
                if not _is_ndindexer(nd):
                    return UNKNOWN
                resolved = self._resolve_indexer(env, nd)
                if isinstance(resolved, _Unknown):
                    return UNKNOWN
                sl = tuple(
                    (slice(r[0], r[0] + r[1] * r[2], r[2])
                     if isinstance(r, tuple) else r)
                    for r in resolved)
                val = val[sl]
            if np.ndim(val) == 0:
                return val.item() if hasattr(val, "item") else val
            return UNKNOWN
        except (IndexError, TypeError, ValueError):
            return UNKNOWN

    def _cond(self, env, eqn, vals):
        pred = vals[0]
        branches = eqn.params["branches"]
        n_out = len(eqn.outvars)
        if isinstance(pred, _Unknown):
            from parallel_heat_tpu.analysis.contracts import _sub_jaxprs

            for s in _sub_jaxprs(eqn.params):
                if _has_dma(s):
                    self.report(
                        "HL401",
                        "async copy under a branch whose predicate "
                        "the static evaluator cannot resolve — the "
                        "DMA schedule is unprovable (branch "
                        "predicates must be functions of program_id/"
                        "num_programs/constants)",
                        soundness=True)
                    break
            return [UNKNOWN] * n_out
        idx = int(pred)
        idx = max(0, min(len(branches) - 1, idx))
        return self.run(branches[idx], vals[1:])

    def _call(self, env, eqn, vals):
        from parallel_heat_tpu.analysis.contracts import _sub_jaxprs

        subs = list(_sub_jaxprs(eqn.params))
        n_out = len(eqn.outvars)
        if len(subs) != 1:
            for s in subs:
                if _has_dma(s):
                    self.report(
                        "HL401",
                        f"async copy under unsupported call primitive "
                        f"{eqn.primitive.name!r} — schedule unprovable",
                        soundness=True)
            return [UNKNOWN] * n_out
        body = subs[0]
        jaxpr = getattr(body, "jaxpr", body)
        if len(jaxpr.invars) == len(vals):
            return self.run(body, vals)
        if _has_dma(body):
            self.report(
                "HL401",
                f"async copy under {eqn.primitive.name!r} with "
                f"mismatched arity — schedule unprovable",
                soundness=True)
        return [UNKNOWN] * n_out

    def _dma(self, env, eqn, kind):
        from jax import tree_util

        tree = eqn.params["tree"]
        st = tree_util.tree_unflatten(tree, eqn.invars)
        # Layout (pallas mosaic primitives): (src_ref, src_transforms,
        # dst_ref, dst_transforms, sem_ref, sem_transforms, ...remote).
        if len(st) < 6:
            self.report("HL401", f"{kind}: unrecognized copy "
                                 f"descriptor layout — unprovable",
                        soundness=True)
            return
        src = self._val(env, st[0])
        dst = self._val(env, st[2])
        sem = self._val(env, st[4])
        if not (isinstance(src, _Ref) and isinstance(dst, _Ref)
                and isinstance(sem, _Ref)):
            self.report("HL401", f"{kind}: copy endpoints are not "
                                 f"statically-known refs — unprovable",
                        soundness=True)
            return
        src_win = self._window(env, st[1], src, "source")
        dst_win = self._window(env, st[3], dst, "destination")
        sem_idx = self._window(env, st[5], sem, "semaphore")
        if sem_idx is None:
            return
        sem_key = (sem.rid, tuple(sem_idx))
        self.events.append(_DmaEvent(
            "start" if kind == "dma_start" else "wait",
            sem_key, src, src_win, dst, dst_win,
            f"instance {self.instance}"))

    def _window(self, env, transforms, ref, what):
        """Resolve one endpoint's indexer chain; bounds-check against
        the ref shape (rule HL401). Returns the resolved entries or
        None when unprovable (already reported)."""
        if not isinstance(transforms, (tuple, list)):
            transforms = (transforms,)
        transforms = [t for t in transforms if t is not None]
        if len(transforms) == 0:
            return tuple((0, d, 1) for d in ref.shape)
        if len(transforms) != 1 or not _is_ndindexer(transforms[0]):
            self.report("HL401",
                        f"chained/unrecognized indexer on a copy "
                        f"{what} — window unprovable",
                        soundness=True)
            return None
        nd = transforms[0]
        resolved = self._resolve_indexer(env, nd)
        if isinstance(resolved, _Unknown):
            self.report(
                "HL401",
                f"copy {what} window start is not statically "
                f"derivable from program_id/constants/prefetch — "
                f"in-bounds is unprovable (ref shape {ref.shape})",
                soundness=True)
            return None
        shape = tuple(nd.shape)
        for d, (entry, dim) in enumerate(zip(resolved, shape)):
            if isinstance(entry, tuple):
                start, size, stride = entry
                last = start + (size - 1) * stride
                if start < 0 or last >= dim or size < 1:
                    self.report(
                        "HL401",
                        f"copy {what} window out of bounds: axis {d} "
                        f"reads [{start}, {last + 1}) of a {dim}-"
                        f"extent ref (shape {shape}) at "
                        f"{self.instance} — on hardware this DMA "
                        f"corrupts adjacent buffers silently")
                    return None
            else:
                if entry < 0 or entry >= dim:
                    self.report(
                        "HL401",
                        f"copy {what} index {entry} out of bounds on "
                        f"axis {d} of shape {shape}")
                    return None
        return tuple(resolved)


# ---------------------------------------------------------------------------
# Per-call audits
# ---------------------------------------------------------------------------

def _kernel_refs(eqn):
    """(refs, prefetch_slots) — _Ref handles for every kernel jaxpr
    invar, in operand order."""
    gm = eqn.params["grid_mapping"]
    jaxpr = eqn.params["jaxpr"]
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    refs = []
    for i, var in enumerate(jaxpr.invars):
        refs.append(_Ref(i, var.aval))
    return refs, gm.num_index_operands


def _grid_instances(grid, report) -> Optional[list]:
    grid = tuple(int(g) for g in grid)
    if not grid:
        return [()]
    total = 1
    for g in grid:
        total *= g
    if total > _MAX_INSTANCES:
        report("HL401",
               f"grid {grid} has {total} instances, past the audit's "
               f"{_MAX_INSTANCES}-instance exhaustion bound — pick a "
               f"smaller representative geometry for this target",
               soundness=True)
        return None
    return list(itertools.product(*(range(g) for g in grid)))


def _audit_schedule(target, eqn, report):
    """HL401 (in-bounds) + HL403 (semaphore discipline) for one
    pallas_call: evaluate every grid instance, then simulate."""
    import numpy as np

    gm = eqn.params["grid_mapping"]
    jaxpr = eqn.params["jaxpr"]
    refs, n_prefetch = _kernel_refs(eqn)
    if not _has_dma(jaxpr):
        return
    # Attach audit-chosen prefetch values.
    if n_prefetch:
        pf = target.prefetch
        if pf is not None:
            pf = np.atleast_1d(np.asarray(pf))
            for r in refs[:n_prefetch]:
                if pf.shape == r.shape:
                    r.value = pf
    instances = _grid_instances(gm.grid, report)
    if instances is None:
        return
    events = []
    for inst in instances:
        ev = _KernelEval(gm.grid, inst, report, events)
        ev.run(jaxpr, refs)
    # HL403: counting-semaphore simulation over the sequential grid.
    outstanding = {}
    for e in events:
        if e.kind == "start":
            for key, lst in outstanding.items():
                for o in lst:
                    if (e.dst is not None and o.dst is not None
                            and e.dst.rid == o.dst.rid
                            and _windows_overlap(e.dst_win, o.dst_win)):
                        report(
                            "HL403",
                            f"async copy started into destination "
                            f"window {e.dst_win} ({e.where}) while an "
                            f"un-waited copy into overlapping window "
                            f"{o.dst_win} ({o.where}) is still in "
                            f"flight on the same ref — double-buffer "
                            f"slot reused before its wait; the DMA "
                            f"engine may interleave both writes")
            outstanding.setdefault(e.sem_key, []).append(e)
        else:
            lst = outstanding.get(e.sem_key, [])
            if not lst:
                report(
                    "HL403",
                    f"async-copy wait at {e.where} on semaphore "
                    f"{e.sem_key[1]} with NO outstanding copy — the "
                    f"kernel would block forever on hardware (wait "
                    f"without a matching start)")
                continue
            match = next((i for i, o in enumerate(lst)
                          if o.descriptor() == e.descriptor()), 0)
            lst.pop(match)
    leaked = [(k, o) for k, lst in outstanding.items() for o in lst]
    for key, o in leaked:
        report(
            "HL403",
            f"async copy started at {o.where} (semaphore {key[1]}, "
            f"destination window {o.dst_win}) is never waited — the "
            f"copy outlives the kernel and its semaphore increment "
            f"leaks into the next kernel's waits")


def _windows_overlap(a, b) -> bool:
    if a is None or b is None:
        return True  # unprovable windows: assume the worst
    if len(a) != len(b):
        return True
    for ea, eb in zip(a, b):
        sa, la = ((ea[0], ea[0] + (ea[1] - 1) * ea[2] + 1)
                  if isinstance(ea, tuple) else (ea, ea + 1))
        sb, lb = ((eb[0], eb[0] + (eb[1] - 1) * eb[2] + 1)
                  if isinstance(eb, tuple) else (eb, eb + 1))
        if la <= sb or lb <= sa:
            return False
    return True


def _audit_vmem(target, eqn, report, limit_bytes):
    """HL402: static VMEM footprint vs the generation's limit."""
    import numpy as np

    gm = eqn.params["grid_mapping"]
    jaxpr = eqn.params["jaxpr"]
    jaxpr = getattr(jaxpr, "jaxpr", jaxpr)
    total = 0
    parts = []
    for bm in gm.block_mappings:
        aval = bm.transformed_block_aval
        if "vmem" not in _space_of(aval):
            continue
        bytes_ = int(np.prod(aval.shape)) * np.dtype(aval.dtype).itemsize
        # The Mosaic pipeline double-buffers every grid-mapped block.
        total += 2 * bytes_
        parts.append(f"2x{tuple(aval.shape)} block")
    n_scratch = gm.num_scratch_operands
    if n_scratch:
        for var in jaxpr.invars[len(jaxpr.invars) - n_scratch:]:
            aval = var.aval
            if "vmem" not in _space_of(aval):
                continue
            bytes_ = int(np.prod(aval.shape)) * \
                np.dtype(aval.dtype).itemsize
            total += bytes_
            parts.append(f"{tuple(aval.shape)} scratch")
    if total > limit_bytes:
        report(
            "HL402",
            f"static VMEM footprint {total} bytes "
            f"({' + '.join(parts)}) exceeds "
            f"TpuParams.vmem_limit_bytes={limit_bytes} — a geometry "
            f"the picker admits would be rejected by Mosaic with a "
            f"scoped-vmem OOM at compile time; shrink the block/"
            f"scratch model or fix the picker budget")


def _audit_grid_coverage(target, eqn, report):
    """HL404: divisibility, index-map range, output coverage."""
    gm = eqn.params["grid_mapping"]
    instances = _grid_instances(gm.grid, report)
    if instances is None:
        return
    n_in = gm.num_inputs
    n_out = gm.num_outputs
    refs, n_prefetch = _kernel_refs(eqn)
    prefetch_refs = refs[:n_prefetch]
    import numpy as np

    if n_prefetch and target.prefetch is not None:
        pf = np.atleast_1d(np.asarray(target.prefetch))
        for r in prefetch_refs:
            if pf.shape == r.shape:
                r.value = pf
    for k, bm in enumerate(gm.block_mappings):
        aval = bm.transformed_block_aval
        space = _space_of(aval)
        if "vmem" not in space and "smem" not in space:
            continue  # ANY-space refs are not grid-blocked
        block = tuple(1 if b is None else int(b)
                      for b in bm.block_shape)
        array = tuple(int(d) for d in bm.array_shape_dtype.shape)
        role = "output" if k >= n_in else "input"
        bad_div = [d for d, (b, a) in enumerate(zip(block, array))
                   if b and a % b != 0]
        if bad_div:
            report(
                "HL404",
                f"{role} block {block} does not divide ref shape "
                f"{array} on axis {bad_div[0]} — the kernel family's "
                f"exact-tiling contract (the BlockSpec analogue of "
                f"config.divisible_factorizations) requires whole "
                f"blocks; a ragged edge block reads/writes padding "
                f"Mosaic invents")
            continue
        nblocks = tuple(a // b if b else 1
                        for a, b in zip(array, block))
        seen_idx = set()
        unprovable = False
        for inst in instances:
            ev = _KernelEval(gm.grid, inst, report, [])
            idx = ev.run(bm.index_map_jaxpr,
                         list(inst) + list(prefetch_refs))
            if any(isinstance(i, _Unknown) for i in idx):
                report(
                    "HL404",
                    f"{role} block index map is not statically "
                    f"derivable from program_id/constants/prefetch at "
                    f"grid instance {inst} — range and coverage are "
                    f"unprovable (ref shape {array}, block {block})")
                unprovable = True
                break
            idx = tuple(int(i) for i in idx)
            for d, (i, nb) in enumerate(zip(idx, nblocks)):
                if not (0 <= i < nb):
                    report(
                        "HL404",
                        f"{role} index map returns block {idx} at "
                        f"grid instance {inst}, outside the "
                        f"{nblocks} blocks of ref shape {array} "
                        f"(block {block}) — the window would read/"
                        f"write past the ref")
                    unprovable = True
                    break
            if unprovable:
                break
            seen_idx.add(idx)
        if unprovable:
            continue
        if role == "output":
            missing = [i for i in itertools.product(
                *(range(nb) for nb in nblocks)) if i not in seen_idx]
            if missing:
                report(
                    "HL404",
                    f"output blocks {missing[:4]}"
                    f"{'...' if len(missing) > 4 else ''} of "
                    f"{nblocks} are never visited by the index map "
                    f"over grid {tuple(gm.grid)} — those output "
                    f"regions leave the kernel as uninitialized "
                    f"VMEM")


# ---------------------------------------------------------------------------
# Site coverage
# ---------------------------------------------------------------------------

def _source_kernel_names() -> dict:
    """{literal heat_* name: lineno} for every pallas_call site in the
    kernel modules — ops/pallas_stencil.py, ops/batched.py (the
    member-batched ensemble kernels) AND ops/multigrid.py (the
    implicit V-cycle's restriction/prolongation transfer kernels) —
    parsed with ast (the same literals HL203 enforces). A new kernel
    module must be added HERE for its sites to join the coverage
    cross-check; the pinning test
    (test_analysis.test_kernel_coverage_site_count) counts the total,
    so an uncounted extra site fails CI either way."""
    import ast

    from parallel_heat_tpu.ops import batched as bt
    from parallel_heat_tpu.ops import multigrid as mgrid
    from parallel_heat_tpu.ops import pallas_stencil as ps

    out = {}
    for mod in (ps, bt, mgrid):
        path = mod.__file__
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = getattr(node.func, "attr",
                            getattr(node.func, "id", None))
            if fname != "pallas_call":
                continue
            for kw in node.keywords:
                if kw.arg == "name" \
                        and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    out[kw.value.value] = node.lineno
    return out


# ---------------------------------------------------------------------------
# audit driver
# ---------------------------------------------------------------------------

def audit_kernels(targets=None, limit_bytes=None,
                  check_coverage=None) -> List[Finding]:
    """Run HL401-HL404 over ``targets`` (default: every builder at a
    representative geometry, with source-site coverage enforced)."""
    from parallel_heat_tpu.ops.tpu_params import params

    if limit_bytes is None:
        limit_bytes = params().vmem_limit_bytes
    if check_coverage is None:
        check_coverage = targets is None
    traced = _traced(targets)
    out = []
    seen = set()
    covered = set()

    for target, eqn in traced:
        name = _call_name(eqn)
        covered.add(name)
        label = f"{target.label}/{name}"

        def report(rule, message, _label=label, soundness=False):
            key = (rule, _label, message)
            if key not in seen:
                seen.add(key)
                out.append(Finding(rule, "error", _LOC, 0, _label,
                                   message, soundness=soundness))

        _audit_schedule(target, eqn, report)
        _audit_vmem(target, eqn, report, limit_bytes)
        _audit_grid_coverage(target, eqn, report)

    if check_coverage:
        source = _source_kernel_names()
        for name, lineno in sorted(source.items()):
            if name not in covered:
                out.append(Finding(
                    "HL401", "error", _LOC, lineno, name,
                    f"pallas_call site {name!r} is not covered by any "
                    f"kernel-audit target — every kernel site needs a "
                    f"representative geometry in "
                    f"analysis.kernels.default_kernel_targets so its "
                    f"DMA windows/VMEM budget stay proven",
                    soundness=True))
    return out


def _rule_runner(rule_id):
    def run():
        return run_kernels({rule_id})

    return run


KERNEL_RULES = {
    "HL401": ("error", "DMA window out of bounds or unprovable",
              _rule_runner("HL401")),
    "HL402": ("error", "kernel VMEM footprint exceeds the device limit",
              _rule_runner("HL402")),
    "HL403": ("error", "async-copy semaphore discipline violated",
              _rule_runner("HL403")),
    "HL404": ("error", "grid/BlockSpec tiling incomplete or ragged",
              _rule_runner("HL404")),
}


def run_kernels(rules=None) -> List[Finding]:
    """Run the kernel-safety audits against the installed package
    (one shared trace pass serves all four rules)."""
    wanted = set(KERNEL_RULES) if rules is None else set(rules)
    # Soundness sentinels survive any rule filter: they mean an audit
    # was silently skipped, so a --rules subset must not report clean.
    return [f for f in audit_kernels()
            if f.rule in wanted or f.soundness]
