"""Static contract verification for the solver's pinned invariants.

Five PRs accumulated load-bearing contracts that were only enforced
dynamically, test by test: the observation-only cache-key partition
(guard/diag/pipeline_depth stripped from ``_build_runner`` keys), the
donation-safety rule in the pipelined stream, Dirichlet
never-write-the-boundary semantics, f32chunk's once-per-chunk rounding,
the ``name="heat_*"`` annotation on every Pallas call site, and the
lock discipline around thread-shared observer state. Each contract is
exactly the kind of invariant that rots when the config/kernel surface
multiplies (ROADMAP items 2-3); this package makes them machine-checked
before a kernel ever runs.

Four layers (SEMANTICS.md "Statically verified contracts"):

- :mod:`contracts` — **trace-level** verifiers (rules ``HL1xx``): they
  trace solver programs to jaxprs (abstract evaluation — nothing
  executes) and audit the cache-key partition functionally against the
  real strip site.
- :mod:`astlint` — **AST-level** lint (rules ``HL2xx``) over the
  package source: blocking host syncs in dispatch regions, wall-clock/
  RNG in traced code, Pallas kernel names, lock discipline, import
  hygiene.
- :mod:`spmd` — **SPMD/collective** verifiers (rules ``HL3xx``): they
  trace the real sharded programs on a simulated multi-device mesh and
  prove the halo ``ppermute`` protocol (bijection + direction
  symmetry), collective-sequence convergence across branches/variants,
  and replication of every scalar that feeds host control flow.
- :mod:`kernels` — **Pallas kernel-safety** verifiers (rules
  ``HL4xx``): every kernel builder is traced at a representative
  geometry and its DMA windows, VMEM footprint, semaphore discipline
  and grid/BlockSpec tiling are checked per grid instance.

``tools/heatlint.py`` is the CLI; ``make lint`` gates CI on
``--fail-on error``. Intentionally-kept findings live in
``heatlint.baseline.json`` with a one-line justification each
(:mod:`findings`).
"""

from parallel_heat_tpu.analysis.findings import (  # noqa: F401
    Finding,
    apply_baseline,
    load_baseline,
    render_findings,
)
from parallel_heat_tpu.analysis.astlint import (  # noqa: F401
    AST_RULES,
    lint_paths,
)
from parallel_heat_tpu.analysis.contracts import (  # noqa: F401
    CONTRACT_RULES,
    run_contracts,
)
from parallel_heat_tpu.analysis.spmd import (  # noqa: F401
    SPMD_RULES,
    run_spmd,
)
from parallel_heat_tpu.analysis.kernels import (  # noqa: F401
    KERNEL_RULES,
    run_kernels,
)

ALL_RULES = {**CONTRACT_RULES, **AST_RULES, **SPMD_RULES,
             **KERNEL_RULES}

# Layer name -> (rule table, runner). The CLI's --layer flag and the
# per-layer timing summary both read this; a new analyzer layer lands
# by adding one row.
LAYERS = {
    "trace": (CONTRACT_RULES, lambda rules=None: run_contracts(rules)),
    "ast": (AST_RULES, lambda rules=None: lint_paths(None, rules=rules)),
    "spmd": (SPMD_RULES, lambda rules=None: run_spmd(rules)),
    "kernels": (KERNEL_RULES, lambda rules=None: run_kernels(rules)),
}


def layer_of(rule_id: str) -> str:
    """The layer name a rule id belongs to (``HL1xx`` -> trace, ...)."""
    for name, (table, _run) in LAYERS.items():
        if rule_id in table:
            return name
    return "?"


def run_all(paths=None, baseline=None):
    """Run every layer; returns ``(findings, stale_baseline_entries)``.

    ``paths`` scopes the AST layer (defaults inside
    :func:`astlint.lint_paths`); the other layers always audit the
    installed package. ``baseline`` (a parsed baseline, see
    :func:`findings.load_baseline`) suppresses matched findings.
    """
    out = list(run_contracts())
    out.extend(lint_paths(paths))
    out.extend(run_spmd())
    out.extend(run_kernels())
    return apply_baseline(out, baseline)
