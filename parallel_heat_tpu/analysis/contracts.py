"""Layer 1: trace-level contract verifiers (``HL1xx``).

These rules check the solver's pinned semantic contracts by tracing
programs to jaxprs (abstract evaluation — no simulation executes) and
by auditing the real strip/dispatch sites, not copies of them:

- **HL101 cache-key-partition** — every ``HeatConfig`` field is
  classified exactly once (``config.SEMANTIC_FIELDS`` vs
  ``config.OBSERVATION_ONLY_FIELDS``), and every observation-only
  field is *provably* stripped by ``solver._observer_free`` — the one
  function standing between user configs and the
  ``_build_runner``/executable cache keys. A new config field that is
  classified nowhere, an observation-only field the strip site leaves
  in place (which would silently fork compiled programs per observer
  setting), and a semantic field the strip site erases (which would
  silently alias *different* simulations to one executable) all fail.
  An AST pass additionally requires every direct ``_build_runner``
  caller to strip first.
- **HL102 donation-safety** — in the pipelined stream's dispatch path,
  a donated buffer is never read after the dispatch that donates it:
  (a) the argument of a donating call (a callable obtained from
  ``_compiled_for``) must not be read again until reassigned, and
  (b) inside a dispatch region (``# heatlint: dispatch-region``) a
  name bound to the raw dispatch output must not escape (``append``/
  ``yield``/``return``) unless one of its bindings is a
  ``jnp.copy(...)`` — the donation-protected copy of SEMANTICS.md
  "Pipelined stream".
- **HL103 dirichlet-write-set** — tracing representative solver
  programs (2D/3D, fixed/converge, storage/f32chunk; jnp backend),
  every in-place write into a grid-shaped buffer
  (``dynamic_update_slice``/``scatter``) provably excludes the
  Dirichlet boundary: literal start indices ≥ 1 on every axis and
  ``start + extent ≤ dim - 1``. Non-literal start indices on a
  grid-shaped write are reported as unprovable.
- **HL104 f32chunk-chain** — tracing the f32chunk accumulation chunk,
  no value is rounded to the sub-f32 storage dtype and then used in
  further arithmetic within the same chunk (a mid-chain downcast
  would move a rounding point — SEMANTICS.md "Sub-f32 rounding
  points"; the single per-chunk downcast feeding the chunk output /
  loop carry is the contract's one rounding event).

All audits accept injection points (config class, field partition,
target functions, file paths) so the test fixtures can seed violations
without patching the real solver.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import List, Optional

from parallel_heat_tpu.analysis.findings import Finding

# Scan scope of the HL101 AST pass (direct _build_runner callers).
_CALLER_SCAN = ("parallel_heat_tpu", "tools", "bench.py")


# ---------------------------------------------------------------------------
# HL101 cache-key partition
# ---------------------------------------------------------------------------

_SENTINELS = {int: 7919, float: 0.1239871, bool: True, str: "x-sentinel"}


def _sentinel_for(f: dataclasses.Field, default):
    """A value for field ``f`` that provably differs from its default."""
    for t, v in _SENTINELS.items():
        if isinstance(default, t) and not isinstance(default, bool):
            return v if v != default else v * 2
    if isinstance(default, bool):
        return not default
    # None / tuple / anything else: an int sentinel is fine — the strip
    # function only compares against the default, it never validates.
    return 7919 if default != 7919 else 7920


def audit_cache_keys(config_cls=None, semantic=None, observation=None,
                     strip=None, scan_paths=None) -> List[Finding]:
    """The cache-key partition audit (rule HL101). All parameters
    default to the real solver surface; tests inject doctored ones."""
    if config_cls is None:
        from parallel_heat_tpu.config import HeatConfig

        config_cls = HeatConfig
    if semantic is None or observation is None:
        from parallel_heat_tpu import config as _cfg

        semantic = _cfg.SEMANTIC_FIELDS if semantic is None else semantic
        observation = (_cfg.OBSERVATION_ONLY_FIELDS
                       if observation is None else observation)
    if strip is None:
        from parallel_heat_tpu.solver import _observer_free

        strip = _observer_free

    out = []
    loc = "parallel_heat_tpu/config.py"
    fields = {f.name: f for f in dataclasses.fields(config_cls)}
    sem, obs = set(semantic), set(observation)

    # 1. Partition: total and disjoint over the ACTUAL dataclass.
    for name in sorted(set(fields) - sem - obs):
        out.append(Finding(
            "HL101", "error", loc, 0, config_cls.__name__,
            f"config field {name!r} is classified neither SEMANTIC nor "
            f"OBSERVATION_ONLY — an unclassified field reaches "
            f"_build_runner cache keys unaudited and can silently fork "
            f"compiled programs; add it to exactly one of "
            f"config.SEMANTIC_FIELDS / config.OBSERVATION_ONLY_FIELDS"))
    for name in sorted((sem | obs) - set(fields)):
        out.append(Finding(
            "HL101", "error", loc, 0, config_cls.__name__,
            f"classified field {name!r} does not exist on "
            f"{config_cls.__name__} — stale partition entry"))
    for name in sorted(sem & obs):
        out.append(Finding(
            "HL101", "error", loc, 0, config_cls.__name__,
            f"config field {name!r} is classified BOTH semantic and "
            f"observation-only; the partition must be disjoint"))

    # 2. Functional strip proof against the real strip site.
    try:
        default_cfg = config_cls()
    except TypeError as e:
        out.append(Finding(
            "HL101", "error", loc, 0, config_cls.__name__,
            f"cannot construct a default {config_cls.__name__} "
            f"({e}) — every field needs a default for the strip "
            f"audit"))
        return out
    stripped_default = strip(default_cfg)
    if stripped_default != default_cfg:
        out.append(Finding(
            "HL101", "error", loc, 0, config_cls.__name__,
            "stripping the default config changed it — the strip "
            "site must be the identity on observer-free configs"))
    for name in sorted(obs & set(fields)):
        f = fields[name]
        if f.default is dataclasses.MISSING and \
                f.default_factory is dataclasses.MISSING:
            out.append(Finding(
                "HL101", "error", loc, 0, config_cls.__name__,
                f"observation-only field {name!r} has no default — "
                f"stripping must be able to reset it"))
            continue
        default = (f.default if f.default is not dataclasses.MISSING
                   else f.default_factory())
        cfg = dataclasses.replace(default_cfg,
                                  **{name: _sentinel_for(f, default)})
        if strip(cfg) != stripped_default:
            out.append(Finding(
                "HL101", "error", loc, 0, config_cls.__name__,
                f"observation-only field {name!r} is NOT stripped from "
                f"_build_runner cache keys: two configs differing only "
                f"in {name!r} would compile (and cache) separate "
                f"programs, breaking the observation-only contract "
                f"(SEMANTICS.md) — strip it in solver._observer_free "
                f"or reclassify it as semantic"))
    for name in sorted(sem & set(fields)):
        f = fields[name]
        if f.default is dataclasses.MISSING and \
                f.default_factory is dataclasses.MISSING:
            continue
        default = (f.default if f.default is not dataclasses.MISSING
                   else f.default_factory())
        cfg = dataclasses.replace(default_cfg,
                                  **{name: _sentinel_for(f, default)})
        if strip(cfg) == stripped_default:
            out.append(Finding(
                "HL101", "error", loc, 0, config_cls.__name__,
                f"semantic field {name!r} is erased by the strip site: "
                f"two DIFFERENT simulations would alias one compiled "
                f"program — remove it from the strip set"))

    # 3. AST pass: direct _build_runner callers must strip first.
    out.extend(_audit_runner_callers(scan_paths))
    return out


def audit_cache_keys_all() -> List[Finding]:
    """Rule HL101, both partitions: the ``HeatConfig`` semantic /
    observation-only split against ``solver._observer_free`` (plus the
    ``_build_runner`` caller scan), and the ``EnsembleConfig``
    semantic / orchestration split against
    ``EnsembleConfig.orchestration_free`` — the ensemble engine's
    runner caches key on the orchestration-free config, so an
    unstripped orchestration field would fork batched programs per
    compaction/window setting exactly like an unstripped observer
    field forks solo programs."""
    out = list(audit_cache_keys())
    from parallel_heat_tpu.config import (
        ENSEMBLE_ORCHESTRATION_FIELDS,
        ENSEMBLE_SEMANTIC_FIELDS,
        EnsembleConfig,
    )

    out.extend(audit_cache_keys(
        config_cls=EnsembleConfig,
        semantic=ENSEMBLE_SEMANTIC_FIELDS,
        observation=ENSEMBLE_ORCHESTRATION_FIELDS,
        strip=lambda c: c.orchestration_free(),
        scan_paths=[]))  # the caller scan already ran above
    return out


def _audit_runner_callers(scan_paths=None) -> List[Finding]:
    from parallel_heat_tpu.analysis.astlint import (REPO_ROOT,
                                                    _iter_py_files)

    if scan_paths is None:
        scan_paths = [p for p in
                      (os.path.join(REPO_ROOT, x) for x in _CALLER_SCAN)
                      if os.path.exists(p)]
    out = []
    for path in _iter_py_files(scan_paths):
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            continue  # astlint reports HL200 for this
        # Every call site counts — nested defs, class methods, and
        # module-level script lines, not just top-level functions.
        funcs = [n for n in ast.walk(tree)
                 if isinstance(n, (ast.FunctionDef,
                                   ast.AsyncFunctionDef))]
        build_calls = []
        strip_linenos = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                name = node.func.attr if isinstance(
                    node.func, ast.Attribute) else getattr(
                    node.func, "id", None)
                if name == "_build_runner":
                    build_calls.append(node)
                elif name == "_observer_free":
                    strip_linenos.append(node.lineno)

        def enclosing(lineno):
            """(innermost function name for reporting, outermost
            enclosing function's start line for strip scoping) — a
            strip in the outer function covers its nested dispatch
            closures. Module scope: ``("<module>", 1)``."""
            inner = outer = None
            for fn in funcs:
                if fn.lineno <= lineno <= fn.end_lineno:
                    if inner is None or fn.lineno > inner.lineno:
                        inner = fn
                    if outer is None or fn.lineno < outer.lineno:
                        outer = fn
            if inner is not None:
                return inner.name, outer.lineno
            return "<module>", 1

        for call in build_calls:
            arg = call.args[0] if call.args else None
            inline = (isinstance(arg, ast.Call) and (
                getattr(arg.func, "id", None) == "_observer_free"
                or getattr(arg.func, "attr", None)
                == "_observer_free"))
            symbol, scope_start = enclosing(call.lineno)
            # OK when the arg is an inline strip, or a strip ran
            # lexically earlier within the same enclosing scope.
            if inline or any(scope_start <= ln <= call.lineno
                             for ln in strip_linenos):
                continue
            out.append(Finding(
                "HL101", "error", path, call.lineno, symbol,
                "_build_runner called on a config that was not "
                "passed through solver._observer_free — an "
                "observation field left in the key forks the "
                "compiled-program cache; call "
                "_observer_free(config) first (it is the identity "
                "on observer-free configs)"))
    return out


# ---------------------------------------------------------------------------
# HL102 donation safety
# ---------------------------------------------------------------------------

def audit_donation(path: Optional[str] = None) -> List[Finding]:
    """Donation-aliasing safety over one source file (default:
    the installed ``solver.py``)."""
    if path is None:
        import parallel_heat_tpu.solver as _solver

        path = _solver.__file__
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError:
        return []
    src_lines = src.splitlines() or [""]
    out = []
    # Nested defs are analyzed both standalone and as part of their
    # enclosing function (the donated names cross scopes via nonlocal);
    # dedup identical findings by location+message.
    seen = set()
    for fn in ast.walk(tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for f in _donation_in_function(fn, src_lines, path):
                k = (f.rule, f.file, f.line, f.message)
                if k not in seen:
                    seen.add(k)
                    out.append(f)
    return out


def _assigned_names(target):
    if isinstance(target, ast.Name):
        yield target.id
    elif isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _assigned_names(elt)


def _is_copy_call(node) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "copy")


def _donation_in_function(fn, src_lines, path) -> List[Finding]:
    out = []
    # Donating callables: names assigned from _compiled_for(...).
    donating = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            callee = node.value.func
            name = (callee.attr if isinstance(callee, ast.Attribute)
                    else getattr(callee, "id", None))
            if name == "_compiled_for":
                for t in node.targets:
                    donating.update(_assigned_names(t))
    if not donating:
        return out

    # (a) read-after-donate: the donated argument name must not be
    # loaded after the donating call until reassigned (linear
    # source-order approximation — adequate for the straight-line
    # dispatch paths this contract governs).
    events = []  # (lineno, kind, name)  kind: donate | load | store
    donate_outputs = set()  # names bound to raw dispatch results
    copy_bound = set()      # names with at least one jnp.copy binding
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            cal = node.func
            cname = (cal.attr if isinstance(cal, ast.Attribute)
                     else getattr(cal, "id", None))
            if cname in donating and node.args and isinstance(
                    node.args[0], ast.Name):
                # A donate spans the whole (possibly wrapped) call:
                # the argument's own continuation line is part of the
                # dispatch, not a read-after-donate.
                events.append(((node.lineno, node.end_lineno),
                               "donate", node.args[0].id))
        elif isinstance(node, ast.Name):
            kind = ("load" if isinstance(node.ctx, ast.Load)
                    else "store")
            events.append((node.lineno, kind, node.id))
        if isinstance(node, ast.Assign):
            val = node.value
            if isinstance(val, ast.Call):
                cal = val.func
                cname = (cal.attr if isinstance(cal, ast.Attribute)
                         else getattr(cal, "id", None))
                if cname in donating:
                    for t in node.targets:
                        names = list(_assigned_names(t))
                        if names:
                            donate_outputs.add(names[0])  # the grid
            if _is_copy_call(val):
                for t in node.targets:
                    copy_bound.update(_assigned_names(t))
            elif isinstance(val, ast.Name):
                # alias propagation: B = A where A is a raw output
                if val.id in donate_outputs:
                    for t in node.targets:
                        donate_outputs.update(_assigned_names(t))
    for where, kind, name in events:
        if kind != "donate":
            continue
        start, end = where
        # First load strictly after the donating call's last line, vs
        # first store at/after its first line (a store ON the donating
        # statement is the common `u = step(u)` rebind idiom).
        loads = [ln for ln, k, n in events
                 if n == name and k == "load" and ln > end]
        stores = [ln for ln, k, n in events
                  if n == name and k == "store" and ln >= start]
        if loads and (not stores or min(stores) > min(loads)):
            out.append(Finding(
                "HL102", "error", path, min(loads), fn.name,
                f"{name!r} is read after the dispatch at line "
                f"{start} donated its buffer — the read observes "
                f"freed/garbage memory; rebind the name from the "
                f"dispatch result before any further use"))

    # (b) raw-output escape from dispatch regions: fn itself or any
    # nested def carrying the pragma (the pipelined stream's _dispatch
    # closure is nested in solve_stream, which binds `step`).
    from parallel_heat_tpu.analysis.astlint import _PRAGMA_FUNC

    marked = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cand = [src_lines[node.lineno - 1]]
        if node.lineno >= 2:
            cand.append(src_lines[node.lineno - 2])
        if any(_PRAGMA_FUNC in c for c in cand):
            marked.append((node.lineno, node.end_lineno))
    if not marked:
        return out
    raw = donate_outputs - copy_bound
    if not raw:
        return out

    def in_marked(lineno):
        return any(lo <= lineno <= hi for lo, hi in marked)

    def names_in(node):
        return {n.id for n in ast.walk(node)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)}

    for node in ast.walk(fn):
        expr = None
        what = None
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr in ("append", "appendleft", "put",
                                       "put_nowait"):
            expr, what = node, f"{node.func.attr}()"
        elif isinstance(node, (ast.Yield, ast.YieldFrom)) \
                and node.value is not None:
            expr, what = node.value, "yield"
        elif isinstance(node, ast.Return) and node.value is not None:
            expr, what = node.value, "return"
        if expr is None or not in_marked(node.lineno):
            continue
        escaped = sorted(raw & names_in(expr))
        if escaped:
            out.append(Finding(
                "HL102", "error", path, node.lineno, fn.name,
                f"raw dispatch output {escaped} escapes this dispatch "
                f"region via {what} without a donation-protected "
                f"jnp.copy binding — the next dispatch donates that "
                f"buffer, so any later consumer reads freed memory "
                f"(SEMANTICS.md 'Pipelined stream')"))
    return out


# ---------------------------------------------------------------------------
# jaxpr plumbing (shared by HL103 / HL104)
# ---------------------------------------------------------------------------

def _sub_jaxprs(params):
    import jax.core as jcore

    ClosedJaxpr = getattr(jcore, "ClosedJaxpr", None)
    Jaxpr = getattr(jcore, "Jaxpr", None)

    def is_jaxpr(v):
        return (ClosedJaxpr is not None and isinstance(v, ClosedJaxpr)) \
            or (Jaxpr is not None and isinstance(v, Jaxpr))

    for v in params.values():
        if is_jaxpr(v):
            yield v
        elif isinstance(v, (list, tuple)):
            for item in v:
                if is_jaxpr(item):
                    yield item


def _walk_jaxprs(closed):
    """Yield every (sub-)jaxpr reachable from ``closed``, outermost
    first."""
    seen = set()
    stack = [closed]
    while stack:
        j = stack.pop()
        jaxpr = getattr(j, "jaxpr", j)
        if id(jaxpr) in seen:
            continue
        seen.add(id(jaxpr))
        yield jaxpr
        for eqn in jaxpr.eqns:
            stack.extend(_sub_jaxprs(eqn.params))


def _literal_val(invar):
    import jax.core as jcore

    if isinstance(invar, jcore.Literal):
        return invar.val
    return None


def _fold_constants(jaxpr):
    """Forward constant-folding over one jaxpr: var id -> concrete
    numpy value for every value derivable from literals alone (index
    vectors like ``concatenate(broadcast(1), broadcast(1))`` — the
    lowering of ``u.at[1:-1, 1:-1].set``). Jaxpr invars and constvars
    stay unknown: anything data-dependent must remain unprovable."""
    import numpy as np

    env = {}

    def val_of(v):
        lit = _literal_val(v)
        return lit if lit is not None else env.get(id(v))

    for eqn in jaxpr.eqns:
        vals = [val_of(v) for v in eqn.invars]
        if any(v is None for v in vals):
            continue
        prim, p = eqn.primitive.name, eqn.params
        try:
            if prim == "broadcast_in_dim":
                op = np.asarray(vals[0])
                shape = tuple(p["shape"])
                newshape = [1] * len(shape)
                for i, d in enumerate(p["broadcast_dimensions"]):
                    newshape[d] = op.shape[i]
                res = np.broadcast_to(op.reshape(newshape), shape)
            elif prim == "concatenate":
                res = np.concatenate([np.asarray(v) for v in vals],
                                     axis=p["dimension"])
            elif prim == "convert_element_type":
                res = np.asarray(vals[0], dtype=p["new_dtype"])
            elif prim == "reshape":
                res = np.reshape(np.asarray(vals[0]), p["new_sizes"])
            elif prim == "squeeze":
                res = np.squeeze(np.asarray(vals[0]),
                                 axis=tuple(p["dimensions"]))
            elif prim == "add":
                res = np.asarray(vals[0]) + np.asarray(vals[1])
            elif prim == "sub":
                res = np.asarray(vals[0]) - np.asarray(vals[1])
            elif prim == "mul":
                res = np.asarray(vals[0]) * np.asarray(vals[1])
            elif prim == "max":
                res = np.maximum(vals[0], vals[1])
            elif prim == "min":
                res = np.minimum(vals[0], vals[1])
            else:
                continue
        except Exception:  # noqa: BLE001 — fold failure = stay unknown
            continue
        if len(eqn.outvars) == 1:
            env[id(eqn.outvars[0])] = res
    return env


def _scatter_window(eqn, env):
    """``[(start, extent), ...]`` per operand dim for a single-window
    scatter with a constant index vector, or None when the write set is
    not statically derivable (dynamic indices, multi-window scatter,
    batched dims)."""
    operand, indices, update = eqn.invars[:3]
    lit = _literal_val(indices)
    idx = lit if lit is not None else env.get(id(indices))
    if idx is None:
        return None
    import numpy as np

    idx = np.asarray(idx)
    if idx.ndim != 1:  # one index vector = one window write
        return None
    d = eqn.params["dimension_numbers"]
    if getattr(d, "operand_batching_dims", ()) or \
            getattr(d, "scatter_indices_batching_dims", ()):
        return None
    rank = len(operand.aval.shape)
    upd_shape = tuple(update.aval.shape)
    window_ops = [i for i in range(rank)
                  if i not in d.inserted_window_dims]
    if len(d.update_window_dims) != len(window_ops):
        return None
    extent = {od: upd_shape[ud]
              for od, ud in zip(window_ops, d.update_window_dims)}
    for od in d.inserted_window_dims:
        extent[od] = 1
    start = {od: int(idx[k])
             for k, od in enumerate(d.scatter_dims_to_operand_dims)}
    return [(start.get(i, 0), extent[i]) for i in range(rank)]


# ---------------------------------------------------------------------------
# HL103 Dirichlet write-set
# ---------------------------------------------------------------------------

def _default_dirichlet_targets():
    """(label, fn, example-input ShapeDtypeStruct) triples covering the
    CPU-traceable solver programs: the jnp 2D/3D fixed and converge
    loops and the f32chunk chunk chain."""
    import jax

    from parallel_heat_tpu.config import HeatConfig
    from parallel_heat_tpu.solver import (_make_loop, _single_multistep)

    targets = []
    matrix = [
        ("jnp-2d-fixed", HeatConfig(nx=16, ny=16, steps=4,
                                    backend="jnp")),
        ("jnp-2d-converge", HeatConfig(nx=16, ny=16, steps=40,
                                       converge=True, check_interval=20,
                                       backend="jnp")),
        ("jnp-3d-fixed", HeatConfig(nx=8, ny=8, nz=8, steps=4,
                                    backend="jnp")),
        ("jnp-2d-f32chunk", HeatConfig(nx=16, ny=16, steps=32,
                                       dtype="bfloat16",
                                       accumulate="f32chunk",
                                       backend="jnp")),
        # The implicit update program (SEMANTICS.md "Implicit
        # stepping"): the whole V-cycle — smoothing sweeps at every
        # level, the per-step while_loop, the storage round-off — must
        # prove its grid-shaped writes interior-only exactly like the
        # explicit loops (coarse-level arrays are differently shaped
        # and out of scope by construction).
        ("jnp-2d-implicit-be", HeatConfig(nx=16, ny=16, steps=4,
                                          cx=5.0, cy=5.0,
                                          scheme="backward_euler",
                                          backend="jnp")),
        ("jnp-2d-implicit-cn", HeatConfig(nx=16, ny=16, steps=40,
                                          cx=5.0, cy=5.0,
                                          scheme="crank_nicolson",
                                          converge=True,
                                          check_interval=20,
                                          backend="jnp")),
    ]
    for label, cfg in matrix:
        ms, msr = _single_multistep(cfg, "jnp")
        run = _make_loop(ms, msr, cfg)
        sds = jax.ShapeDtypeStruct(cfg.shape, cfg.dtype)
        targets.append((label, run, sds, cfg.shape))
    return targets


def audit_dirichlet(targets=None) -> List[Finding]:
    """Write-set analysis (rule HL103): trace each target and verify
    every grid-shaped in-place write excludes the boundary. ``targets``
    is an iterable of ``(label, fn, example_sds, grid_shape)``."""
    import jax

    if targets is None:
        targets = _default_dirichlet_targets()
    out = []
    seen = set()
    loc = "parallel_heat_tpu/ops/stencil.py"

    def report(label, message):
        # One finding per distinct (target, message): the same write
        # site appears once per loop iteration/sub-jaxpr otherwise.
        if (label, message) not in seen:
            seen.add((label, message))
            out.append(Finding("HL103", "error", loc, 0, label, message))

    def check_window(label, window, grid_shape, what):
        for d, ((start, ext), dim) in enumerate(zip(window, grid_shape)):
            if start < 1 or start + ext > dim - 1:
                report(label,
                       f"write-set touches the Dirichlet boundary: "
                       f"{what} axis {d} writes [{start}, "
                       f"{start + ext}) of a {dim}-cell axis — "
                       f"interior writes must stay within [1, "
                       f"{dim - 1}) (SEMANTICS.md 'Boundary "
                       f"exactness': boundary cells are never "
                       f"written)")
                return

    for label, fn, sds, grid_shape in targets:
        try:
            closed = jax.make_jaxpr(fn)(sds)
        except Exception as e:  # noqa: BLE001 — an untraceable target
            report(label, f"could not trace target for write-set "
                          f"analysis: {type(e).__name__}: {e}")
            continue
        grid_shape = tuple(grid_shape)
        for jaxpr in _walk_jaxprs(closed):
            env = None  # fold lazily, once per jaxpr that needs it
            for eqn in jaxpr.eqns:
                prim = eqn.primitive.name
                if prim == "dynamic_update_slice":
                    operand, update, *starts = eqn.invars
                    if tuple(operand.aval.shape) != grid_shape:
                        continue
                    upd_shape = tuple(update.aval.shape)
                    vals = [_literal_val(s) for s in starts]
                    if any(v is None for v in vals):
                        report(label,
                               f"grid-shaped write with non-literal "
                               f"start indices — the Dirichlet "
                               f"write-set cannot be proven "
                               f"boundary-free statically (update "
                               f"shape {upd_shape})")
                        continue
                    window = [(int(s), e)
                              for s, e in zip(vals, upd_shape)]
                    check_window(label, window, grid_shape,
                                 "dynamic_update_slice")
                elif prim.startswith("scatter"):
                    operand = eqn.invars[0]
                    if tuple(operand.aval.shape) != grid_shape:
                        continue
                    if env is None:
                        env = _fold_constants(jaxpr)
                    window = _scatter_window(eqn, env)
                    if window is None:
                        report(label,
                               "grid-shaped scatter write whose index "
                               "set is not a trace-time constant — "
                               "the Dirichlet write-set cannot be "
                               "proven boundary-free statically; use "
                               "a static interior slice-assign "
                               "(u.at[1:-1, ...].set) instead")
                        continue
                    check_window(label, window, grid_shape, prim)
    return out


# ---------------------------------------------------------------------------
# HL104 f32chunk accumulation chain
# ---------------------------------------------------------------------------

# Primitives whose output propagates the (possibly rounded) VALUE
# unchanged — traversal continues through them.
_PASS_THROUGH = {
    "convert_element_type", "dynamic_update_slice", "dynamic_slice",
    "slice", "reshape", "broadcast_in_dim", "transpose", "squeeze",
    "concatenate", "copy", "pad", "rev",
}
# Arithmetic: a rounded value feeding one of these means the chain
# continued past a rounding point.
_ARITHMETIC = {
    "add", "sub", "mul", "div", "max", "min", "integer_pow", "pow",
    "dot_general", "exp", "log", "sqrt", "rsqrt", "abs", "neg",
    "tanh", "logistic", "atan2", "rem", "nextafter", "fma",
}


def _default_f32chunk_targets():
    import jax
    import jax.numpy as jnp

    from parallel_heat_tpu.ops.pallas_stencil import (
        _sub_rows, f32chunk_jnp_multistep)

    shape, dtype = (16, 16), "bfloat16"
    sub = _sub_rows(dtype)
    ms, msr = f32chunk_jnp_multistep(shape, dtype, 0.1, 0.1)
    sds = jax.ShapeDtypeStruct(shape, jnp.bfloat16)
    return [
        ("f32chunk-multistep", lambda u: ms(u, sub), sds),
        ("f32chunk-residual", lambda u: msr(u, sub), sds),
        ("f32chunk-two-chunks", lambda u: ms(u, 2 * sub), sds),
    ]


def audit_f32chunk(targets=None) -> List[Finding]:
    """Mid-chain downcast analysis (rule HL104). ``targets`` is an
    iterable of ``(label, fn, example_sds)`` where each ``fn`` is one
    f32chunk accumulation chunk (chunk boundaries — loop carries —
    are the contract's legitimate rounding points and naturally scope
    the per-jaxpr analysis)."""
    import jax
    import numpy as np

    if targets is None:
        targets = _default_f32chunk_targets()
    out = []
    loc = "parallel_heat_tpu/ops/pallas_stencil.py"
    for label, fn, sds in targets:
        try:
            closed = jax.make_jaxpr(fn)(sds)
        except Exception as e:  # noqa: BLE001
            out.append(Finding(
                "HL104", "error", loc, 0, label,
                f"could not trace f32chunk chain: "
                f"{type(e).__name__}: {e}"))
            continue
        for jaxpr in _walk_jaxprs(closed):
            consumers = {}
            for eqn in jaxpr.eqns:
                for v in eqn.invars:
                    if _literal_val(v) is None:
                        consumers.setdefault(id(v), []).append(eqn)
            for eqn in jaxpr.eqns:
                if eqn.primitive.name != "convert_element_type":
                    continue
                src_dt = np.dtype(eqn.invars[0].aval.dtype)
                dst_dt = np.dtype(eqn.outvars[0].aval.dtype)
                if not (src_dt.itemsize >= 4 and dst_dt.itemsize < 4):
                    continue  # not a downcast to sub-f32 storage
                # BFS from the rounded value through value-preserving
                # primitives; arithmetic consumption = mid-chain round.
                frontier = [eqn.outvars[0]]
                seen = set()
                hit = None
                while frontier and hit is None:
                    var = frontier.pop()
                    if id(var) in seen:
                        continue
                    seen.add(id(var))
                    for c in consumers.get(id(var), ()):
                        prim = c.primitive.name
                        if prim in _ARITHMETIC:
                            hit = prim
                            break
                        if prim in _PASS_THROUGH:
                            frontier.extend(c.outvars)
                if hit is not None:
                    out.append(Finding(
                        "HL104", "error", loc, 0, label,
                        f"mid-chain downcast: a value rounded to "
                        f"{dst_dt.name} is consumed by arithmetic "
                        f"({hit}) within the same f32chunk chunk — "
                        f"the chain must carry float32 and round to "
                        f"storage exactly once, at the chunk boundary "
                        f"(SEMANTICS.md 'Sub-f32 rounding points')"))
    return out


# ---------------------------------------------------------------------------
# registry / driver
# ---------------------------------------------------------------------------

CONTRACT_RULES = {
    "HL101": ("error", "cache-key partition violated or unproven",
              audit_cache_keys_all),
    "HL102": ("error", "donated buffer read/escaped after dispatch",
              audit_donation),
    "HL103": ("error", "kernel write-set touches the Dirichlet boundary",
              audit_dirichlet),
    "HL104": ("error", "f32chunk chain downcasts mid-chain",
              audit_f32chunk),
}


def run_contracts(rules=None) -> List[Finding]:
    """Run the trace-level audits against the installed package."""
    out = []
    for rule_id, (_sev, _summary, fn) in CONTRACT_RULES.items():
        if rules is not None and rule_id not in rules:
            continue
        out.extend(fn())
    return out
