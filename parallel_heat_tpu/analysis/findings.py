"""Finding/severity/baseline plumbing shared by both analyzer layers.

A finding is one rule violation at one source location. The baseline
file (``heatlint.baseline.json`` at the repo root by default) is the
justified-keeps ledger: findings the team has inspected and decided to
keep, each with a one-line justification. Baseline entries match on
``(rule, file, symbol)`` — the enclosing function/class, not the line
number, so unrelated edits above a kept finding don't invalidate the
entry — and every entry must carry a non-empty justification; entries
that no longer match anything are reported as stale so the ledger can
never silently outlive the code it excuses.

Format::

    {
      "version": 1,
      "entries": [
        {"rule": "HL205", "file": "parallel_heat_tpu/utils/compat.py",
         "symbol": "<module>", "justification": "re-export shim"}
      ]
    }
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Optional

BASELINE_VERSION = 1
BASELINE_DEFAULT = "heatlint.baseline.json"

# Severity order for --fail-on thresholds.
SEVERITIES = ("info", "warning", "error")


@dataclass
class Finding:
    """One rule violation: ``rule`` id (``HLxxx``), ``severity``
    (``error``/``warning``/``info``), ``file`` (repo-relative when
    possible), 1-based ``line`` (0 = whole-file/whole-audit),
    ``symbol`` (enclosing function/class, ``<module>`` at top level —
    the baseline match key), human ``message``."""

    rule: str
    severity: str
    file: str
    line: int
    symbol: str
    message: str
    # Set when a baseline entry suppressed this finding (carried in
    # to_dict() output; suppressed findings never gate).
    justification: Optional[str] = None
    # True for audit-soundness sentinels (exhaustion bounds, unprovable
    # schedules, vacuous target matrices): they report that an audit
    # could not run to completion, so a rule-subset run must surface
    # them even when their nominal rule id was filtered out — otherwise
    # "clean" can mean "silently skipped".
    soundness: bool = False

    def key(self):
        return (self.rule, _norm(self.file), self.symbol)

    def to_dict(self) -> dict:
        d = {"rule": self.rule, "severity": self.severity,
             "file": _norm(self.file), "line": self.line,
             "symbol": self.symbol, "message": self.message}
        if self.justification is not None:
            d["justification"] = self.justification
        if self.soundness:
            # Machine consumers must be able to tell "the audit could
            # not run" from an ordinary violation of the same rule id.
            d["soundness"] = True
        return d


@dataclass
class Baseline:
    """Parsed baseline file: entry key -> justification."""

    entries: dict = field(default_factory=dict)
    path: Optional[str] = None


_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _norm(path: str) -> str:
    """Repo-relative forward-slash path (baseline keys must not depend
    on the machine — or the cwd — the analyzer ran from)."""
    p = os.path.normpath(str(path)).replace(os.sep, "/")
    for root in (_REPO_ROOT.replace(os.sep, "/") + "/",
                 os.getcwd().replace(os.sep, "/") + "/"):
        if p.startswith(root):
            return p[len(root):]
    return p


def load_baseline(path: Optional[str] = None) -> Baseline:
    """Load and validate a baseline file; a missing default file is an
    empty baseline, a malformed file or an entry without a justification
    raises (a silent bad ledger would un-gate CI)."""
    explicit = path is not None
    # The default ledger is the repo's, wherever the analyzer runs from.
    path = path or os.path.join(_REPO_ROOT, BASELINE_DEFAULT)
    if not os.path.exists(path):
        if explicit:
            raise FileNotFoundError(f"baseline file {path!r} not found")
        return Baseline()
    with open(path) as f:
        doc = json.load(f)
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path!r}: unsupported version {doc.get('version')!r}"
            f" (expected {BASELINE_VERSION})")
    out = {}
    for i, e in enumerate(doc.get("entries", [])):
        missing = [k for k in ("rule", "file", "symbol", "justification")
                   if not isinstance(e.get(k), str)]
        if missing:
            raise ValueError(
                f"baseline {path!r} entry {i}: missing/non-string "
                f"field(s) {missing}")
        if not e["justification"].strip():
            raise ValueError(
                f"baseline {path!r} entry {i} ({e['rule']} {e['file']} "
                f"{e['symbol']}): empty justification — every kept "
                f"finding must say why")
        out[(e["rule"], _norm(e["file"]), e["symbol"])] = e["justification"]
    return Baseline(entries=out, path=path)


def apply_baseline(findings, baseline: Optional[Baseline],
                   assessed_rules=None, assessed_paths=None,
                   path_rules=()):
    """Split findings into (active, suppressed-but-annotated) and
    report stale entries. Returns ``(active, stale)`` where ``active``
    excludes suppressed findings and ``stale`` is a list of baseline
    keys that matched nothing (each rendered as an ``HL000`` warning by
    the CLI so the ledger shrinks when code improves).

    ``assessed_rules`` (a set of rule ids, default: all) scopes
    stale-ness: an entry whose rule was NOT assessed this run — its
    layer skipped via ``--layer``/``--rules`` — is neither matched nor
    stale, just unassessed. Without this, any partial run
    (``make lint-fast``) would flag every entry of the layers it
    skipped, and ``--strict-baseline`` would turn that into a spurious
    gate.

    ``assessed_paths`` (normalized path roots, default: everything)
    scopes stale-ness for the rules in ``path_rules`` (the AST layer):
    an entry whose file lies outside every scanned root was never given
    a chance to match — its violation may still be alive in the
    unscanned file — so it is unassessed, not stale. Entries whose
    files WERE scanned still go stale normally."""
    if baseline is None:
        baseline = Baseline()
    matched = set()
    active = []
    for f in findings:
        just = baseline.entries.get(f.key())
        if just is not None:
            matched.add(f.key())
            f.justification = just
            continue
        active.append(f)

    def _path_assessed(rule, fpath):
        if assessed_paths is None or rule not in path_rules:
            return True
        return any(fpath == root or fpath.startswith(root + "/")
                   for root in assessed_paths)

    stale = [k for k in baseline.entries
             if k not in matched
             and (assessed_rules is None or k[0] in assessed_rules)
             and _path_assessed(k[0], k[1])]
    return active, stale


def gates(findings, fail_on: str) -> bool:
    """True when any finding is at/above the ``fail_on`` severity."""
    threshold = SEVERITIES.index(fail_on)
    return any(SEVERITIES.index(f.severity) >= threshold
               for f in findings)


def render_findings(findings, stale=()) -> str:
    """Human rendering, one line per finding: file:line: [RULE/sev]
    symbol: message."""
    lines = []
    order = {s: i for i, s in enumerate(SEVERITIES)}
    for f in sorted(findings,
                    key=lambda f: (-order[f.severity], _norm(f.file),
                                   f.line, f.rule)):
        lines.append(f"{_norm(f.file)}:{f.line}: [{f.rule}/{f.severity}]"
                     f" {f.symbol}: {f.message}")
    for rule, fpath, symbol in stale:
        lines.append(f"{fpath}:0: [HL000/warning] {symbol}: stale "
                     f"baseline entry for {rule} — the finding it kept "
                     f"no longer exists; delete it")
    return "\n".join(lines)
